"""Durable server control-plane snapshots + the round/cohort ledger.

The PR-5 fault-tolerance layer made the *silos* survivable; the server
was still a single point of failure: kill it mid-schedule and the whole
federation dies, because the round-schedule state (round index, live
set, compression mirror, pending replies, aggregation partials) lived
only in process memory. This module is the durable half of the elastic
control plane: :class:`ServerControlCheckpointer` snapshots the FULL
control state dict the server captures (``_capture_control_state`` in
``algorithms/fedavg_cross_silo.py`` — field manifest in
``control/manifest.py``, enforced by lint rule FT009) and restores it
on restart, so a killed-and-restarted server resumes mid-schedule and
the existing silo-side rejoin protocol reconnects the fleet.

Format: one ``state_<seq>.msgpack`` blob per snapshot
(``flax.serialization.msgpack_serialize`` — template-free restore, so
variable-structure state like the pending-reply dict round-trips) plus a
``state_<seq>.json`` sidecar with the round index. Writes follow the
repo's atomic idiom (tmp + ``os.replace``, blob first, sidecar last):
a crash at ANY point leaves either a complete older snapshot or a
complete newer one — a snapshot without its sidecar is invisible to
``load_latest`` and swept by GC (crash-consistency tested, mirroring
``test_state_store.py``).

The **ledger** (``ledger.jsonl``) is the schedule's durable trace: one
JSON line per closed round with the round index, the broadcast cohort,
the reporting silos, and whether the close was partial. It is the
acceptance oracle for failover — a resumed run's ledger must match the
unkilled reference's — and the progress feed the failover harness polls.
Lines are appended *before* the snapshot, so a crash between the two
re-closes the round after restore and re-appends it: readers dedup by
round keeping the LAST occurrence.
"""

from __future__ import annotations

import json
import logging
import os
import re
from typing import Any, Dict, List, Optional

_STATE_RE = re.compile(r"state_(\d+)\.msgpack$")

#: bumped when the snapshot layout changes incompatibly
STATE_FORMAT = 1


class ServerControlCheckpointer:
    def __init__(self, directory: str, keep_last_n: int = 3):
        self.directory = directory
        self.keep_last_n = max(1, int(keep_last_n))
        os.makedirs(directory, exist_ok=True)

    # -- snapshot naming ----------------------------------------------------
    def _path(self, seq: int) -> str:
        return os.path.join(self.directory, f"state_{seq:012d}.msgpack")

    def _seqs(self) -> List[int]:
        """Snapshot sequence numbers with BOTH files present (a blob
        whose sidecar never landed is a torn write — invisible)."""
        names = set(os.listdir(self.directory))
        out = []
        for fn in sorted(names):
            m = _STATE_RE.fullmatch(fn)
            if m and fn[:-len(".msgpack")] + ".json" in names:
                out.append(int(m.group(1)))
        return sorted(out)

    # -- save / load --------------------------------------------------------
    def save(self, state: Dict[str, Any]) -> str:
        """Atomically persist one control-state snapshot; returns its
        path. ``state`` must be msgpack-serializable (numpy arrays,
        dicts with str keys, lists, scalars, None) — the server's
        capture method guarantees that shape."""
        from flax import serialization as fser
        seqs = self._seqs()
        seq = (seqs[-1] + 1) if seqs else 0
        path = self._path(seq)
        blob = fser.msgpack_serialize(dict(state, format=STATE_FORMAT))
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        # sidecar LAST: _seqs() requires both files, so a crash anywhere
        # in this method leaves the previous snapshot authoritative
        side = path[:-len(".msgpack")] + ".json"
        stmp = f"{side}.{os.getpid()}.tmp"
        with open(stmp, "w") as f:
            json.dump({"seq": seq, "round_idx": int(state["round_idx"]),
                       "format": STATE_FORMAT}, f)
        os.replace(stmp, side)
        self._gc()
        return path

    def load_latest(self) -> Optional[Dict[str, Any]]:
        """The newest complete snapshot as a plain dict (numpy leaves),
        or None when the directory holds none."""
        from flax import serialization as fser
        seqs = self._seqs()
        if not seqs:
            return None
        with open(self._path(seqs[-1]), "rb") as f:
            state = fser.msgpack_restore(f.read())
        fmt = int(state.get("format", 0))
        if fmt != STATE_FORMAT:
            raise ValueError(
                f"server snapshot {self._path(seqs[-1])} has format {fmt}, "
                f"this build reads {STATE_FORMAT} — refusing a silently "
                "wrong resume")
        return state

    def latest_round(self) -> Optional[int]:
        seqs = self._seqs()
        if not seqs:
            return None
        with open(self._path(seqs[-1])[:-len(".msgpack")] + ".json") as f:
            return int(json.load(f)["round_idx"])

    def _gc(self) -> None:
        keep = set(self._seqs()[-self.keep_last_n:])
        # sorted: deletion order must not depend on the filesystem (a
        # crash mid-GC leaves a deterministic survivor set)
        for fn in sorted(os.listdir(self.directory)):
            if not fn.startswith("state_"):
                continue
            stem = fn.split(".")[0]
            try:
                seq = int(stem.split("_")[1])
            except (IndexError, ValueError):
                continue
            # stray .tmp files and sidecar-less blobs from a crash are
            # orphans _seqs() never reports — sweep them too
            complete = not fn.endswith(".tmp") and seq in keep
            if not complete:
                try:
                    os.remove(os.path.join(self.directory, fn))
                except FileNotFoundError:
                    pass

    # -- the round/cohort ledger --------------------------------------------
    @property
    def ledger_path(self) -> str:
        return os.path.join(self.directory, "ledger.jsonl")

    def append_ledger(self, rec: Dict[str, Any]) -> None:
        """One closed round -> one JSON line (append + flush: line-level
        durability; the snapshot that follows is the consistency point)."""
        with open(self.ledger_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def read_ledger(self, dedup: bool = True) -> List[Dict[str, Any]]:
        """Ledger rows in round order. ``dedup`` keeps the LAST
        occurrence per round (a crash between ledger append and snapshot
        makes the restored server re-close that round — the re-append is
        the authoritative row). A torn final line (kill mid-write) is
        skipped."""
        if not os.path.exists(self.ledger_path):
            return []
        rows: List[Dict[str, Any]] = []
        with open(self.ledger_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    logging.warning("ledger %s: skipping torn line %r",
                                    self.ledger_path, line[:80])
        if dedup:
            by_round = {int(r["round"]): r for r in rows}
            rows = [by_round[r] for r in sorted(by_round)]
        return rows
