"""Durable server control-plane snapshots + the round/cohort ledger.

The PR-5 fault-tolerance layer made the *silos* survivable; the server
was still a single point of failure: kill it mid-schedule and the whole
federation dies, because the round-schedule state (round index, live
set, compression mirror, pending replies, aggregation partials) lived
only in process memory. This module is the durable half of the elastic
control plane: :class:`ServerControlCheckpointer` snapshots the FULL
control state dict the server captures (``_capture_control_state`` in
``algorithms/fedavg_cross_silo.py`` — field manifest in
``control/manifest.py``, enforced by lint rule FT009) and restores it
on restart, so a killed-and-restarted server resumes mid-schedule and
the existing silo-side rejoin protocol reconnects the fleet.

Format: one ``state_<seq>.msgpack`` blob per snapshot
(``flax.serialization.msgpack_serialize`` — template-free restore, so
variable-structure state like the pending-reply dict round-trips) plus a
``state_<seq>.json`` sidecar with the round index. Writes follow the
repo's atomic idiom (tmp + ``os.replace``, blob first, sidecar last):
a crash at ANY point leaves either a complete older snapshot or a
complete newer one — a snapshot without its sidecar is invisible to
``load_latest`` and swept by GC (crash-consistency tested, mirroring
``test_state_store.py``). The blob and sidecar are fsynced before their
rename and the directory entry is fsynced after (``utils/fsio.py`` —
degrade-to-warning on filesystems that refuse directory fsync).

**Incremental serialization** (:class:`IncrementalStateSerializer`):
``save(state, versions=...)`` caches each versioned field's msgpack
bytes keyed by a caller-supplied version token plus a content sha, and
reassembles the blob from cached bytes when the token is unchanged —
byte-identical output to the monolithic ``msgpack_serialize`` (verified
once per process, permanent fallback on mismatch). The global model
only changes at aggregation, so every mid-round extension snapshot
reuses its cached bytes instead of re-serializing megabytes.

**Asynchronous writes** (:class:`AsyncCheckpointWriter`): wraps a
checkpointer so ``save`` becomes a cheap hand-off to a dedicated writer
thread behind a depth-1 coalescing slot (newest snapshot wins — a
writer that falls behind skips intermediate snapshots, never queues
them). The writer preserves the durability ordering the crash oracles
pin: it syncs the ledger through the covered round BEFORE publishing
the snapshot, so a crash can only ever lose *trailing* state — restore
lands on an older complete boundary and the deterministic schedule
replays forward (re-appended ledger rows dedup by round, keeping the
last). ``flush()`` is the barrier the close/SIGTERM/extension-
exhaustion paths take; ``--checkpoint_sync`` skips the wrapper entirely
for the old inline semantics.

The **ledger** (``ledger.jsonl``) is the schedule's durable trace: one
JSON line per closed round with the round index, the broadcast cohort,
the reporting silos, and whether the close was partial. It is the
acceptance oracle for failover — a resumed run's ledger must match the
unkilled reference's — and the progress feed the failover harness polls.
Lines are appended *before* the snapshot, so a crash between the two
re-closes the round after restore and re-appends it: readers dedup by
round keeping the LAST occurrence. Appends are one write+flush of a
complete line (torn-at-most-final-line for readers); the fsync is
**group-committed** — every ``group_commit_lines`` lines or
``group_commit_ms`` milliseconds, plus the pre-publish sync barrier and
flush-on-close — so the round thread no longer pays a disk sync per
close.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from fedml_tpu.utils.fsio import fsync_dir

_STATE_RE = re.compile(r"state_(\d+)\.msgpack$")

#: bumped when the snapshot layout changes incompatibly
STATE_FORMAT = 1

#: group-commit defaults used by the async control plane (the legacy
#: synchronous checkpointer fsyncs every ledger line: lines=1, ms=0)
GROUP_COMMIT_LINES = 8
GROUP_COMMIT_MS = 50.0


def _msgpack_map_header(n: int) -> bytes:
    """The msgpack map header for an ``n``-entry map — the only piece of
    the format the incremental assembler writes itself (entries are
    standard ``packb`` output spliced verbatim)."""
    if n <= 0x0F:
        return bytes([0x80 | n])
    if n <= 0xFFFF:
        return b"\xde" + n.to_bytes(2, "big")
    return b"\xdf" + n.to_bytes(4, "big")


class IncrementalStateSerializer:
    """Per-field msgpack byte cache for the control-state blob.

    msgpack encodes a map as ``header + concat(packb(key) + packb(value))``
    — so the full-state blob can be reassembled from independently packed
    fields, and a field whose caller-supplied version token is unchanged
    reuses its cached bytes (the global model between aggregations, the
    mirror between broadcasts) instead of re-serializing megabytes.
    Cached entries carry a content sha256 so every reuse is traceable to
    the bytes it stands for.

    The first assembled blob is verified byte-identical against the
    monolithic ``flax.serialization.msgpack_serialize`` output; a
    mismatch (a future msgpack/flax encoding change) logs once and falls
    back to monolithic serialization permanently — correctness never
    rides on the splice.
    """

    def __init__(self) -> None:
        #: field -> (version token, packed bytes, content sha256)
        self._cache: Dict[str, Tuple[Any, bytes, str]] = {}
        self.cache_hits = 0
        self.cache_misses = 0
        self._verified = False
        self._fallback = False

    def serialize(self, state: Dict[str, Any],
                  versions: Optional[Dict[str, Any]] = None) -> bytes:
        from flax import serialization as fser
        if self._fallback or not versions:
            return fser.msgpack_serialize(state)
        import msgpack
        parts = [_msgpack_map_header(len(state))]
        # flax's serializer runs the state through tree_map, which
        # rebuilds dicts with SORTED keys — the splice must iterate in
        # the same order to be byte-identical (nested dicts are handled
        # by the per-field msgpack_serialize call itself)
        for key in sorted(state):
            value = state[key]
            parts.append(msgpack.packb(key))
            token = versions.get(key)
            cached = self._cache.get(key) if token is not None else None
            if cached is not None and cached[0] == token:
                self.cache_hits += 1
                parts.append(cached[1])
                continue
            packed = fser.msgpack_serialize(value)
            if token is not None:
                self.cache_misses += 1
                self._cache[key] = (token, packed,
                                    hashlib.sha256(packed).hexdigest())
            parts.append(packed)
        blob = b"".join(parts)
        if not self._verified:
            # one-time parity oracle per process: the splice must be
            # byte-identical to the monolithic serializer before any
            # cached reuse is trusted
            self._verified = True
            full = fser.msgpack_serialize(state)
            if blob != full:
                logging.warning(
                    "incremental snapshot serializer does not match "
                    "msgpack_serialize output (%d vs %d bytes) — "
                    "falling back to monolithic serialization",
                    len(blob), len(full))
                self._fallback = True
                self._cache.clear()
                return full
        return blob

    def field_sha(self, field: str) -> Optional[str]:
        """Content fingerprint of a cached field's serialized bytes."""
        entry = self._cache.get(field)
        return entry[2] if entry is not None else None


class ServerControlCheckpointer:
    """Synchronous snapshot + ledger store (the durable substrate both
    the legacy ``--checkpoint_sync`` path and the async writer share).

    ``save``/``append_ledger`` are not re-entrant with themselves, but
    ``append_ledger`` (round thread) is safe against ``sync_ledger``/
    ``save`` (writer thread) — the ledger handle is guarded by
    ``_ledger_wlock`` and the snapshot path touches only fresh files.
    """

    def __init__(self, directory: str, keep_last_n: int = 3,
                 group_commit_lines: int = 1,
                 group_commit_ms: float = 0.0):
        self.directory = directory
        self.keep_last_n = max(1, int(keep_last_n))
        #: ledger fsync cadence: 1/0 = the legacy fsync-per-line
        self.group_commit_lines = max(1, int(group_commit_lines))
        self.group_commit_ms = float(group_commit_ms)
        self._serializer = IncrementalStateSerializer()
        # ledger group-commit state (handle + pending-line bookkeeping);
        # the "wlock" suffix marks it as a sanctioned I/O-under-lock
        # site for FT022 — the only blocking work under it is the
        # ledger's own write/flush/group-commit fsync
        self._ledger_wlock = threading.Lock()
        self._ledger_fh = None
        self._ledger_pending = 0
        self._ledger_last_fsync = time.monotonic()
        # durability accounting (the round_overheads bench's fsync
        # breakdown reads these; pure observers)
        self.fsync_count = 0
        self.ledger_fsync_count = 0
        self.ledger_lines = 0
        self.save_count = 0
        os.makedirs(directory, exist_ok=True)

    # -- snapshot naming ----------------------------------------------------
    def _path(self, seq: int) -> str:
        return os.path.join(self.directory, f"state_{seq:012d}.msgpack")

    def _seqs(self) -> List[int]:
        """Snapshot sequence numbers with BOTH files present (a blob
        whose sidecar never landed is a torn write — invisible)."""
        names = set(os.listdir(self.directory))
        out = []
        for fn in sorted(names):
            m = _STATE_RE.fullmatch(fn)
            if m and fn[:-len(".msgpack")] + ".json" in names:
                out.append(int(m.group(1)))
        return sorted(out)

    # -- save / load --------------------------------------------------------
    def save(self, state: Dict[str, Any],
             versions: Optional[Dict[str, Any]] = None) -> str:
        """Atomically persist one control-state snapshot; returns its
        path. ``state`` must be msgpack-serializable (numpy arrays,
        dicts with str keys, lists, scalars, None) — the server's
        capture method guarantees that shape. ``versions`` maps field
        names to version tokens for the incremental serializer: a field
        whose token is unchanged since the last save reuses its cached
        bytes instead of re-serializing."""
        seqs = self._seqs()
        seq = (seqs[-1] + 1) if seqs else 0
        path = self._path(seq)
        blob = self._serializer.serialize(
            dict(state, format=STATE_FORMAT), versions)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
            self.fsync_count += 1
        os.replace(tmp, path)
        # sidecar LAST: _seqs() requires both files, so a crash anywhere
        # in this method leaves the previous snapshot authoritative
        side = path[:-len(".msgpack")] + ".json"
        stmp = f"{side}.{os.getpid()}.tmp"
        with open(stmp, "w") as f:
            json.dump({"seq": seq, "round_idx": int(state["round_idx"]),
                       "format": STATE_FORMAT,
                       "blob_sha256":
                           hashlib.sha256(blob).hexdigest()}, f)
            f.flush()
            os.fsync(f.fileno())
            self.fsync_count += 1
        os.replace(stmp, side)
        # the two renames live in the directory entry: fsync it so the
        # publish survives power loss too, not just process death
        if fsync_dir(self.directory):
            self.fsync_count += 1
        self.save_count += 1
        self._gc()
        return path

    def serializer_cache_hits(self) -> int:
        return self._serializer.cache_hits

    def load_latest(self) -> Optional[Dict[str, Any]]:
        """The newest complete snapshot as a plain dict (numpy leaves),
        or None when the directory holds none."""
        from flax import serialization as fser
        seqs = self._seqs()
        if not seqs:
            return None
        with open(self._path(seqs[-1]), "rb") as f:
            state = fser.msgpack_restore(f.read())
        fmt = int(state.get("format", 0))
        if fmt != STATE_FORMAT:
            raise ValueError(
                f"server snapshot {self._path(seqs[-1])} has format {fmt}, "
                f"this build reads {STATE_FORMAT} — refusing a silently "
                "wrong resume")
        return state

    def latest_round(self) -> Optional[int]:
        seqs = self._seqs()
        if not seqs:
            return None
        with open(self._path(seqs[-1])[:-len(".msgpack")] + ".json") as f:
            return int(json.load(f)["round_idx"])

    def _gc(self) -> None:
        keep = set(self._seqs()[-self.keep_last_n:])
        # sorted: deletion order must not depend on the filesystem (a
        # crash mid-GC leaves a deterministic survivor set)
        for fn in sorted(os.listdir(self.directory)):
            if not fn.startswith("state_"):
                continue
            stem = fn.split(".")[0]
            try:
                seq = int(stem.split("_")[1])
            except (IndexError, ValueError):
                continue
            # stray .tmp files and sidecar-less blobs from a crash are
            # orphans _seqs() never reports — sweep them too
            complete = not fn.endswith(".tmp") and seq in keep
            if not complete:
                try:
                    os.remove(os.path.join(self.directory, fn))
                except FileNotFoundError:
                    pass

    # -- the round/cohort ledger --------------------------------------------
    @property
    def ledger_path(self) -> str:
        return os.path.join(self.directory, "ledger.jsonl")

    def append_ledger(self, rec: Dict[str, Any]) -> None:
        """One closed round -> one JSON line. The write+flush of a
        complete line keeps the torn-at-most-final-line reader contract;
        the fsync is group-committed (every ``group_commit_lines`` lines
        or ``group_commit_ms`` ms, whichever first — the legacy
        fsync-per-line is ``lines=1``). ``sync_ledger`` is the barrier:
        the async writer takes it before every snapshot publish, so
        snapshot durability never outruns ledger durability."""
        line = json.dumps(rec) + "\n"
        with self._ledger_wlock:
            if self._ledger_fh is None:
                self._ledger_fh = open(self.ledger_path, "a")
            self._ledger_fh.write(line)
            self._ledger_fh.flush()
            self.ledger_lines += 1
            self._ledger_pending += 1
            now = time.monotonic()
            due = (self._ledger_pending >= self.group_commit_lines
                   or (self.group_commit_ms > 0.0
                       and (now - self._ledger_last_fsync) * 1e3  # ft: allow[FT015] group-commit deadline is a real-time durability contract — it schedules WHEN the fsync lands, never a ledger line's content, so replay parity is untouched
                       >= self.group_commit_ms))
            if due:
                os.fsync(self._ledger_fh.fileno())
                self.fsync_count += 1
                self.ledger_fsync_count += 1
                self._ledger_pending = 0
                self._ledger_last_fsync = now

    def sync_ledger(self) -> None:
        """Force-fsync any pending ledger lines (the pre-publish
        ordering barrier and the flush-on-close path)."""
        with self._ledger_wlock:
            if self._ledger_fh is not None and self._ledger_pending:
                self._ledger_fh.flush()
                os.fsync(self._ledger_fh.fileno())
                self.fsync_count += 1
                self.ledger_fsync_count += 1
                self._ledger_pending = 0
                self._ledger_last_fsync = time.monotonic()

    def close(self) -> None:
        """Flush-on-close: sync pending ledger lines and release the
        append handle (safe to call more than once)."""
        with self._ledger_wlock:
            if self._ledger_fh is not None:
                try:
                    self._ledger_fh.flush()
                    if self._ledger_pending:
                        os.fsync(self._ledger_fh.fileno())
                        self.fsync_count += 1
                        self.ledger_fsync_count += 1
                        self._ledger_pending = 0
                    self._ledger_fh.close()
                except OSError:
                    logging.warning("ledger close for %s failed",
                                    self.ledger_path, exc_info=True)
                self._ledger_fh = None

    def read_ledger(self, dedup: bool = True) -> List[Dict[str, Any]]:
        """Ledger rows in round order. ``dedup`` keeps the LAST
        occurrence per round (a crash between ledger append and snapshot
        makes the restored server re-close that round — the re-append is
        the authoritative row). A torn final line (kill mid-write) is
        skipped."""
        if not os.path.exists(self.ledger_path):
            return []
        rows: List[Dict[str, Any]] = []
        with open(self.ledger_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    logging.warning("ledger %s: skipping torn line %r",
                                    self.ledger_path, line[:80])
        if dedup:
            by_round = {int(r["round"]): r for r in rows}
            rows = [by_round[r] for r in sorted(by_round)]
        return rows


class AsyncCheckpointWriter:
    """Depth-1 coalescing writer thread over a
    :class:`ServerControlCheckpointer` — the round thread's ``save``
    becomes an O(1) slot swap, and serialization/tmp-write/fsync/publish
    run on the dedicated writer.

    Coalescing: the slot holds at most ONE pending snapshot; a submit
    that finds the slot full replaces it (newest wins) and bumps
    ``coalesced`` — under backpressure the writer publishes the latest
    state, never a stale backlog. Restore may therefore land on an
    older round boundary than the ledger tail; the deterministic
    schedule replays forward and the parity oracles stay bit-exact
    because the writer syncs the ledger BEFORE each publish (snapshot
    durability never outruns ledger durability — the one new invariant
    async checkpointing needs).

    ``flush()`` is the synchronous barrier (schedule close, SIGTERM,
    extension exhaustion); ``abort()`` is the simulated-SIGKILL used by
    the in-process failover harness — drop the pending slot and stop,
    exactly what a kill does to the writer thread.
    """

    def __init__(self, inner: ServerControlCheckpointer,
                 name: str = "ckpt-writer"):
        self.inner = inner
        self._cond = threading.Condition()
        self._slot: Optional[Tuple[Dict[str, Any],
                                   Optional[Dict[str, Any]]]] = None
        self._seq_submitted = 0
        self._seq_done = 0
        self._stopped = False
        self.coalesced = 0
        self._coalesced_popped = 0
        self.published = 0
        self.failed = 0
        self.last_flush_ms = 0.0
        self.flush_ms_total = 0.0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._thread.start()

    # -- the checkpointer surface the server drives -------------------------
    @property
    def directory(self) -> str:
        return self.inner.directory

    @property
    def ledger_path(self) -> str:
        return self.inner.ledger_path

    def append_ledger(self, rec: Dict[str, Any]) -> None:
        self.inner.append_ledger(rec)

    def read_ledger(self, dedup: bool = True) -> List[Dict[str, Any]]:
        return self.inner.read_ledger(dedup=dedup)

    def load_latest(self) -> Optional[Dict[str, Any]]:
        return self.inner.load_latest()

    def latest_round(self) -> Optional[int]:
        return self.inner.latest_round()

    def save(self, state: Dict[str, Any],
             versions: Optional[Dict[str, Any]] = None) -> None:
        """Hand one snapshot to the writer (O(1): a slot swap + notify).
        After the wrapper is stopped (close/abort) the save degrades to
        the inline synchronous path — late barrier-side saves (the
        extension-exhaustion error path racing a close) still land."""
        with self._cond:
            if not self._stopped:
                if self._slot is not None:
                    self.coalesced += 1
                self._slot = (state, versions)
                self._seq_submitted += 1
                self._cond.notify_all()
                return
        self.inner.save(state, versions=versions)

    # -- writer thread ------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                while self._slot is None and not self._stopped:
                    self._cond.wait()
                if self._slot is None and self._stopped:
                    return
                state, versions = self._slot
                self._slot = None
                target = self._seq_submitted
            t0 = time.perf_counter()
            try:
                # durability ordering: the ledger must be durable
                # through the round this snapshot covers BEFORE the
                # snapshot publishes — else a crash could surface a
                # snapshot newer than the durable ledger and the replay
                # oracle would see rounds the ledger never closed
                self.inner.sync_ledger()
                self.inner.save(state, versions=versions)
                self.published += 1
            except Exception:
                self.failed += 1
                logging.warning(
                    "async control snapshot write failed — the schedule "
                    "continues WITHOUT failover protection",
                    exc_info=True)
            finally:
                self.last_flush_ms = (time.perf_counter() - t0) * 1e3
                self.flush_ms_total += self.last_flush_ms
                with self._cond:
                    self._seq_done = max(self._seq_done, target)
                    self._cond.notify_all()

    # -- barriers / lifecycle ----------------------------------------------
    def flush(self, timeout: Optional[float] = 60.0) -> bool:
        """Block until every snapshot submitted BEFORE this call is
        published (or failed-with-warning). The barrier the schedule
        close, SIGTERM, and extension-exhaustion paths take before they
        let the process die."""
        with self._cond:
            target = self._seq_submitted
            ok = self._cond.wait_for(
                lambda: self._seq_done >= target or self._stopped,
                timeout=timeout)
        self.inner.sync_ledger()
        return bool(ok)

    def close(self, timeout: Optional[float] = 60.0) -> None:
        """flush + stop the writer + flush-on-close the ledger."""
        self.flush(timeout=timeout)
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)
        self.inner.close()

    def abort(self) -> None:
        """Simulated SIGKILL (failover tests): drop the pending slot and
        stop WITHOUT flushing — pending state is lost exactly as a real
        kill loses it; restore lands on the last published boundary."""
        with self._cond:
            self._slot = None
            self._stopped = True
            self._seq_done = self._seq_submitted
            self._cond.notify_all()
        self._thread.join(timeout=10)

    # -- telemetry ----------------------------------------------------------
    def pop_coalesced(self) -> int:
        """Coalesced-submit count since the last pop (the server credits
        this into ``cp_writer_queue_coalesced`` at round close)."""
        with self._cond:
            delta = self.coalesced - self._coalesced_popped
            self._coalesced_popped = self.coalesced
            return delta

    def stats(self) -> Dict[str, Any]:
        with self._cond:
            return {
                "published": self.published,
                "coalesced": self.coalesced,
                "failed": self.failed,
                "pending": 0 if self._slot is None else 1,
                "last_flush_ms": self.last_flush_ms,
                "flush_ms_total": self.flush_ms_total,
            }
