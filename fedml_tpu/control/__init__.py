"""Elastic federation control plane (Bonawitz et al., MLSys 2019).

PR 5 made the *silos* survivable; this package makes the *coordinator*
survivable and its schedule adaptive:

- :class:`~fedml_tpu.control.checkpoint.ServerControlCheckpointer` —
  durable snapshots of the server's full round-schedule state (round
  index, live set, compression mirror / base seqs, pending replies,
  aggregation partials, steering windows) plus the round/cohort ledger;
  a killed-and-restarted server resumes mid-schedule.
- :class:`~fedml_tpu.control.pace.PaceSteerer` — adaptive round
  deadlines (p90 · margin, clamped) and quorum targets from the observed
  per-silo report-latency distribution, replacing the static
  ``--round_deadline_s`` / ``--min_quorum_frac`` when ``--pace_steering``
  is on.
- :class:`~fedml_tpu.control.admission.JoinAdmissionController` — a
  token bucket between mass-rejoin floods and the server's
  full-precision resync path, with BACKPRESSURE replies.
- ``control/manifest.py`` — the checkpoint field manifest lint rule
  FT009 enforces against the server classes.
- ``control/failover_harness.py`` — the SIGKILL-the-server acceptance
  harness (also the ``server_failover`` bench stage's kill leg).
"""

from fedml_tpu.control.admission import JoinAdmissionController
from fedml_tpu.control.checkpoint import (GROUP_COMMIT_LINES,
                                          GROUP_COMMIT_MS,
                                          AsyncCheckpointWriter,
                                          ServerControlCheckpointer)
from fedml_tpu.control.pace import QUORUM_CEIL, PaceSteerer


class SchedulingStallError(RuntimeError):
    """A round exhausted its deadline-extension budget
    (``--max_deadline_extensions``) while below quorum: the federation
    cannot make progress (too many silos permanently dark for the quorum
    target). The server checkpoints its final state, FINISHes the
    surviving silos, and the launcher raises this — a loud scheduling
    failure instead of the pre-control-plane forever-extend hang."""


def build_control_plane(server_checkpoint_dir=None, pace_steering=False,
                        join_rate_limit=0.0, round_deadline_s=None,
                        min_quorum_frac=0.5, max_deadline_extensions=25,
                        checkpoint_sync=False):
    """Resolve the control-plane flags into the kwargs the round-based
    server managers take (``server_ckpt`` / ``pace`` / ``join_admission``
    / ``max_deadline_extensions``). All-defaults resolves to the inert
    configuration — byte-identical to the pre-control-plane servers.

    Checkpointing is asynchronous by default (a dedicated writer thread
    with a depth-1 coalescing slot and group-committed ledger fsyncs —
    the round thread only pays the capture copy); ``checkpoint_sync``
    forces the legacy inline snapshot-at-every-boundary semantics with
    an fsync per ledger line."""
    if pace_steering and not round_deadline_s:
        raise ValueError(
            "--pace_steering needs --round_deadline_s as the base "
            "deadline steering starts from (and falls back to until "
            "enough report latencies are observed)")

    def _make_ckpt():
        if checkpoint_sync:
            return ServerControlCheckpointer(server_checkpoint_dir)
        return AsyncCheckpointWriter(ServerControlCheckpointer(
            server_checkpoint_dir,
            group_commit_lines=GROUP_COMMIT_LINES,
            group_commit_ms=GROUP_COMMIT_MS))

    return {
        "server_ckpt": _make_ckpt() if server_checkpoint_dir else None,
        # the floor is the caller's static quorum, capped at the steering
        # ceiling (a 1.0 floor would pin steering at the full barrier —
        # the deadlock the deadline exists to break)
        "pace": (PaceSteerer(base_deadline_s=round_deadline_s,
                             quorum_floor=min(min_quorum_frac,
                                              QUORUM_CEIL))
                 if pace_steering else None),
        "join_admission": (JoinAdmissionController(join_rate_limit)
                           if join_rate_limit and join_rate_limit > 0
                           else None),
        "max_deadline_extensions": max_deadline_extensions,
    }


__all__ = ["AsyncCheckpointWriter", "JoinAdmissionController",
           "PaceSteerer", "ServerControlCheckpointer",
           "SchedulingStallError", "build_control_plane"]
