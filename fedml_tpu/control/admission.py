"""JOIN admission control — a token bucket between the rejoin protocol
and the server's expensive resync path.

A network partition healing is the worst case for the PR-5 rejoin
protocol: every silo on the wrong side of the cut escalates to JOIN on
its heartbeat cadence at once, and each admitted JOIN costs the server a
FULL-precision mirror resync (the expensive frames the downlink
compression ladder exists to avoid) plus a broadcast-path device
dispatch. A mass rejoin therefore stampedes exactly the component that
just recovered. :class:`JoinAdmissionController` is the standard fix: a
token bucket (``rate_per_s`` sustained, ``burst`` instantaneous) gates
the resync path; a JOIN that finds the bucket empty gets a BACKPRESSURE
reply carrying ``retry_after_s`` instead of a resync, and the silo
defers its next JOIN attempt by that long (its heartbeat keeps beating —
backpressure rejects the *resync*, not the proof of life).

The clock is injectable for deterministic tests; the controller is
thread-safe (JOINs arrive on the server's receive thread, but tests
drive it from anywhere).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class JoinAdmissionController:
    def __init__(self, rate_per_s: float, burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        if rate_per_s <= 0:
            raise ValueError(f"rate_per_s must be > 0 (got {rate_per_s}); "
                             "leave admission control off instead")
        self.rate_per_s = float(rate_per_s)
        #: bucket capacity: how many JOINs may land back-to-back before
        #: throttling starts (default: one second's worth, at least 1)
        self.burst = float(burst) if burst is not None \
            else max(1.0, self.rate_per_s)
        self._clock = clock
        self._tokens = self.burst
        self._last = self._clock()
        self._lock = threading.Lock()
        self.admitted = 0
        self.throttled = 0

    def _refill(self, now: float) -> None:
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last)
                           * self.rate_per_s)
        self._last = now

    def try_acquire(self) -> bool:
        """Consume one token if available. False = throttle this JOIN."""
        with self._lock:
            self._refill(self._clock())
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.admitted += 1
                return True
            self.throttled += 1
            return False

    def retry_after_s(self) -> float:
        """How long until a token exists — the backpressure reply's
        deferral hint (>= 0; small positive jitterless value the silo
        adds to its own heartbeat cadence)."""
        with self._lock:
            self._refill(self._clock())
            if self._tokens >= 1.0:
                return 0.0
            return (1.0 - self._tokens) / self.rate_per_s
