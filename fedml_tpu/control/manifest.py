"""The server checkpoint field manifest — the contract FT009 enforces.

Every mutable attribute a cross-silo *server manager* writes from its
message/round loop must be accounted for here, in exactly one of two
sets:

- :data:`SERVER_CHECKPOINT_FIELDS` — round-schedule state that the
  elastic control plane (``fedml_tpu/control/checkpoint.py``) snapshots
  at round boundaries / deadline closes, and restores on server
  failover. Forgetting a NEW field here is the bug class FT009 exists
  for: the field silently resets on restart and the resumed schedule
  diverges from the unkilled run.
- :data:`SERVER_EPHEMERAL_FIELDS` — state that is *meaningless across a
  process restart* (armed timers, wall-clock origins, terminal error
  latches) and is deliberately NOT checkpointed; each entry documents
  why.

This module is imported by the FT009 lint rule
(``fedml_tpu/analysis/rules/server_state.py``) and must stay
import-light (no jax/flax) so the analyzer never pays a framework
import to read a frozenset.
"""

from __future__ import annotations

#: round-schedule state captured by ``_capture_control_state`` /
#: restored by ``_restore_control_state`` (algorithms/fedavg_cross_silo.py)
SERVER_CHECKPOINT_FIELDS = frozenset({
    # -- schedule position --------------------------------------------------
    "round_idx",            # the sampling cursor: cohorts + client RNG keys
                            # are pure functions of (seed, round_idx)
    "global_model",         # the aggregated model entering the round
    "_round_cohort",        # the cohort broadcast for the OPEN round
    # -- liveness / fault-tolerance ledger ----------------------------------
    "liveness",             # live set + evict/rejoin counters + latency
                            # window (last-seen wall-clocks are NOT restored
                            # — they restart fresh at re-launch)
    "live_history",         # per-round {round, reported, live, partial}
    "ft_counters",          # partial_rounds / stale_replies / ... roll-up
    "cp_counters",          # checkpoints / restores / adjustments / throttles
    "_resynced_round",      # one-JOIN-resync-per-round throttle state
    # -- downlink compression chain -----------------------------------------
    "_bcast_seq",           # broadcast version counter
    "_mirror",              # the model every in-sync silo holds
    "_mirror_fp",
    "_worker_base",         # per-silo (held seq, structure fp) reports;
                            # snapshotted for forensics, CLEARED on restore
                            # (value-level staleness across a failover is
                            # undetectable, so the first post-restore
                            # broadcast rebases full precision)
    # -- pace steering ------------------------------------------------------
    "round_deadline_s",     # the CURRENT (possibly steered) deadline
    "min_quorum_frac",      # the CURRENT (possibly steered) quorum target
    "_evict_on_deadline",   # which close policy the schedule runs under
    "_extensions_this_round",
    # -- pending round (mid-round snapshots: deadline extensions, the
    #    extension-cap error path) ------------------------------------------
    "aggregator",           # model_dict / sample_num_dict / uploaded flags
    # -- subclass state ------------------------------------------------------
    "server_opt_state",     # FedOptServerManager's persistent optimizer
    "partial_rounds",       # QuorumFedAvgServerManager's below-strength log
})

#: deliberately NOT checkpointed — each entry says why restart-fresh is
#: correct
SERVER_EPHEMERAL_FIELDS = frozenset({
    "_timer",               # armed threading.Timer: re-armed by the first
                            # post-restore broadcast
    "_bcast_at",            # monotonic-clock latency origin of the open
                            # round: meaningless in a new process
    "scheduling_error",     # terminal latch: a run that died on it is over,
                            # not resumable past the error
    "_control_restored",    # one-shot restore latch inside send_init_msg:
                            # a fresh process restores at most once
    "_model_version",       # serialization token for the incremental
                            # snapshot writer: a restarted server starts a
                            # fresh serializer cache, so the counter may
                            # restart from zero
    "_gm_capture_cache",    # (version, state-dict) capture memo keyed by
                            # _model_version: derived from global_model,
                            # rebuilt on first post-restore capture
})

#: server classes exempt from FT009: no round schedule exists to resume.
#: FedAsync merges every update into a version counter with no round
#: barrier — a restarted FedAsync server is just a fresh server.
UNCHECKPOINTED_SERVER_CLASSES = frozenset({"AsyncFedAvgServerManager"})
