"""Server failover harness — the control plane's acceptance oracle.

Two kill modes over one deterministic fixture (seeded blob federation +
logistic regression, identical in every process that builds it):

- **Simulated crash** (:func:`run_simulated_failover`, fast lane):
  phase 1 runs a server whose receive loop stops COLD — no FINISH, no
  cleanup — right before broadcasting round ``crash_at_round`` (exactly
  what SIGKILL looks like to the fleet); phase 2 constructs a FRESH
  server manager over the same comm fabric (same inproc router / same
  TCP port), which restores the newest control snapshot and completes
  the schedule against the SAME still-running silo actors. Memory loss
  is real (a new manager object); only the OS process survives.
- **Real SIGKILL** (:func:`run_failover_scenario`, slow lane + the
  ``server_failover`` bench kill leg): the server runs as a SUBPROCESS
  over TCP (``python -m fedml_tpu.control.failover_harness --role
  server``), the silos as threads in the caller's process. The driver
  polls the durable round/cohort ledger, SIGKILLs the server once
  ``kill_after_round`` closes, respawns it with the same flags (it
  auto-restores), and waits for the schedule to finish. Optionally a
  seeded :class:`~fedml_tpu.comm.faults.FaultPlan` flaps a fraction of
  the silos throughout — the ISSUE's chaos acceptance.

The parity oracle either way is the ledger: the resumed run's
round/cohort sequence must equal an unkilled reference's
(:func:`ledger_schedule`).

``--smoke`` runs a small SIGKILL scenario end-to-end and exits non-zero
unless the schedule completed with ``cp_restores >= 1`` — the cpu-smoke
fronting ``ci/run_fast.sh``.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

#: fixture constants — every process that builds the fixture must agree
FIXTURE_SEED = 3
MODEL_SEED = 0
DEFAULT_WORKERS = 3
DEFAULT_ROUNDS = 8


def build_fixture(workers: int = DEFAULT_WORKERS):
    """The shared deterministic federation: (dataset, module, train_cfg).
    Pure function of its arguments — the server subprocess and the silo
    process build bit-identical copies."""
    from fedml_tpu.data.synthetic import make_blob_federated
    from fedml_tpu.models.lr import LogisticRegression
    from fedml_tpu.trainer.functional import TrainConfig
    ds = make_blob_federated(client_num=workers, dim=8, class_num=3,
                             n_samples=120, seed=FIXTURE_SEED)
    return ds, LogisticRegression(num_classes=3), TrainConfig(
        epochs=1, batch_size=8, lr=0.3)


def make_addresses(port_base: int, size: int) -> Dict[int, Tuple[str, int]]:
    return {r: ("127.0.0.1", port_base + r) for r in range(size)}


def _make_com(backend: str, rank: int, size: int, *, router=None,
              addresses=None, fault_plan=None, bind_retry_s: float = 10.0):
    """create_comm_manager with a bind-retry loop: a restarted server
    re-binds the port its previous incarnation held — the old listener
    closes within its 0.5 s accept timeout (simulated crash) or at
    process death (SIGKILL), so EADDRINUSE here is transient."""
    from fedml_tpu.comm import create_comm_manager
    deadline = time.monotonic() + bind_retry_s
    while True:
        try:
            return create_comm_manager(backend, rank, size, router=router,
                                       addresses=addresses, wire_codec=True,
                                       fault_plan=fault_plan)
        except OSError:
            # ft: allow[FT015] bind-retry budget against the kernel's TIME_WAIT — real time is the only signal a port frees on
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.2)


def start_silos(backend: str, workers: int, *, router=None, addresses=None,
                fault_plan=None, heartbeat_s: float = 0.3):
    """The silo half of launch_federation, standalone: client managers +
    receive threads that outlive any number of server incarnations
    (heartbeat + JOIN escalation is their reconnect path)."""
    from fedml_tpu.algorithms.fedavg_cross_silo import FedAvgClientManager
    ds, module, tcfg = build_fixture(workers)
    size = workers + 1
    clients, threads = [], []
    for rank in range(1, size):
        com = _make_com(backend, rank, size, router=router,
                        addresses=addresses, fault_plan=fault_plan)
        clients.append(FedAvgClientManager(
            rank, size, com, ds, module, "classification", tcfg,
            seed=MODEL_SEED, heartbeat_s=heartbeat_s))
    for c in clients:
        t = threading.Thread(target=c.run, daemon=True)
        t.start()
        threads.append(t)
    return clients, threads


def _build_server(com, workers: int, rounds: int, ckpt_dir: str, *,
                  deadline_s: Optional[float], min_quorum_frac: float,
                  pace: bool, join_rate_limit: float,
                  max_deadline_extensions: int, server_cls=None,
                  obs_dir: Optional[str] = None,
                  checkpoint_sync: bool = False):
    from fedml_tpu.algorithms.fedavg_cross_silo import (FedAvgAggregator,
                                                        FedAvgServerManager)
    from fedml_tpu.control import build_control_plane
    from fedml_tpu.utils.tracing import RoundTimer
    import jax
    import jax.numpy as jnp
    ds, module, _ = build_fixture(workers)
    global_model = module.init(jax.random.key(MODEL_SEED),
                               jnp.asarray(ds.train_data_global[0][:1]),
                               train=False)
    control = build_control_plane(
        server_checkpoint_dir=ckpt_dir, pace_steering=pace,
        join_rate_limit=join_rate_limit, round_deadline_s=deadline_s,
        min_quorum_frac=min_quorum_frac,
        max_deadline_extensions=max_deadline_extensions,
        checkpoint_sync=checkpoint_sync)
    cls = server_cls or FedAvgServerManager
    server = cls(0, workers + 1, com, FedAvgAggregator(workers), rounds,
                 ds.client_num, global_model,
                 round_deadline_s=deadline_s,
                 min_quorum_frac=min_quorum_frac, **control)
    server.round_timer = RoundTimer()
    if obs_dir:
        # flight recorder next to the checkpoints/ledger: a restarted
        # server APPENDS to the same flight log under a new transport
        # epoch, so the merged timeline shows both lives
        from fedml_tpu.obs import build_observability, endpoint_epoch
        obs = build_observability(obs_dir, job_id="failover", rank=0,
                                  role="server")
        obs.recorder.set_epoch(endpoint_epoch(com))
        obs.bind_timer(server.round_timer)
        server.obs = obs
    return server


def serve(rounds: int, workers: int, port_base: int, ckpt_dir: str, *,
          deadline_s: float, min_quorum_frac: float = 0.5,
          pace: bool = False, join_rate_limit: float = 0.0,
          max_deadline_extensions: int = 25,
          join_timeout_s: float = 600.0,
          obs_dir: Optional[str] = None,
          checkpoint_sync: bool = False) -> int:
    """Subprocess entry: run ONE server incarnation over TCP until the
    schedule completes (or this process is killed mid-flight — the point
    of the exercise). Writes ``server_summary.json`` next to the
    checkpoints and returns a process exit code."""
    size = workers + 1
    com = _make_com("TCP", 0, size,
                    addresses=make_addresses(port_base, size))
    try:
        return _serve_with(com, workers, rounds, ckpt_dir,
                           deadline_s=deadline_s,
                           min_quorum_frac=min_quorum_frac, pace=pace,
                           join_rate_limit=join_rate_limit,
                           max_deadline_extensions=max_deadline_extensions,
                           join_timeout_s=join_timeout_s,
                           obs_dir=obs_dir,
                           checkpoint_sync=checkpoint_sync)
    finally:
        # the listener must not survive a raise: the supervisor
        # relaunches this incarnation on the SAME port, and a leaked
        # bind turns every failover into EADDRINUSE
        com.stop_receive_message()


def _serve_with(com, workers: int, rounds: int, ckpt_dir: str, *,
                deadline_s: float, min_quorum_frac: float, pace: bool,
                join_rate_limit: float, max_deadline_extensions: int,
                join_timeout_s: float, obs_dir: Optional[str],
                checkpoint_sync: bool = False) -> int:
    server = _build_server(com, workers, rounds, ckpt_dir,
                           deadline_s=deadline_s,
                           min_quorum_frac=min_quorum_frac, pace=pace,
                           join_rate_limit=join_rate_limit,
                           max_deadline_extensions=max_deadline_extensions,
                           obs_dir=obs_dir,
                           checkpoint_sync=checkpoint_sync)
    # graceful-stop barrier: SIGTERM (supervisor drain, NOT the SIGKILL
    # legs) flushes the async writer's pending snapshot + the ledger's
    # group-commit tail before the default handler takes the process
    def _sigterm(signum, frame):
        ckpt = server._server_ckpt
        if ckpt is not None:
            flush = getattr(ckpt, "flush", None)
            if flush is not None:
                flush(timeout=30)
            sync = getattr(ckpt, "inner", ckpt)
            sync.sync_ledger()
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)
    try:
        signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:
        pass  # not the main thread (embedded use): no signal barrier
    thread = threading.Thread(target=server.run, daemon=True)
    thread.start()
    server.send_init_msg()
    thread.join(timeout=join_timeout_s)
    done = server.round_idx >= rounds and not thread.is_alive()
    summary = {
        "rounds_completed": int(server.round_idx),
        "schedule_rounds": int(rounds),
        "done": bool(done),
        "cp_counters": {k: int(v) for k, v in server.cp_counters.items()},
        "ft_counters": {k: int(v) for k, v in server.ft_counters.items()},
        "evictions": int(server.liveness.evictions),
        "rejoins": int(server.liveness.rejoins),
        "final_deadline_s": server.round_deadline_s,
        "error": (str(server.scheduling_error)
                  if server.scheduling_error else None),
    }
    tmp = os.path.join(ckpt_dir, f"summary.{os.getpid()}.tmp")
    with open(tmp, "w") as f:
        json.dump(summary, f)
    os.replace(tmp, os.path.join(ckpt_dir, "server_summary.json"))
    return 0 if done else 1


# ---------------------------------------------------------------------------
# simulated crash (in-process; fast lane + INPROC/TCP resume-parity tests)
# ---------------------------------------------------------------------------
def make_crashing_server_cls(crash_at_round: int):
    """A server that 'dies' — stops its receive loop cold, no FINISH, no
    cleanup — right before broadcasting ``crash_at_round``. The newest
    control snapshot at that moment is exactly a SIGKILL's."""
    from fedml_tpu.algorithms.fedavg_cross_silo import (
        MSG_TYPE_S2C_SYNC_MODEL, FedAvgServerManager)

    class CrashBeforeBroadcast(FedAvgServerManager):
        crashed = False

        def _broadcast_model(self, msg_type, idxs):
            if (msg_type == MSG_TYPE_S2C_SYNC_MODEL
                    and self.round_idx == crash_at_round):
                type(self).crashed = True
                self._cancel_deadline()
                self.com_manager.stop_receive_message()
                # a real SIGKILL takes the async checkpoint writer
                # thread with it; the in-process simulation must do the
                # same (drop the pending slot, no flush) or the dead
                # server's writer would keep publishing snapshots and
                # race the phase-2 restore in this very process
                abort = getattr(self._server_ckpt, "abort", None)
                if abort is not None:
                    abort()
                return
            super()._broadcast_model(msg_type, idxs)

    return CrashBeforeBroadcast


def run_simulated_failover(ckpt_dir: str, *, rounds: int = 6,
                           workers: int = DEFAULT_WORKERS,
                           crash_at_round: int = 3,
                           backend: str = "INPROC",
                           port_base: Optional[int] = None,
                           deadline_s: Optional[float] = None,
                           min_quorum_frac: float = 0.5,
                           pace: bool = False,
                           join_timeout_s: float = 180.0,
                           obs_dir: Optional[str] = None,
                           checkpoint_sync: bool = False):
    """Kill-and-restart without subprocesses. Returns
    ``(final_model_numpy, ledger, server2)`` — server2 carries the
    restored counters and the bound RoundTimer."""
    import jax
    import numpy as np
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.control import ServerControlCheckpointer

    router = InProcRouter() if backend.upper() == "INPROC" else None
    size = workers + 1
    addresses = (make_addresses(port_base, size)
                 if backend.upper() == "TCP" else None)
    clients, client_threads = start_silos(backend, workers, router=router,
                                          addresses=addresses)
    common = dict(deadline_s=deadline_s, min_quorum_frac=min_quorum_frac,
                  pace=pace, join_rate_limit=0.0,
                  max_deadline_extensions=25, obs_dir=obs_dir,
                  checkpoint_sync=checkpoint_sync)

    # phase 1: runs to crash_at_round, then goes dark mid-schedule
    # (crash_at_round >= rounds never crashes: the unkilled reference leg)
    crashing = crash_at_round < rounds
    com1 = _make_com(backend, 0, size, router=router, addresses=addresses)
    s1 = _build_server(com1, workers, rounds, ckpt_dir,
                       server_cls=(make_crashing_server_cls(crash_at_round)
                                   if crashing else None),
                       **common)
    t1 = threading.Thread(target=s1.run, daemon=True)
    t1.start()
    s1.send_init_msg()
    t1.join(timeout=join_timeout_s)
    assert not t1.is_alive(), "phase-1 server never reached its crash point"
    s2 = s1
    if crashing:
        assert type(s1).crashed, "crash point not hit — schedule too short?"
        if router is not None:
            # the crashed server stopped from INSIDE its receive loop, so
            # its _STOP sentinel (and any stale heartbeats) still sit in
            # the shared rank-0 mailbox — a real process death frees its
            # queues; the in-proc simulation must drain them or the
            # restarted server's loop dies on the stale sentinel
            import queue as _queue
            q = router.mailbox(0)
            while True:
                try:
                    q.get_nowait()
                except _queue.Empty:
                    break
        # phase 2: a FRESH server over the same fabric restores + finishes
        com2 = _make_com(backend, 0, size, router=router,
                         addresses=addresses)
        s2 = _build_server(com2, workers, rounds, ckpt_dir, **common)
        t2 = threading.Thread(target=s2.run, daemon=True)
        t2.start()
        s2.send_init_msg()
        t2.join(timeout=join_timeout_s)
        assert not t2.is_alive(), \
            "restored server did not finish the schedule"
        assert s2.round_idx >= rounds, \
            (f"restored server stopped early at round {s2.round_idx} "
             f"of {rounds}")
    for t in client_threads:
        t.join(timeout=60)
    ledger = ServerControlCheckpointer(ckpt_dir).read_ledger()
    model = jax.tree.map(np.asarray, s2.global_model)
    return model, ledger, s2


# ---------------------------------------------------------------------------
# real SIGKILL (server subprocess over TCP; slow lane + bench kill leg)
# ---------------------------------------------------------------------------
def _spawn_server(port_base: int, rounds: int, workers: int, ckpt_dir: str,
                  deadline_s: float, pace: bool, join_rate_limit: float,
                  log_path: str,
                  obs_dir: Optional[str] = None,
                  checkpoint_sync: bool = False) -> subprocess.Popen:
    cmd = [sys.executable, "-m", "fedml_tpu.control.failover_harness",
           "--role", "server", "--rounds", str(rounds),
           "--workers", str(workers), "--port_base", str(port_base),
           "--ckpt_dir", ckpt_dir, "--deadline_s", str(deadline_s),
           "--join_rate_limit", str(join_rate_limit)]
    if pace:
        cmd.append("--pace")
    if checkpoint_sync:
        cmd.append("--checkpoint_sync")
    if obs_dir:
        cmd.extend(["--obs_dir", obs_dir])
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    logf = open(log_path, "ab")
    try:
        return subprocess.Popen(cmd, stdout=logf, stderr=logf, env=env)
    finally:
        logf.close()  # the child holds its own fd


def _wait_for_round(ckpt_dir: str, round_idx: int, proc: subprocess.Popen,
                    timeout_s: float) -> None:
    from fedml_tpu.control import ServerControlCheckpointer
    ckp = ServerControlCheckpointer(ckpt_dir)
    deadline = time.monotonic() + timeout_s
    # ft: allow[FT015] harness-side poll of a live subprocess's ledger — a real-time timeout on external progress, not schedule logic
    while time.monotonic() < deadline:
        rows = ckp.read_ledger()
        if rows and rows[-1]["round"] >= round_idx:
            return
        if proc.poll() is not None:
            raise RuntimeError(
                f"server subprocess exited (rc={proc.returncode}) before "
                f"round {round_idx} closed — see its log")
        time.sleep(0.05)
    raise TimeoutError(f"round {round_idx} did not close in {timeout_s}s")


def run_failover_scenario(ckpt_dir: str, *, rounds: int = DEFAULT_ROUNDS,
                          workers: int = DEFAULT_WORKERS,
                          kill_after_round: int = 2,
                          port_base: int = 40110,
                          deadline_s: float = 2.0,
                          pace: bool = False,
                          join_rate_limit: float = 0.0,
                          silo_fault_plan=None,
                          timeout_s: float = 300.0,
                          obs_dir: Optional[str] = None,
                          checkpoint_sync: bool = False) -> Dict:
    """SIGKILL the server subprocess mid-schedule, restart it, and wait
    for the full schedule. ``silo_fault_plan`` (e.g. a 30% flap) wraps
    the SILO endpoints only — the chaos rides the fleet while the kill
    rides the coordinator. Returns the final server summary + ledger +
    kill bookkeeping."""
    from fedml_tpu.control import ServerControlCheckpointer
    os.makedirs(ckpt_dir, exist_ok=True)
    log_path = os.path.join(ckpt_dir, "server.log")
    clients, client_threads = start_silos(
        "TCP", workers, addresses=make_addresses(port_base, workers + 1),
        fault_plan=silo_fault_plan)
    proc = _spawn_server(port_base, rounds, workers, ckpt_dir, deadline_s,
                         pace, join_rate_limit, log_path, obs_dir=obs_dir,
                         checkpoint_sync=checkpoint_sync)
    killed_at = None
    try:
        _wait_for_round(ckpt_dir, kill_after_round, proc, timeout_s / 2)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        killed_at = kill_after_round
        proc = _spawn_server(port_base, rounds, workers, ckpt_dir,
                             deadline_s, pace, join_rate_limit, log_path,
                             obs_dir=obs_dir,
                             checkpoint_sync=checkpoint_sync)
        rc = proc.wait(timeout=timeout_s)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    for t in client_threads:
        t.join(timeout=60)
    summary_path = os.path.join(ckpt_dir, "server_summary.json")
    summary = {}
    if os.path.exists(summary_path):
        with open(summary_path) as f:
            summary = json.load(f)
    return {
        "summary": summary,
        "ledger": ServerControlCheckpointer(ckpt_dir).read_ledger(),
        "killed_at_round": killed_at,
        "restart_rc": rc,
        "server_log": log_path,
    }


def ledger_schedule(ledger: List[Dict]) -> List[Tuple[int, Tuple[int, ...]]]:
    """The parity projection: (round, cohort) pairs — what the resumed
    run must replay identically to the unkilled reference."""
    return [(int(r["round"]), tuple(r["cohort"] or ())) for r in ledger]


# ---------------------------------------------------------------------------
def _smoke(tmp_root: Optional[str],
           obs_dir: Optional[str] = None,
           checkpoint_sync: bool = False) -> int:
    import tempfile
    root = tmp_root or tempfile.mkdtemp(prefix="fedml_failover_smoke_")
    ref_dir = os.path.join(root, "reference")
    kill_dir = os.path.join(root, "killed")
    t0 = time.time()
    # unkilled reference over the same TCP topology. Default mode is the
    # ASYNC checkpoint writer, so every smoke exercises replay-from-an-
    # older-boundary recovery; --checkpoint_sync pins the legacy
    # snapshot-at-every-boundary leg.
    ref_model, ref_ledger, _ = run_simulated_failover(
        ref_dir, rounds=6, crash_at_round=10**9, backend="TCP",
        port_base=40210, deadline_s=5.0,
        checkpoint_sync=checkpoint_sync)
    # the kill leg records a flight log when asked (--obs_dir): both
    # SIGKILL server lives append under distinct epochs — the CI lane
    # then runs `obs merge --ledger` against exactly this log
    res = run_failover_scenario(kill_dir, rounds=6, kill_after_round=2,
                                port_base=40230, deadline_s=2.0,
                                obs_dir=obs_dir,
                                checkpoint_sync=checkpoint_sync)
    ok = (res["summary"].get("done") is True
          and res["summary"].get("cp_counters", {}).get("restores", 0) >= 1
          and ledger_schedule(res["ledger"]) == ledger_schedule(ref_ledger))
    print(json.dumps({
        "server_failover_smoke": "ok" if ok else "FAILED",
        "elapsed_s": round(time.time() - t0, 1),
        "killed_at_round": res["killed_at_round"],
        "rounds_completed": res["summary"].get("rounds_completed"),
        "cp_restores": res["summary"].get("cp_counters",
                                          {}).get("restores"),
        "ledger_matches_reference": ledger_schedule(res["ledger"])
        == ledger_schedule(ref_ledger),
    }))
    return 0 if ok else 1


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser("fedml_tpu server-failover harness")
    p.add_argument("--role", choices=["server", "smoke"], default="smoke")
    p.add_argument("--smoke", action="store_true",
                   help="run the SIGKILL cpu-smoke scenario and exit "
                        "non-zero unless the schedule recovered")
    p.add_argument("--rounds", type=int, default=DEFAULT_ROUNDS)
    p.add_argument("--workers", type=int, default=DEFAULT_WORKERS)
    p.add_argument("--port_base", type=int, default=40110)
    p.add_argument("--ckpt_dir", type=str, default=None)
    p.add_argument("--deadline_s", type=float, default=2.0)
    p.add_argument("--min_quorum_frac", type=float, default=0.5)
    p.add_argument("--pace", action="store_true")
    p.add_argument("--join_rate_limit", type=float, default=0.0)
    p.add_argument("--checkpoint_sync", action="store_true",
                   help="force the legacy synchronous snapshot-at-every-"
                        "boundary checkpointing (default: async writer "
                        "thread with newest-wins coalescing)")
    p.add_argument("--obs_dir", type=str, default=None,
                   help="flight-recorder directory (fedml_tpu/obs) for "
                        "the server incarnation(s)")
    args = p.parse_args(argv)
    if args.smoke:
        args.role = "smoke"  # the documented invocation wins over --role
    if args.role == "server":
        if not args.ckpt_dir:
            p.error("--role server requires --ckpt_dir")
        return serve(args.rounds, args.workers, args.port_base,
                     args.ckpt_dir, deadline_s=args.deadline_s,
                     min_quorum_frac=args.min_quorum_frac, pace=args.pace,
                     join_rate_limit=args.join_rate_limit,
                     obs_dir=args.obs_dir,
                     checkpoint_sync=args.checkpoint_sync)
    return _smoke(args.ckpt_dir, obs_dir=args.obs_dir,
                  checkpoint_sync=args.checkpoint_sync)


if __name__ == "__main__":
    sys.exit(main())
