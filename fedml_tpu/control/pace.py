"""Adaptive pace steering — deadlines and quorum targets from observed
straggler behavior (Bonawitz et al., MLSys 2019 §4.2).

The PR-5 fault-tolerance layer runs a *static* schedule:
``--round_deadline_s`` and ``--min_quorum_frac`` are fixed numbers that
ignore the straggler distribution the server actually observes. Set the
deadline too tight and healthy silos get evicted every round; too loose
and one straggler stretches every round to the worst case.
:class:`PaceSteerer` closes the loop: it feeds on the
``SiloLivenessTable``'s sliding report-latency window
(``utils/watchdog.SlidingQuantileTracker`` — the time from a round's
broadcast to each silo's reply) and derives

- **the next round's deadline**: ``quantile(q) * margin`` (default
  p90 · 1.5), clamped to ``[min_deadline_s, max_deadline_s]`` (default
  base/4 .. base·4) so a burst of anomalous samples can never collapse
  the deadline to zero or stretch it unboundedly;
- **the next round's quorum fraction**: the 25th percentile of recent
  per-round report fractions minus a slack (default 0.1), clamped to
  ``[quorum_floor, QUORUM_CEIL]`` — when every silo reliably reports the
  target tightens toward the full barrier; when 30% of the fleet flaps
  it relaxes toward the caller's floor. The fraction ceiling alone
  cannot prevent the single-straggler deadlock (``ceil(0.9·n) == n``
  for every fleet of 10 or fewer), so the deadline server additionally
  caps the *effective* requirement at ``live - 1`` silos whenever
  steering is active (``handle_round_timeout``) — a steered schedule
  never demands every live silo on a multi-silo fleet.

Until ``min_samples`` observations exist both knobs return the caller's
static values — with steering off (the default) behavior is
byte-identical to the static flags. The steerer's windows are part of
the server control-plane snapshot (``state()`` / ``load_state()``), so a
restored server steers from the SAME evidence as the unkilled one.

**Window semantics (churn hardening).** The latency evidence is a
bounded fleet-wide sliding window (``SlidingQuantileTracker``, default
128 observations): every accepted observation stays until 128 newer ones
push it out, so any burst of outliers inflates the p90 — and therefore
the steered deadline — for up to a full window width. Three guards keep
churn from poisoning the schedule:

1. **rejoin-resync replies are excluded** at the observation site
   (``fedavg_cross_silo.handle_message_receive_model_from_client``): a
   silo resynced mid-round reports ``broadcast -> reply`` latency that
   measures its OUTAGE plus the resync detour, not its report pace. A
   flap burst produces a burst of exactly these; they are skipped and
   counted (``cp_resync_latency_skips``). Regression-tested with an
   injected flap burst (tests/test_wan.py).
2. **the clamp** bounds any residual excursion to
   ``[min_deadline_s, max_deadline_s]`` (default base/4 .. base*4) — a
   poisoned window can never stretch the deadline unboundedly.
3. **recovery is automatic**: excluded-or-not, the window is sliding —
   once healthy reports resume, 128 of them restore the steady-state
   quantiles; nothing is latched.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

#: steered quorum never demands more than this fraction — a full-barrier
#: (1.0) target would deadlock on the first permanently-dead silo
QUORUM_CEIL = 0.95


def interpolated_quantile(values: List[float], q: float) -> float:
    """Linear-interpolation quantile (numpy's default method), dependency
    free so the watchdog tracker can share it."""
    if not values:
        raise ValueError("quantile of an empty window")
    s = sorted(values)
    if len(s) == 1:
        return float(s[0])
    idx = q * (len(s) - 1)
    lo = int(idx)
    frac = idx - lo
    hi = min(lo + 1, len(s) - 1)
    return float(s[lo] * (1.0 - frac) + s[hi] * frac)


class PaceSteerer:
    def __init__(self, base_deadline_s: float,
                 quantile: float = 0.9, margin: float = 1.5,
                 min_deadline_s: Optional[float] = None,
                 max_deadline_s: Optional[float] = None,
                 min_samples: int = 4,
                 quorum_floor: float = 0.5,
                 quorum_slack: float = 0.1,
                 window: int = 32):
        if base_deadline_s is None or base_deadline_s <= 0:
            raise ValueError("pace steering needs a positive base "
                             "deadline (--round_deadline_s) to fall back "
                             f"on; got {base_deadline_s!r}")
        if not 0.0 < quantile <= 1.0:
            raise ValueError(f"quantile must be in (0, 1], got {quantile}")
        if not 0.0 < quorum_floor <= QUORUM_CEIL:
            raise ValueError(f"quorum_floor must be in (0, {QUORUM_CEIL}], "
                             f"got {quorum_floor}")
        self.base_deadline_s = float(base_deadline_s)
        self.quantile = float(quantile)
        self.margin = float(margin)
        self.min_deadline_s = (float(min_deadline_s)
                               if min_deadline_s is not None
                               else self.base_deadline_s / 4.0)
        self.max_deadline_s = (float(max_deadline_s)
                               if max_deadline_s is not None
                               else self.base_deadline_s * 4.0)
        if self.min_deadline_s > self.max_deadline_s:
            raise ValueError(
                f"min_deadline_s {self.min_deadline_s} > max_deadline_s "
                f"{self.max_deadline_s}")
        self.min_samples = max(1, int(min_samples))
        self.quorum_floor = float(quorum_floor)
        self.quorum_slack = float(quorum_slack)
        #: per-round fraction of live silos that reported before the close
        self._report_fracs: deque = deque(maxlen=int(window))

    # -- evidence -----------------------------------------------------------
    def observe_round(self, reported: int, live: int) -> None:
        """Record one closed round's participation (reported / live)."""
        self._report_fracs.append(min(1.0, reported / max(1, live)))

    # -- the two steered knobs ----------------------------------------------
    def next_deadline(self, latencies) -> float:
        """``latencies`` is a SlidingQuantileTracker (or anything with
        ``count()``/``quantile(q)``). Below ``min_samples`` the static
        base deadline rules — steering never extrapolates from nothing."""
        if latencies is None or latencies.count() < self.min_samples:
            return self.base_deadline_s
        q = latencies.quantile(self.quantile)
        return min(self.max_deadline_s,
                   max(self.min_deadline_s, q * self.margin))

    def next_quorum_frac(self) -> float:
        if len(self._report_fracs) < self.min_samples:
            return self.quorum_floor
        p25 = interpolated_quantile(list(self._report_fracs), 0.25)
        return min(QUORUM_CEIL,
                   max(self.quorum_floor, p25 - self.quorum_slack))

    # -- checkpoint plumbing ------------------------------------------------
    def state(self) -> Dict:
        return {"report_fracs": [float(f) for f in self._report_fracs]}

    def load_state(self, state: Optional[Dict]) -> None:
        if not state:
            return
        self._report_fracs.clear()
        self._report_fracs.extend(float(f)
                                  for f in state.get("report_fracs", ()))
