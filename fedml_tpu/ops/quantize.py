"""Block-scaled int8 quantization kernels for comm compression.

Cross-silo federated rounds ship full model deltas over DCN/WAN; the
reference ships them as full-precision pickled tensors (reference:
mpi_send_thread.py:27, or JSON float lists for mobile — fedavg/utils.py:12).
Here deltas are compressed 4x with per-block int8 quantization + stochastic
rounding (unbiased: E[q] = x, so FedAvg's weighted mean stays unbiased).

The kernel is pure arithmetic — random bits are generated outside with
``jax.random.bits`` and streamed in — so the identical kernel runs under the
Pallas interpreter on the CPU test mesh and compiled on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLOCK = 512     # values per scale block (4 lanes of 128)
_TILE_R = 32    # row tile; int8 min sublane tile on TPU


def _quant_kernel(x_ref, rand_ref, vals_ref, scales_ref):
    x = x_ref[:]
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    scaled = x / scale
    # stochastic rounding: floor + Bernoulli(frac) using uniform [0,1) bits
    u = (rand_ref[:] >> jnp.uint32(8)).astype(jnp.float32) * (2.0 ** -24)
    low = jnp.floor(scaled)
    q = low + (u < (scaled - low)).astype(jnp.float32)
    q = jnp.clip(q, -127.0, 127.0)
    vals_ref[:] = q.astype(jnp.int8)
    scales_ref[:] = jnp.broadcast_to(scale, scales_ref.shape)


def _dequant_kernel(vals_ref, scales_ref, out_ref):
    out_ref[:] = vals_ref[:].astype(jnp.float32) * scales_ref[:, :1]


def _pad_rows(d: int) -> tuple[int, int]:
    rows = -(-d // BLOCK)
    return rows, -rows % _TILE_R


@functools.partial(jax.jit, static_argnames=("interpret",))
def quantize_int8(x: jax.Array, key: jax.Array, *,
                  interpret: bool = False) -> tuple[jax.Array, jax.Array]:
    """Quantize a flat float vector to (int8 values, per-block f32 scales).

    Returns ``values [D]`` and ``scales [ceil(D/BLOCK)]``. Zero-padding in the
    last block quantizes to zero, so dequantize+slice round-trips exactly.
    """
    (d,) = x.shape
    rows, row_pad = _pad_rows(d)
    xp = jnp.pad(x.astype(jnp.float32), (0, rows * BLOCK - d))
    xp = jnp.pad(xp.reshape(rows, BLOCK), ((0, row_pad), (0, 0)))
    rp = rows + row_pad
    rand = jax.random.bits(key, (rp, BLOCK), jnp.uint32)

    vals, scales = pl.pallas_call(
        _quant_kernel,
        grid=(rp // _TILE_R,),
        in_specs=[
            pl.BlockSpec((_TILE_R, BLOCK), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_R, BLOCK), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_TILE_R, BLOCK), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_R, 128), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rp, BLOCK), jnp.int8),
            jax.ShapeDtypeStruct((rp, 128), jnp.float32),
        ],
        interpret=interpret,
    )(xp, rand)
    return vals.reshape(-1)[:d], scales[:rows, 0]


@functools.partial(jax.jit, static_argnames=("d", "interpret"))
def dequantize_int8(values: jax.Array, scales: jax.Array, d: int, *,
                    interpret: bool = False) -> jax.Array:
    """Inverse of :func:`quantize_int8` — returns the ``[d]`` f32 vector."""
    rows, row_pad = _pad_rows(d)
    vp = jnp.pad(values, (0, rows * BLOCK - d)).reshape(rows, BLOCK)
    vp = jnp.pad(vp, ((0, row_pad), (0, 0)))
    sp = jnp.pad(scales, (0, row_pad))
    rp = rows + row_pad
    sp = jnp.broadcast_to(sp[:, None], (rp, 128))

    out = pl.pallas_call(
        _dequant_kernel,
        grid=(rp // _TILE_R,),
        in_specs=[
            pl.BlockSpec((_TILE_R, BLOCK), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_R, 128), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_TILE_R, BLOCK), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rp, BLOCK), jnp.float32),
        interpret=interpret,
    )(vp, sp)
    return out.reshape(-1)[:d]


def quantize_tree(tree, key, *, interpret: bool = False):
    """Quantize a parameter pytree; returns ``(values, scales, spec)``.

    ``spec`` carries the treedef + leaf shapes/dtypes needed to rebuild; the
    (values, scales) pair is what goes on the wire — 4x smaller than f32.
    """
    leaves, treedef = jax.tree.flatten(tree)
    flat = jnp.concatenate([l.reshape(-1).astype(jnp.float32)
                            for l in leaves])
    vals, scales = quantize_int8(flat, key, interpret=interpret)
    spec = (treedef, [(l.shape, l.dtype.name) for l in leaves], flat.size)
    return vals, scales, spec


def dequantize_tree(values, scales, spec, *, interpret: bool = False):
    """Rebuild the pytree from :func:`quantize_tree` output."""
    treedef, leaf_meta, d = spec
    flat = dequantize_int8(values, scales, d, interpret=interpret)
    out, off = [], 0
    for shape, dtype in leaf_meta:
        size = 1
        for s in shape:
            size *= s
        out.append(flat[off:off + size].reshape(shape).astype(dtype))
        off += size
    return jax.tree.unflatten(treedef, out)
