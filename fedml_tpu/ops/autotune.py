"""Shape-aware attention autotuning with a persistent decision cache.

Round 5 showed why a fixed hand-picked Pallas block shape cannot carry the
transformer perf claim: the 128x128 flash-attention kernel measured 1.376x
OVER the XLA reference attention in one chip window and 0.70x / 0.895x
UNDER it in the next two (VERDICT r5 "What's weak" #1). The winner depends
on the dispatched shape and the chip, so it must be *measured*, not
presumed — and measured once, because tuning on a tunnel-windowed chip
budget is itself expensive.

This module provides that measurement and its memoization:

* :func:`autotune_attention` — for one attention shape
  ``(seq_len, head_dim, num_heads, batch, dtype, causal)``, time a small
  grid of
  Pallas ``(block_q, block_k)`` candidates AND the XLA reference
  attention (the same fwd+bwd payload for every candidate), pick the
  fastest, and persist the decision.
* :class:`AutotuneCache` — an on-disk JSON map
  ``{device_kind}/{shape key} -> decision`` under a configurable cache
  dir, so a later *process* (the next launcher on the same window, or the
  next window on the same chip) skips tuning entirely.
* :func:`make_autotuned_attention` — an ``attn_fn`` drop-in for
  :class:`fedml_tpu.models.transformer.TransformerLM` (and the sequence-
  parallel local attention) that resolves the decision lazily per shape at
  trace time and dispatches the winner. When no decision exists and tuning
  is unavailable (CPU backend, or ``FEDML_TPU_AUTOTUNE=0``), it dispatches
  the XLA reference — the implementation that never silently loses.

Knobs (documented in README "Autotuning & persistent caches"):
``FEDML_TPU_AUTOTUNE_CACHE`` — cache dir (default
``~/.cache/fedml_tpu``); delete ``attention_autotune.json`` inside it to
re-tune. ``FEDML_TPU_AUTOTUNE=0`` — never time candidates; cached
decisions still apply, unseen shapes fall back to the XLA reference.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

try:
    import fcntl
except ImportError:  # non-POSIX: writes stay atomic, merges best-effort
    fcntl = None

CACHE_DIR_ENV = "FEDML_TPU_AUTOTUNE_CACHE"
AUTOTUNE_ENV = "FEDML_TPU_AUTOTUNE"
CACHE_FILENAME = "attention_autotune.json"

#: (block_q, block_k) candidates; entries not dividing seq_len are dropped
#: per shape. 128 multiples: the MXU is 128x128 and the r4/r5 bench sweeps
#: never saw a sub-128 block win on chip.
DEFAULT_BLOCK_GRID: Tuple[Tuple[int, int], ...] = (
    (128, 128), (256, 128), (128, 256), (256, 256), (512, 256))

#: timing protocol for the default measure: median of ``_TIME_REPEATS``
#: timed runs after ``_TIME_WARMUP`` warmups (the first call compiles).
_TIME_WARMUP = 1
_TIME_REPEATS = 3

# measure(label, attn_fn) -> seconds; lower is better. attn_fn has the
# attn contract (q, k, v, causal=...) -> out.
Measure = Callable[[str, Callable], float]


@dataclasses.dataclass(frozen=True)
class AttentionDecision:
    """The memoized outcome of tuning one attention shape."""

    impl: str                    # "pallas" | "xla"
    block_q: Optional[int] = None   # set iff impl == "pallas"
    block_k: Optional[int] = None
    source: str = "tuned"        # "tuned" | "cache" | "default"
    timings: Optional[Dict[str, float]] = None  # label -> seconds

    def to_json(self) -> dict:
        out = {"impl": self.impl}
        if self.impl == "pallas":
            out["block_q"] = self.block_q
            out["block_k"] = self.block_k
        if self.timings:
            out["timings"] = {k: round(v, 9) for k, v in
                              self.timings.items()}
        return out

    @classmethod
    def from_json(cls, obj: dict, source: str = "cache"
                  ) -> "AttentionDecision":
        return cls(impl=obj["impl"], block_q=obj.get("block_q"),
                   block_k=obj.get("block_k"), source=source,
                   timings=obj.get("timings"))

    @property
    def label(self) -> str:
        if self.impl == "pallas":
            return f"pallas_{self.block_q}x{self.block_k}"
        return "xla"


def attention_key(seq_len: int, head_dim: int, num_heads: int,
                  dtype, causal: bool, batch: int = 1) -> str:
    """Shape key: everything the winner can depend on except the chip
    (the device kind is the cache's outer key). Batch is part of the
    dispatched shape — a winner tuned at one batch must not be silently
    served at another."""
    import jax.numpy as jnp
    return (f"s{seq_len}_d{head_dim}_h{num_heads}_b{batch}_"
            f"{jnp.dtype(dtype).name}_{'causal' if causal else 'full'}")


def device_kind() -> str:
    """Cache namespace: the accelerator model (``'cpu'`` on the host
    backend, so interpret-mode decisions can never leak onto a chip)."""
    import jax
    if jax.default_backend() == "cpu":
        return "cpu"
    return jax.devices()[0].device_kind.replace(" ", "_")


def tuning_enabled() -> bool:
    """``FEDML_TPU_AUTOTUNE=0`` turns off candidate *timing* (cached
    decisions still apply; unseen shapes fall back to XLA)."""
    return os.environ.get(AUTOTUNE_ENV, "1").lower() not in (
        "0", "false", "off")


class AutotuneCache:
    """On-disk JSON decision cache: ``{device_kind}/{shape_key} -> row``.

    One file (``attention_autotune.json``) under the cache dir; writes are
    atomic (tmp + rename) so concurrent launchers can only ever read a
    complete file. A fresh instance re-reads from disk, which is exactly
    the second-process-skips-tuning contract the tests pin down.
    """

    def __init__(self, cache_dir: Optional[str] = None):
        cache_dir = (cache_dir or os.environ.get(CACHE_DIR_ENV)
                     or os.path.join(os.path.expanduser("~"), ".cache",
                                     "fedml_tpu"))
        self.cache_dir = cache_dir
        self.path = os.path.join(cache_dir, CACHE_FILENAME)
        self._entries: Optional[Dict[str, dict]] = None

    def _load(self) -> Dict[str, dict]:
        if self._entries is None:
            try:
                with open(self.path) as f:
                    loaded = json.load(f)
                self._entries = loaded if isinstance(loaded, dict) else {}
            except (OSError, ValueError):
                self._entries = {}
        return self._entries

    def get(self, key: str) -> Optional[AttentionDecision]:
        row = self._load().get(key)
        if not isinstance(row, dict) or "impl" not in row:
            return None
        return AttentionDecision.from_json(row, source="cache")

    @contextlib.contextmanager
    def _write_lock(self):
        """Serialize read-merge-replace cycles across processes (flock on
        a sidecar, so readers never block and the data file itself stays
        atomically replaced)."""
        if fcntl is None:
            yield
            return
        with open(self.path + ".lock", "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)

    def put(self, key: str, decision: AttentionDecision) -> None:
        # merge-on-write UNDER the lock: re-read the file so entries
        # written by concurrent launchers since our last read survive
        # (last writer wins per KEY, not per file — a whole-file
        # overwrite from a stale memo would erase other processes' tuned
        # decisions and re-pay their tuning cost next window), and hold
        # the lock across read->replace so no writer lands in between
        os.makedirs(self.cache_dir, exist_ok=True)
        with self._write_lock():
            self._entries = None
            entries = self._load()
            entries[key] = decision.to_json()
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(entries, f, indent=2, sort_keys=True)
            os.replace(tmp, self.path)

    def clear(self) -> None:
        self._entries = {}
        try:
            os.remove(self.path)
        except OSError:
            pass


_DEFAULT_CACHE: Optional[AutotuneCache] = None


def default_cache() -> AutotuneCache:
    """Process-wide cache singleton, re-resolved whenever the env-derived
    dir changes — including back to the default when the env var is
    UNSET (constructing the throwaway instance does no I/O)."""
    global _DEFAULT_CACHE
    current = AutotuneCache()
    if _DEFAULT_CACHE is None or _DEFAULT_CACHE.cache_dir != \
            current.cache_dir:
        _DEFAULT_CACHE = current
    return _DEFAULT_CACHE


def block_candidates(seq_len: int,
                     grid: Optional[Sequence[Tuple[int, int]]] = None
                     ) -> Tuple[Tuple[int, int], ...]:
    """Grid entries whose blocks evenly divide ``seq_len`` (the kernel's
    grid requires ``s % block == 0``; its min(block, s) clamp only helps
    when s < block, in which case the clamped pair must still divide)."""
    out = []
    for bq, bk in (grid or DEFAULT_BLOCK_GRID):
        cq, ck = min(bq, seq_len), min(bk, seq_len)
        if seq_len % cq == 0 and seq_len % ck == 0 and (cq, ck) not in out:
            out.append((cq, ck))
    return tuple(out)


def _candidate_attn(impl: str, block_q: Optional[int],
                    block_k: Optional[int], interpret: bool):
    """Build the attn-contract callable for one candidate."""
    if impl == "xla":
        from fedml_tpu.parallel.sequence import reference_attention
        return reference_attention

    def pallas_attn(q, k, v, causal: bool = True):
        from fedml_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal, block_q, block_k, interpret)
    return pallas_attn


def _default_measure(batch: int, seq_len: int, num_heads: int,
                     head_dim: int, dtype, causal: bool) -> Measure:
    """Time the candidate on the training payload: one fwd+bwd of the bare
    attention op at the exact shape (custom-VJP kernels included), median
    of ``_TIME_REPEATS`` after a compile warmup."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(batch, seq_len, num_heads, head_dim),
                           dtype) for _ in range(3))

    def measure(label: str, attn_fn) -> float:
        @jax.jit
        def step(q, k, v):
            def loss(q):
                return jnp.sum(attn_fn(q, k, v, causal=causal)
                               .astype(jnp.float32) ** 2)
            return jax.grad(loss)(q)

        for _ in range(_TIME_WARMUP):
            jax.block_until_ready(step(q, k, v))
        times = []
        for _ in range(_TIME_REPEATS):
            t0 = time.perf_counter()
            jax.block_until_ready(step(q, k, v))
            times.append(time.perf_counter() - t0)
        times.sort()
        return times[len(times) // 2]

    return measure


def autotune_attention(seq_len: int, head_dim: int, num_heads: int = 1,
                       batch: int = 1, dtype=None, causal: bool = True, *,
                       cache: Optional[AutotuneCache] = None,
                       grid: Optional[Sequence[Tuple[int, int]]] = None,
                       measure: Optional[Measure] = None,
                       interpret: Optional[bool] = None,
                       refresh: bool = False) -> AttentionDecision:
    """Resolve (cache) or measure (tune) the winner for one shape.

    ``measure(label, attn_fn) -> seconds`` is injectable: tests pass a
    fake timer for determinism, bench.py passes the full LM-train-step
    timer so the decision it records is the one its tokens/s claim is
    made from. ``refresh=True`` re-times even on a cache hit (the bench's
    mode: fresh evidence every window, never a stale decision hiding a
    regression).

    Returns the decision; tuned decisions are persisted through ``cache``.
    """
    import jax
    import jax.numpy as jnp

    dtype = jnp.dtype(dtype or jnp.float32)
    cache = cache or default_cache()
    key = (device_kind() + "/"
           + attention_key(seq_len, head_dim, num_heads, dtype, causal,
                           batch=batch))
    enabled = tuning_enabled()
    if not refresh or not enabled:
        hit = cache.get(key)
        if hit is not None:
            return hit
    if not enabled:
        # the documented kill switch beats everything, including an
        # injected timer and refresh=True: FEDML_TPU_AUTOTUNE=0 means
        # NEVER time candidates (cached decisions above still apply)
        return AttentionDecision(impl="xla", source="default")

    candidates = block_candidates(seq_len, grid)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if measure is None:
        # no injected timer: real timing is only meaningful on a real
        # accelerator with at least one Pallas candidate in the race —
        # otherwise fall back to the XLA reference (not persisted: a
        # later process WITH a chip should still get to tune this shape)
        if interpret or not candidates:
            return AttentionDecision(impl="xla", source="default")
        measure = _default_measure(batch, seq_len, num_heads, head_dim,
                                   dtype, causal)

    timings: Dict[str, float] = {}
    best_t = timings["xla"] = measure(
        "xla", _candidate_attn("xla", None, None, interpret))
    best = AttentionDecision(impl="xla")
    for bq, bk in candidates:
        label = f"pallas_{bq}x{bk}"
        t = timings[label] = measure(
            label, _candidate_attn("pallas", bq, bk, interpret))
        if t < best_t:
            best_t = t
            best = AttentionDecision(impl="pallas", block_q=bq, block_k=bk)
    decision = dataclasses.replace(best, source="tuned", timings=timings)
    cache.put(key, decision)
    return decision


def make_autotuned_attention(*, cache: Optional[AutotuneCache] = None,
                             grid: Optional[Sequence[Tuple[int, int]]] = None,
                             measure: Optional[Measure] = None,
                             interpret: Optional[bool] = None):
    """``attn_fn`` factory: auto-selected attention, decision per shape.

    The returned callable reads only static metadata from its operands
    (shape, dtype, the ``causal`` flag), so it is safe to call with
    tracers inside jit/shard_map: a cache miss tunes eagerly at trace
    time on concrete self-generated inputs, and the in-process memo makes
    every retrace free. Unseen shapes where tuning is unavailable (CPU
    backend without an injected ``measure``, or ``FEDML_TPU_AUTOTUNE=0``)
    dispatch the XLA reference — the never-silently-slower fallback.
    """
    import jax

    memo: Dict[str, AttentionDecision] = {}

    def attn(q, k, v, causal: bool = True):
        b, s, h, d = q.shape
        run_interpret = (jax.default_backend() == "cpu"
                         if interpret is None else interpret)
        key = attention_key(s, d, h, q.dtype, causal, batch=b)
        decision = memo.get(key)
        if decision is None:
            decision = autotune_attention(
                s, d, num_heads=h, batch=b, dtype=q.dtype, causal=causal,
                cache=cache, grid=grid, measure=measure,
                interpret=run_interpret)
            memo[key] = decision
        if decision.impl == "pallas":
            from fedml_tpu.ops.flash_attention import flash_attention
            return flash_attention(q, k, v, causal, decision.block_q,
                                   decision.block_k, run_interpret)
        from fedml_tpu.parallel.sequence import reference_attention
        return reference_attention(q, k, v, causal=causal)

    return attn
