"""Magnitude top-k sparsification for the cross-silo wire (DGC-style).

Deep Gradient Compression (Lin et al., 2018) ships only the largest-
magnitude entries of the model delta; QSGD-style stochastic int8
quantization (ops/quantize.py) compresses the survivors further. Top-k is
a BIASED compressor, so the un-sent remainder must be fed back: the caller
accumulates the returned ``residual`` into the next round's delta before
compressing again (EF-SGD, Karimireddy et al., 2019) — with that loop the
compressed federation still converges to the uncompressed fixed point.

All kernels operate on the same flat f32 layout ``quantize_tree`` uses
(leaves concatenated in treedef order), so sparsify -> quantize composes
without a second flatten. ``k`` and ``d`` are static: one lowering per
(model size, keep fraction), shared by every round of a run.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.ops.quantize import dequantize_int8, quantize_int8


def k_for(d: int, frac: float) -> int:
    """Survivor count for a ``d``-entry delta at keep-fraction ``frac``
    (ceil, clamped to [1, d] so degenerate tiny models still send)."""
    if not 0.0 < frac <= 1.0:
        raise ValueError(f"topk fraction {frac} outside (0, 1]")
    return max(1, min(d, math.ceil(d * frac)))


@functools.partial(jax.jit, static_argnames=("k",))
def topk_sparsify(x: jax.Array, k: int):
    """Keep the ``k`` largest-|x| entries of a flat ``[d]`` vector.

    Returns ``(idx int32[k], vals f32[k], residual f32[d])`` where
    ``residual`` is ``x`` with the selected entries zeroed — exactly the
    mass the wire does NOT carry, to be error-fed into the next delta.
    """
    _, idx = jax.lax.top_k(jnp.abs(x), k)
    vals = x[idx]
    residual = x.at[idx].set(0.0)
    return idx.astype(jnp.int32), vals, residual


def topk_sparsify_reference(x, k: int):
    """Pure-numpy parity oracle for :func:`topk_sparsify`.

    ``jax.lax.top_k`` selects by descending value and breaks ties by
    LOWEST index first; a stable descending argsort over ``|x|``
    reproduces exactly that order, so indices, values, and the EF
    residual must all match the jitted path bit-for-bit — the contract
    the codec parity tests pin. This path is the oracle only (host
    numpy, no donation, no jit): the wire always rides the jitted
    kernels."""
    x = np.asarray(x, np.float32)
    k = max(1, min(int(x.size), int(k)))
    idx = np.argsort(-np.abs(x), kind="stable")[:k].astype(np.int32)
    vals = x[idx]
    residual = x.copy()
    residual[idx] = 0.0
    return idx, vals, residual


def _donate_flat_input() -> bool:
    """Donate the flat delta buffer only where XLA implements donation
    (tpu/gpu aliasing); the CPU backend warns-and-copies, so tests under
    JAX_PLATFORMS=cpu run the identical program without the donation."""
    return jax.default_backend() in ("tpu", "gpu")


@functools.lru_cache(maxsize=None)
def _donated_topk_sparsify(k: int, donate: bool):
    def fn(x):
        return topk_sparsify(x, k)
    return jax.jit(fn, donate_argnums=((0,) if donate else ()))


def topk_sparsify_donated(x: jax.Array, k: int):
    """:func:`topk_sparsify` with the input buffer donated to the
    computation (the residual reuses the delta's memory on tpu/gpu —
    the flat delta is a freshly built temporary at every call site, so
    the aliasing is free bandwidth). Same compiled program otherwise:
    bit-exact with :func:`topk_sparsify` and the numpy reference."""
    return _donated_topk_sparsify(int(k), _donate_flat_input())(x)


@functools.lru_cache(maxsize=None)
def _donated_topk_quantize(k: int, interpret: bool, donate: bool):
    def fn(x, key):
        return topk_quantize(x, key, k, interpret=interpret)
    return jax.jit(fn, donate_argnums=((0,) if donate else ()))


def topk_quantize_donated(x: jax.Array, key: jax.Array, k: int, *,
                          interpret: bool = False):
    """:func:`topk_quantize` with the flat input donated (see
    :func:`topk_sparsify_donated`) — the uplink encode's steady-state
    entry point."""
    return _donated_topk_quantize(int(k), bool(interpret),
                                  _donate_flat_input())(x, key)


@functools.partial(jax.jit, static_argnames=("d",))
def topk_densify(idx: jax.Array, vals: jax.Array, d: int) -> jax.Array:
    """Scatter sparse ``(idx, vals)`` back to a dense ``[d]`` f32 vector."""
    return jnp.zeros((d,), jnp.float32).at[idx].set(
        vals.astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def topk_quantize(x: jax.Array, key: jax.Array, k: int, *,
                  interpret: bool = False):
    """Sparsify then int8-quantize the survivors (the uplink hot path).

    Returns ``(idx int32[k], q int8[k], scales f32[ceil(k/BLOCK)],
    residual f32[d])``. The residual charges BOTH error sources: the
    dropped entries keep their full value, and each kept entry carries its
    quantization error ``val - dequant(q)`` — so the error-feedback loop
    sees the exact wire-vs-truth gap, not just the sparsification part.
    """
    idx, vals, residual = topk_sparsify(x, k)
    q, scales = quantize_int8(vals, key, interpret=interpret)
    deq = dequantize_int8(q, scales, k, interpret=interpret)
    residual = residual.at[idx].add(vals - deq)
    return idx, q, scales, residual


@functools.partial(jax.jit, static_argnames=("d", "interpret"))
def topk_dequantize(idx: jax.Array, q: jax.Array, scales: jax.Array,
                    d: int, *, interpret: bool = False) -> jax.Array:
    """Inverse of :func:`topk_quantize` — dense ``[d]`` f32 rebuild."""
    k = q.shape[0]
    vals = dequantize_int8(q, scales, k, interpret=interpret)
    return topk_densify(idx, vals, d)


# -- static-analysis hook (fedml_tpu.analysis layer 2) ----------------------
from fedml_tpu.analysis.registry import AuditSpec, hot_entry_point  # noqa: E402

_AUDIT_D, _AUDIT_K = 4096, 128


@hot_entry_point("ops.topk_quantize_fwd")
def _audit_topk_quantize() -> AuditSpec:
    """The uplink compression hot path (sparsify + int8-quantize the
    survivors), swept over two rounds' worth of deltas at the same
    (d, k): every round of a run must hit the one compiled program."""

    def fn(x, key):
        return topk_quantize(x, key, _AUDIT_K, interpret=True)

    sweep = [(jax.random.normal(jax.random.key(i), (_AUDIT_D,),
                                jnp.float32), jax.random.key(100 + i))
             for i in range(2)]
    return AuditSpec(fn=fn, sweep=sweep, max_lowerings=1)


@hot_entry_point("ops.topk_dequant_rebuild")
def _audit_topk_dequant() -> AuditSpec:
    """The server-side rebuild path (dequantize survivors + scatter to the
    dense delta), swept over two payloads of the same (d, k)."""

    def fn(idx, q, scales):
        return topk_dequantize(idx, q, scales, _AUDIT_D, interpret=True)

    def payload(i):
        x = jax.random.normal(jax.random.key(i), (_AUDIT_D,), jnp.float32)
        idx, q, scales, _ = topk_quantize(x, jax.random.key(200 + i),
                                          _AUDIT_K, interpret=True)
        return (idx, q, scales)

    return AuditSpec(fn=fn, sweep=[payload(i) for i in range(2)],
                     max_lowerings=1)
