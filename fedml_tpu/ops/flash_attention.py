"""Flash attention as a Pallas TPU kernel (forward) with a blockwise VJP.

The transformer path's hot op. The naive attention in
``parallel/sequence.reference_attention`` materializes the [S, S] score
matrix in HBM; this kernel streams K/V blocks through VMEM with the online
softmax (running max / denominator in VMEM scratch), so memory is
O(block_q x block_k) and the matmuls land on the MXU at [block, head_dim]
granularity.

Layout: [B, S, H, D] like the rest of the framework; internally the kernel
runs on a (B*H) x q-block x k-block grid. The k-block axis is the
innermost, sequential grid dimension on TPU, so the scratch accumulators
carry across k steps and the output block is finalized at the last k step.

Backward: a ``jax.custom_vjp`` whose residuals are (q, k, v, out, lse);
gradients are computed blockwise with ``lax.scan`` over k blocks (standard
FlashAttention-2 recurrence — dS = P * (dP - rowsum(dO * O))). Each scan
step materializes [B, H, S, block_k] score/probability tensors, so
backward memory is O(S x block_k) — never the full [S, S] matrix, but a
weaker bound than the forward kernel's O(block_q x block_k) VMEM tiles; a
hand-written backward kernel can close that gap later if long-context
training (rather than inference) becomes the bottleneck.

``flash_attention(..., interpret=True)`` runs the identical kernel through
the Pallas interpreter for CPU tests; ``make_flash_attention`` returns an
``attn_fn`` drop-in for :class:`fedml_tpu.models.transformer.TransformerLM`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, causal: bool, scale: float,
                block_q: int, block_k: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def visible():
        # [block_q, D] x [block_k, D]^T on the MXU, f32 accumulation
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                       # [bq, bk]
        l_scr[:] = l_prev * corr + p.sum(axis=1, keepdims=True)
        m_scr[:] = m_new
        v = v_ref[0].astype(jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # whole k block in this q block's future -> skip all compute
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(visible)
    else:
        visible()

    @pl.when(kj == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)           # [bq, 1]


def _fwd_pallas(q, k, v, causal: bool, block_q: int, block_k: int,
                interpret: bool):
    b, s, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    bq, bk = min(block_q, s), min(block_k, s)
    if s % bq or s % bk:
        raise ValueError(
            f"block sizes ({bq},{bk}) must evenly divide seq len {s}")
    # [B, S, H, D] -> [B*H, S, D]
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_q=bq, block_k=bk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            # trailing singleton keeps the block 2-D-tileable on TPU
            pl.BlockSpec((1, bq, 1), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            _vmem_scratch((bq, 1)),
            _vmem_scratch((bq, 1)),
            _vmem_scratch((bq, d)),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return (out.reshape(b, h, s, d).transpose(0, 2, 1, 3),
            lse.reshape(b, h, s))  # lse [B*H, S, 1] -> [B, H, S]


def _vmem_scratch(shape):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """softmax(QK^T/sqrt(d) [+ causal mask]) V for [B, S, H, D] inputs."""
    out, _ = _fwd_pallas(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _fwd_pallas(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    b, s, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    bk = min(block_k, s)
    nk = s // bk

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    # D_i = rowsum(dO * O)  [B, H, S]
    delta = jnp.einsum("bshd,bshd->bhs", dof, out.astype(jnp.float32))
    qpos = jnp.arange(s)

    def kblock(carry, j):
        dq_acc = carry
        ks = jax.lax.dynamic_slice_in_dim(k, j * bk, bk, 1).astype(
            jnp.float32)
        vs = jax.lax.dynamic_slice_in_dim(v, j * bk, bk, 1).astype(
            jnp.float32)
        sblk = jnp.einsum("bqhd,bkhd->bhqk", qf, ks) * scale
        if causal:
            kpos = j * bk + jnp.arange(bk)
            sblk = jnp.where((qpos[:, None] >= kpos[None, :])[None, None],
                             sblk, _NEG_INF)
        p = jnp.exp(sblk - lse[..., None])           # [B,H,S,bk]
        dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vs)
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bkhd->bqhd", ds, ks)
        dk_j = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
        dv_j = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
        return dq_acc, (dk_j, dv_j)

    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        kblock, jnp.zeros_like(qf), jnp.arange(nk))
    dk = dk_blocks.transpose(1, 0, 2, 3, 4).reshape(b, s, h, d)
    dv = dv_blocks.transpose(1, 0, 2, 3, 4).reshape(b, s, h, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def make_flash_attention(block_q: int = 128, block_k: int = 128,
                         interpret: bool = False):
    """attn_fn factory for TransformerLM: (q, k, v, causal=...) -> out."""
    def attn(q, k, v, causal: bool = True):
        return flash_attention(q, k, v, causal, block_q, block_k, interpret)
    return attn
