"""Flash attention as a Pallas TPU kernel (forward) with a blockwise VJP.

The transformer path's hot op. The naive attention in
``parallel/sequence.reference_attention`` materializes the [S, S] score
matrix in HBM; this kernel streams K/V blocks through VMEM with the online
softmax (running max / denominator in VMEM scratch), so memory is
O(block_q x block_k) and the matmuls land on the MXU at [block, head_dim]
granularity.

Layout: [B, S, H, D] like the rest of the framework; internally the kernel
runs on a (B*H) x q-block x k-block grid. The k-block axis is the
innermost, sequential grid dimension on TPU, so the scratch accumulators
carry across k steps and the output block is finalized at the last k step.

Backward: a ``jax.custom_vjp`` whose residuals are (q, k, v, out, lse),
computed by two Pallas kernels with the FlashAttention-2 recurrence
(dS = P * (dP - rowsum(dO * O)), P recomputed from the saved lse — the
[S, S] score matrix is never materialized):

- dK/dV kernel: grid (B*H, k-block, q-block), q innermost sequential, so
  the [block_k, D] accumulators live in VMEM scratch across the q sweep;
- dQ kernel: grid (B*H, q-block, k-block), k innermost, [block_q, D]
  accumulator in scratch.

Both match the forward's O(block_q x block_k) VMEM bound, so long-context
*training* keeps the memory win; the extra recompute of S is the standard
FA-2 trade (one more QK^T matmul on the MXU instead of an HBM-resident
probability tensor). Causal runs skip fully-masked blocks on both grids.

``flash_attention(..., interpret=True)`` runs the identical kernel through
the Pallas interpreter for CPU tests; ``make_flash_attention`` returns an
``attn_fn`` drop-in for :class:`fedml_tpu.models.transformer.TransformerLM`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, causal: bool, scale: float,
                block_q: int, block_k: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def visible():
        # [block_q, D] x [block_k, D]^T on the MXU, f32 accumulation
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                       # [bq, bk]
        l_scr[:] = l_prev * corr + p.sum(axis=1, keepdims=True)
        m_scr[:] = m_new
        v = v_ref[0].astype(jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # whole k block in this q block's future -> skip all compute
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(visible)
    else:
        visible()

    @pl.when(kj == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)           # [bq, 1]


def _fwd_pallas(q, k, v, causal: bool, block_q: int, block_k: int,
                interpret: bool):
    b, s, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    bq, bk = min(block_q, s), min(block_k, s)
    if s % bq or s % bk:
        raise ValueError(
            f"block sizes ({bq},{bk}) must evenly divide seq len {s}")
    # [B, S, H, D] -> [B*H, S, D]
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_q=bq, block_k=bk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            # trailing singleton keeps the block 2-D-tileable on TPU
            pl.BlockSpec((1, bq, 1), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            _vmem_scratch((bq, 1)),
            _vmem_scratch((bq, 1)),
            _vmem_scratch((bq, d)),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return (out.reshape(b, h, s, d).transpose(0, 2, 1, 3),
            lse.reshape(b, h, s))  # lse [B*H, S, 1] -> [B, H, S]


def _vmem_scratch(shape):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """softmax(QK^T/sqrt(d) [+ causal mask]) V for [B, S, H, D] inputs."""
    out, _ = _fwd_pallas(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _fwd_pallas(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _bwd_block_grads(q, k, v, do, lse, delta, causal, scale,
                     qi, kj, block_q, block_k):
    """Recompute P for one (q-block, k-block) tile and return (p, ds).

    Shared by both backward kernels; all operands are f32 VMEM tiles."""
    s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if causal:
        qpos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kpos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(qpos >= kpos, s, _NEG_INF)
    p = jnp.exp(s - lse)                             # [bq, bk]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    return p, ds


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                     scale: float, block_q: int, block_k: int):
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def visible():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        p, ds = _bwd_block_grads(
            q, k_ref[0].astype(jnp.float32), v_ref[0].astype(jnp.float32),
            do, lse_ref[0], delta_ref[0], causal, scale, qi, kj,
            block_q, block_k)
        # P^T dO and dS^T Q: [bq, bk] x [bq, D] contracted over bq -> [bk, D]
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # this k block is in every row of the q block's future -> skip
        pl.when(qi * block_q + block_q - 1 >= kj * block_k)(visible)
    else:
        visible()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr, *, causal: bool, scale: float,
                   block_q: int, block_k: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def visible():
        k = k_ref[0].astype(jnp.float32)
        _, ds = _bwd_block_grads(
            q_ref[0].astype(jnp.float32), k, v_ref[0].astype(jnp.float32),
            do_ref[0].astype(jnp.float32), lse_ref[0], delta_ref[0],
            causal, scale, qi, kj, block_q, block_k)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(visible)
    else:
        visible()

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd(causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    b, s, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    bq, bk = min(block_q, s), min(block_k, s)

    to_bh = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, s, d)  # noqa: E731
    qt, kt, vt, dot_ = to_bh(q), to_bh(k), to_bh(v), to_bh(do)
    # D_i = rowsum(dO * O): elementwise+reduce, XLA fuses it — no kernel
    delta = jnp.sum(dot_.astype(jnp.float32) *
                    to_bh(out).astype(jnp.float32), axis=-1, keepdims=True)
    lse_t = lse.reshape(b * h, s, 1)

    row = pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0))
    col = pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0))
    row_s = pl.BlockSpec((1, bq, 1), lambda bh, i, j: (bh, i, 0))
    # dk/dv grid: (BH, k-block, q-block) — program ids are (bh, j, i)
    rowT = pl.BlockSpec((1, bq, d), lambda bh, j, i: (bh, i, 0))
    colT = pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0))
    rowT_s = pl.BlockSpec((1, bq, 1), lambda bh, j, i: (bh, i, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk),
        grid=(b * h, s // bk, s // bq),
        in_specs=[rowT, colT, colT, rowT, rowT_s, rowT_s],
        out_specs=[colT, colT],
        out_shape=[jax.ShapeDtypeStruct((b * h, s, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, s, d), v.dtype)],
        scratch_shapes=[_vmem_scratch((bk, d)), _vmem_scratch((bk, d))],
        interpret=interpret,
    )(qt, kt, vt, dot_, lse_t, delta)

    dq, = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk),
        grid=(b * h, s // bq, s // bk),
        in_specs=[row, col, col, row, row_s, row_s],
        out_specs=[row],
        out_shape=[jax.ShapeDtypeStruct((b * h, s, d), q.dtype)],
        scratch_shapes=[_vmem_scratch((bq, d))],
        interpret=interpret,
    )(qt, kt, vt, dot_, lse_t, delta)

    from_bh = lambda t: t.reshape(b, h, s, d).transpose(0, 2, 1, 3)  # noqa: E731
    return from_bh(dq), from_bh(dk), from_bh(dv)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def make_flash_attention(block_q=128, block_k=128, interpret: bool = False,
                         autotune_cache=None):
    """attn_fn factory for TransformerLM: (q, k, v, causal=...) -> out.

    ``block_q="auto"`` (or ``block_k="auto"``) returns the shape-aware
    auto-selected attention instead of a fixed-block kernel: per dispatched
    shape, :mod:`fedml_tpu.ops.autotune` times the Pallas block grid
    against the XLA reference, memoizes the winner on disk
    (``autotune_cache`` or the env-configured default), and dispatches it —
    so no shape ever runs the slower path on the strength of a hand-picked
    constant.
    """
    if block_q == "auto" or block_k == "auto":
        from fedml_tpu.ops.autotune import make_autotuned_attention
        return make_autotuned_attention(cache=autotune_cache,
                                        interpret=interpret or None)

    def attn(q, k, v, causal: bool = True):
        return flash_attention(q, k, v, causal, block_q, block_k, interpret)
    return attn


# -- static-analysis hook (fedml_tpu.analysis layer 2) ----------------------
from fedml_tpu.analysis.registry import AuditSpec, hot_entry_point  # noqa: E402


@hot_entry_point("ops.flash_attention_fwd_bwd")
def _audit_flash_fwd_bwd() -> AuditSpec:
    """Forward + backward through the Pallas kernel's custom VJP (the
    transformer path's hot op), traced in interpret mode so the audit
    runs on the CPU CI backend. grad_path=True: a float upcast sneaking
    into the FA-2 recurrence (e.g. an accidental f32->f64 promotion in
    the lse/delta math) fails here."""
    import numpy as np

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True,
                               interpret=True).sum()

    fwd_bwd = jax.value_and_grad(loss, argnums=(0, 1, 2))
    rng = np.random.RandomState(0)
    qkv = tuple(jnp.asarray(rng.randn(1, 128, 2, 32), jnp.float32)
                for _ in range(3))
    # two equivalent arg tuples (fresh strong-typed f32 arrays) — the
    # kernel's signature must not depend on call-site identity
    qkv2 = tuple(jnp.asarray(np.asarray(a), jnp.float32) for a in qkv)
    return AuditSpec(fn=fwd_bwd, sweep=[qkv, qkv2],
                     max_lowerings=1, grad_path=True)
