"""Fused weighted client aggregation as a Pallas TPU kernel.

The FedAvg server update is ``w_global = sum_i n_i * w_i / sum_i n_i``
(reference: FedAVGAggregator.py:72-80). With client updates stacked as a
``[C, D]`` matrix this is a ``[1, C] @ [C, D]`` matvec — exactly the shape the
MXU wants — so the whole aggregation is one kernel pass over HBM instead of a
per-leaf Python loop. The kernel tiles D into VMEM-sized lanes and keeps the
tiny weight vector resident.

CPU/test path: ``interpret=True`` runs the same kernel through the Pallas
interpreter; ``weighted_mean_flat_reference`` is the jnp oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# lane tile for the parameter axis; multiple of 128 (TPU lane width) and
# small enough that [C, TILE_D] fits VMEM for any realistic clients-per-round
_TILE_D = 2048


def _wmean_kernel(w_ref, x_ref, out_ref):
    # w: [1, C], x: [C, TILE_D] -> out: [1, TILE_D]; rides the MXU
    out_ref[:] = jnp.dot(w_ref[:], x_ref[:],
                         preferred_element_type=jnp.float32)


def weighted_mean_flat_reference(stacked: jax.Array,
                                 weights: jax.Array) -> jax.Array:
    """jnp oracle: sample-weighted mean over axis 0 of ``[C, D]``."""
    w = weights.astype(jnp.float32)
    w = w / jnp.sum(w)
    return jnp.einsum("c,cd->d", w, stacked.astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def weighted_mean_flat(stacked: jax.Array, weights: jax.Array,
                       *, interpret: bool = False) -> jax.Array:
    """Sample-weighted mean over the client axis of a ``[C, D]`` stack.

    Returns a ``[D]`` float32 vector. ``weights`` are the per-client sample
    counts ``n_i``; normalization by ``sum(n_i)`` is folded into the weight
    vector so the kernel is a single matvec.
    """
    c, d = stacked.shape
    w = weights.astype(jnp.float32)
    w = (w / jnp.sum(w)).reshape(1, c)

    pad = (-d) % _TILE_D
    if pad:
        stacked = jnp.pad(stacked, ((0, 0), (0, pad)))
    dp = d + pad

    out = pl.pallas_call(
        _wmean_kernel,
        grid=(dp // _TILE_D,),
        in_specs=[
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((c, _TILE_D), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, _TILE_D), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, dp), jnp.float32),
        interpret=interpret,
    )(w, stacked)
    return out[0, :d]


def tree_weighted_mean_pallas(stacked_tree, weights, *,
                              interpret: bool = False):
    """Pytree front-end: ravel all leaves into one ``[C, D]`` matrix, run the
    fused kernel once, and unravel.

    Drop-in for :func:`fedml_tpu.core.pytree.tree_weighted_mean` — one kernel
    launch for the whole model instead of one reduction per leaf, which is the
    difference between a bandwidth-bound single pass and dozens of tiny
    dispatches for deep models (ResNet-56 has 250+ leaves).
    """
    leaves, treedef = jax.tree.flatten(stacked_tree)
    c = leaves[0].shape[0]
    sizes = [leaf[0].size for leaf in leaves]
    shapes = [leaf.shape[1:] for leaf in leaves]
    flat = jnp.concatenate(
        [leaf.reshape(c, -1).astype(jnp.float32) for leaf in leaves], axis=1)
    mean = weighted_mean_flat(flat, weights, interpret=interpret)
    out, off = [], 0
    for size, shape, leaf in zip(sizes, shapes, leaves):
        out.append(mean[off:off + size].reshape(shape).astype(leaf.dtype))
        off += size
    return jax.tree.unflatten(treedef, out)
