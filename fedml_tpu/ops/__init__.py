"""Pallas TPU kernels for the framework's hot ops.

The reference's server hot path is a host-side Python loop over ``state_dict``
keys (reference: fedml_api/distributed/fedavg/FedAVGAggregator.py:58-87) and
its comm payloads are full-precision pickled tensors (reference:
fedml_core/distributed/communication/mpi/mpi_send_thread.py:27). Here the two
corresponding device-side primitives are hand-tiled Pallas kernels:

- :mod:`fedml_tpu.ops.aggregate` — fused sample-weighted client aggregation
  (the FedAvg server rule) over a ``[clients, params]`` stack, tiled so the
  weighted reduction rides the MXU.
- :mod:`fedml_tpu.ops.quantize` — int8 block-scaled quantization with
  stochastic rounding for cross-silo model-delta compression.
- :mod:`fedml_tpu.ops.flash_attention` — streaming-softmax attention for
  the transformer path (VMEM-blocked K/V, causal block skipping), with a
  blockwise custom VJP.
- :mod:`fedml_tpu.ops.autotune` — shape-aware selection between the
  Pallas kernel's (block_q, block_k) grid and the XLA reference
  attention, memoized in an on-disk per-device-kind cache so neither
  tuning nor a losing kernel is ever paid twice.

Every kernel has an ``interpret=True`` path so the math is testable on the
CPU mesh, and a pure-jnp reference used both as the CPU fallback and as the
test oracle.
"""

from fedml_tpu.ops.aggregate import (tree_weighted_mean_pallas,
                                     weighted_mean_flat,
                                     weighted_mean_flat_reference)
from fedml_tpu.ops.autotune import (AttentionDecision, AutotuneCache,
                                    autotune_attention,
                                    make_autotuned_attention)
from fedml_tpu.ops.flash_attention import (flash_attention,
                                           make_flash_attention)
from fedml_tpu.ops.quantize import (dequantize_int8, dequantize_tree,
                                    quantize_int8, quantize_tree)

__all__ = [
    "weighted_mean_flat",
    "weighted_mean_flat_reference",
    "tree_weighted_mean_pallas",
    "quantize_int8",
    "dequantize_int8",
    "quantize_tree",
    "dequantize_tree",
    "flash_attention",
    "make_flash_attention",
    "AttentionDecision",
    "AutotuneCache",
    "autotune_attention",
    "make_autotuned_attention",
]
