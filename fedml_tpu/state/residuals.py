"""Per-silo EF-residual history on the client-state store API.

PR 4 checkpointed each silo's error-feedback residual with a private
:class:`~fedml_tpu.utils.checkpoint.CheckpointManager` under
``checkpoint_dir/silo_<rank>/`` — one msgpack blob + json sidecar per
round. That layout is a per-client-state store in miniature; this module
re-homes it on :class:`~fedml_tpu.state.store.ClientStateStore` (field
``"residual"``, keyed by the ROUND index — the store keys by integer id
and does not care that the integer means "round" here), which buys the
shared atomic-writeback/LRU/counter machinery and retires the bespoke
flax serialization for a flat f32 array.

**Backward compatibility is a hard contract**: a silo resumed against a
PR-4-era directory must restore its residual float-for-float. ``load``
therefore falls back to reading the legacy ``round_<r>`` msgpack layout
when the store has no entry for the round (tested: resume-parity against
artifacts written by the old manager).
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Optional

import numpy as np

from fedml_tpu.state.store import ClientStateStore, StoreFlusher

#: rounds of residual history kept, matching the legacy manager's
#: ``keep_last_n`` default (older rounds are GC'd at save)
KEEP_LAST_N = 3

#: residual history is tiny (one entry per retained round) — one shard
#: file per save keeps write-back O(entry), not O(history)
_SHARD_ROUNDS = 4


class SiloResidualStore:
    def __init__(self, state_dir: str, keep_last_n: int = KEEP_LAST_N,
                 timer=None, async_writeback: bool = False):
        self.state_dir = state_dir
        self.keep_last_n = int(keep_last_n)
        self._store = ClientStateStore(state_dir,
                                       shard_clients=_SHARD_ROUNDS,
                                       cache_clients=_SHARD_ROUNDS
                                       * (self.keep_last_n + 1),
                                       timer=timer)
        self._store.register_field("residual", persist=True)
        #: async write-back (writer-thread flush off the save() caller's
        #: critical path, depth-1 coalesced). Crash semantics unchanged:
        #: shard writes stay individually atomic, and a lost in-flight
        #: flush is convergence-safe — the EF resume path falls back to
        #: zeros, it never reads a torn file. ``close()`` is the durable
        #: barrier (FINISH-time parity with the old inline flush).
        self._flusher = (StoreFlusher(self._store,
                                      name="silo-state-flusher")
                         if async_writeback else None)

    def save(self, round_idx: int, residual: np.ndarray) -> None:
        """Persist the residual entering ``round_idx`` (same
        rounds-completed keying as the server's model checkpoint, so
        restore-at-resumed-round lines both up), GC'ing history beyond
        ``keep_last_n`` — both the store's own and any legacy files."""
        self._store.put("residual", round_idx,
                        np.asarray(residual, dtype=np.float32))
        for old in self._store.known_ids("residual"):
            if old <= round_idx - self.keep_last_n:
                self._store.delete("residual", old)
        if self._flusher is not None:
            self._flusher.request()
        else:
            self._store.flush()
        self._gc_legacy(round_idx)

    def flush(self, timeout: float = 60.0) -> None:
        """Durability barrier: every ``save`` so far is on disk after
        this returns (async mode waits out the writer thread; sync mode
        is already durable)."""
        if self._flusher is not None:
            self._flusher.barrier(timeout=timeout)
        self._store.flush()

    def close(self, timeout: float = 60.0) -> None:
        """Flush-and-stop (silo FINISH). Safe to call twice; after close
        further ``save`` calls degrade to inline synchronous flushes."""
        if self._flusher is not None:
            self._flusher.close(timeout=timeout)
        else:
            self._store.flush()

    def writeback_stats(self) -> Optional[dict]:
        """Writer-thread counters (None in synchronous mode) — the
        bench's write-back evidence row."""
        return None if self._flusher is None else self._flusher.stats()

    def load(self, round_idx: int, dim: int) -> Optional[np.ndarray]:
        """The residual checkpointed for ``round_idx``, or None when no
        layout (new or legacy) holds one — the caller's zeros fallback is
        convergence-safe (EF re-loses pending mass, never corrupts)."""
        try:
            arr = self._store.get("residual", round_idx)
            if arr.shape != (dim,):
                logging.warning(
                    "residual checkpoint for round %d has shape %s, "
                    "expected (%d,) — model changed since the "
                    "checkpoint; starting error feedback from zero",
                    round_idx, arr.shape, dim)
                return None
            return np.asarray(arr, dtype=np.float32)
        except KeyError:
            return self._load_legacy(round_idx, dim)

    # -- PR-4 layout (CheckpointManager: msgpack blob + json sidecar) ------
    def _legacy_path(self, round_idx: int) -> str:
        return os.path.join(self.state_dir, f"round_{round_idx:08d}")

    def _load_legacy(self, round_idx: int, dim: int) -> Optional[np.ndarray]:
        path = self._legacy_path(round_idx)
        if not (os.path.exists(path) and os.path.exists(path + ".json")):
            return None
        import flax.serialization

        with open(path, "rb") as f:
            state = flax.serialization.from_bytes(
                {"residual": np.zeros(dim, np.float32)}, f.read())
        logging.info("restored legacy (PR-4 layout) residual checkpoint "
                     "%s", path)
        return np.asarray(state["residual"], dtype=np.float32)

    def _gc_legacy(self, round_idx: int) -> None:
        """A migrated silo keeps writing rounds forward; its stale legacy
        files would otherwise live forever. Same retention window."""
        try:
            names = sorted(os.listdir(self.state_dir))
        except FileNotFoundError:
            return
        for fn in names:
            if not fn.startswith("round_"):
                continue
            try:
                r = int(fn.split(".")[0].split("_")[1])
            except (IndexError, ValueError):
                continue
            if r <= round_idx - self.keep_last_n:
                with contextlib.suppress(FileNotFoundError):
                    os.remove(os.path.join(self.state_dir, fn))

    def latest_round(self) -> Optional[int]:
        rounds = set(self._store.known_ids("residual"))
        try:
            for fn in sorted(os.listdir(self.state_dir)):
                if fn.startswith("round_") and not fn.endswith(
                        (".json", ".tmp")):
                    stem = fn.split(".")[0]
                    if os.path.exists(os.path.join(
                            self.state_dir, stem + ".json")):
                        rounds.add(int(stem.split("_")[1]))
        except (FileNotFoundError, ValueError):
            pass
        return max(rounds) if rounds else None

    def stats(self) -> dict:
        return self._store.stats()
