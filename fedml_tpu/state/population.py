"""Virtualized client populations: N=10^6 clients, O(cohort + cache) RSS.

A :class:`VirtualFederatedDataset` duck-types the parts of
:class:`fedml_tpu.data.base.FederatedDataset` the round drivers consume
(``client_num`` / ``pack_clients`` / ``client_weights`` /
``cohort_padded_len`` / the eval unions / the per-client size mapping)
WITHOUT holding any per-client Python object for the population. Client
shards are either

- **generative**: a pure function of ``(seed, client_id)`` — per-client
  sizes come from a vectorized integer-hash → Pareto transform, content
  from a per-client ``RandomState`` — so a million-client population
  costs O(1) to construct and O(cohort) per round; or
- **store-backed**: read from :class:`~fedml_tpu.state.store
  .ClientStateStore` shard files a streaming builder emitted
  (``write_federation_store``), with the one O(N) host artifact — the
  int32 sizes index — memory-mapped, not resident.

Either way the shards flow through the store's LRU tier, so repeat
cohort members hit RAM (``state_cache_hits``) and RSS is bounded by the
cache budget, not the population. ``pack_clients`` is thread-safe (the
round prefetcher packs round r+1 from a worker thread) and pins the
cohort's shards for the duration of the gather.

The module doubles as the population-scale measurement harness::

    python -m fedml_tpu.state.population --population 1000000 \
        --rounds 5 --cohort 10

runs FedAvg rounds over the virtual population IN THIS PROCESS and
prints one JSON line with rounds/sec, ``host_rss_peak_mb``, and the
store-tier counters — ``bench.py``'s ``population_scale`` stage shells
out one subprocess per population so each leg's peak-RSS high-water mark
is its own, and ``ci/run_fast.sh`` runs the 100k-client smoke.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Dict, Iterable, Iterator, Optional, Tuple

import numpy as np

from fedml_tpu.state.store import ClientStateStore

_M64 = (1 << 64) - 1


def _mix64(v: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer: uint64 -> well-mixed uint64. The
    per-client hash every size/content derivation keys on — stateless,
    so any client's draw is computable without touching the others.
    Wraparound is the algorithm, so the overflow warning is silenced."""
    with np.errstate(over="ignore"):
        v = (v + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(_M64)
        v = ((v ^ (v >> np.uint64(30)))
             * np.uint64(0xBF58476D1CE4E5B9)) & np.uint64(_M64)
        v = ((v ^ (v >> np.uint64(27)))
             * np.uint64(0x94D049BB133111EB)) & np.uint64(_M64)
        return v ^ (v >> np.uint64(31))


def client_uniform(cids, seed: int, salt: int = 0) -> np.ndarray:
    """Per-client uniform in (0, 1): hash of (seed, salt, cid)."""
    cids = np.asarray(cids, dtype=np.uint64)
    base = _mix64(np.uint64((seed * 0x5851F42D4C957F2D + salt) & _M64))
    u = _mix64(cids ^ base)
    # top 53 bits -> [0, 1); nudge off zero so Pareto's u**-1/a is finite
    return np.maximum((u >> np.uint64(11)) * (2.0 ** -53), 2.0 ** -53)


def pareto_sizes(cids, seed: int, min_samples: int = 10,
                 max_samples: int = 400,
                 alpha: float = 1.3) -> np.ndarray:
    """LEAF-style heavy-tailed per-client sample counts as a PURE function
    of (seed, client id): Pareto(xm=min_samples, alpha) by inverse CDF on
    the hashed uniform, clamped at ``max_samples``. Vectorized — sizing a
    10^6-id chunk is one hash pass, no per-client RNG objects."""
    u = client_uniform(cids, seed, salt=0x51)
    sizes = (min_samples * u ** (-1.0 / alpha)).astype(np.int64)
    return np.clip(sizes, min_samples, max_samples)


def iter_size_chunks(sizes_for, client_num: int, chunk: int = 1 << 17):
    """Walk ``[0, client_num)`` through a vectorized size function in
    fixed chunks — THE population-scan helper every consumer shares
    (dataset reductions, the lazy dict view, ``data/stats``), so the
    chunking policy and any indexing fix live in exactly one place."""
    for lo in range(0, client_num, chunk):
        yield sizes_for(np.arange(lo, min(lo + chunk, client_num)))


class _LazySizeDict:
    """Read-only ``train_data_local_num_dict`` view over a size function:
    O(1) per lookup, nothing resident. Iteration walks the full id range
    (only reached by opt-in diagnostics; the hot paths use the vectorized
    ``sizes_for``)."""

    def __init__(self, n: int, sizes_for: Callable[[np.ndarray], np.ndarray]):
        self._n = n
        self._sizes_for = sizes_for

    def __getitem__(self, cid: int) -> int:
        cid = int(cid)
        if not 0 <= cid < self._n:
            raise KeyError(cid)
        return int(self._sizes_for(np.asarray([cid]))[0])

    def __len__(self) -> int:
        return self._n

    def __iter__(self):
        return iter(range(self._n))

    def __contains__(self, cid) -> bool:
        return 0 <= int(cid) < self._n

    def keys(self):
        return range(self._n)

    def values(self) -> Iterator[int]:
        for chunk in iter_size_chunks(self._sizes_for, self._n):
            for s in chunk:
                yield int(s)

    def items(self):
        return zip(self.keys(), self.values())


class VirtualFederatedDataset:
    """A population that is sampled into existence, never resident.

    ``gen(cid) -> (x, y)`` produces a client's train shard on demand
    (None for store-backed corpora, where shards must already exist on
    disk); ``sizes_for(cids) -> int64[len(cids)]`` is the vectorized
    per-client sample count (callable, or an array/memmap indexed
    directly). Packing semantics (pad-and-mask, cohort pow-2 buckets)
    are IDENTICAL to ``FederatedDataset`` so the compiled round programs
    cannot tell the two apart.
    """

    def __init__(self, client_num: int, class_num: int,
                 sizes_for, gen: Optional[Callable] = None,
                 store: Optional[ClientStateStore] = None,
                 test_global: Optional[Tuple[np.ndarray, np.ndarray]] = None,
                 eval_clients: int = 64, eval_cap: int = 4096,
                 name: str = "virtual"):
        self.client_num = int(client_num)
        self.class_num = int(class_num)
        self.name = name
        self._sizes = sizes_for
        self.gen = gen
        self.store = store if store is not None else ClientStateStore()
        for f in ("train_x", "train_y"):
            # respect a factory's earlier persistence decision (e.g. a
            # generative population whose state_dir persists shards as a
            # cross-run cache); default: persist iff there is no
            # generator to fall back on
            if not self.store.field_registered(f):
                self.store.register_field(f, persist=gen is None)
        self._eval_clients = min(int(eval_clients), self.client_num)
        self._eval_cap = int(eval_cap)
        self._test_global = test_global
        self._train_global = None
        self._max_samples: Optional[int] = None
        self._total_samples: Optional[int] = None
        # pack_clients runs on the prefetch worker concurrently with main-
        # thread eval-union builds; the store has its own lock, this one
        # guards the dataset-level lazy caches
        self._lock = threading.Lock()
        self.train_data_local_num_dict = _LazySizeDict(self.client_num,
                                                       self.sizes_for)

    # -- sizes -------------------------------------------------------------
    def sizes_for(self, cids) -> np.ndarray:
        cids = np.asarray(cids)
        if callable(self._sizes):
            return np.asarray(self._sizes(cids), dtype=np.int64)
        if not len(cids):
            return np.zeros(0, np.int64)
        # index FIRST, convert after: a dtype-converting asarray on the
        # whole backing array would copy the full O(N) index (and read
        # the entire memmap file) on every cohort lookup
        return np.asarray(self._sizes[cids], dtype=np.int64)

    def _scan_sizes(self, reduce_fn):
        out = None
        for s in iter_size_chunks(self.sizes_for, self.client_num):
            v = reduce_fn(s)
            out = v if out is None else reduce_fn(np.asarray([out, v]))
        return out

    @property
    def max_client_samples(self) -> int:
        with self._lock:
            if self._max_samples is None:
                self._max_samples = int(self._scan_sizes(np.max))
            return self._max_samples

    @property
    def train_data_num(self) -> int:
        with self._lock:
            if self._total_samples is None:
                self._total_samples = int(self._scan_sizes(np.sum))
            return self._total_samples

    @property
    def test_data_num(self) -> int:
        xt, _ = self.test_data_global
        return len(xt)

    # -- padding (same formulas as data/base.py, sizes vectorized) ---------
    def padded_len(self, batch_size: Optional[int]) -> int:
        n = self.max_client_samples
        if not batch_size:
            return n
        return ((n + batch_size - 1) // batch_size) * batch_size

    def cohort_padded_len(self, client_idxs,
                          batch_size: Optional[int]) -> int:
        n = int(self.sizes_for(np.asarray(client_idxs,
                                          dtype=np.int64)).max())
        b = batch_size or 1
        nb = (n + b - 1) // b
        bucket = 1 << max(0, (nb - 1).bit_length())
        return min(bucket * b, self.padded_len(batch_size))

    # -- shards ------------------------------------------------------------
    def _client_shard(self, cid: int) -> Tuple[np.ndarray, np.ndarray]:
        """One client's (x, y) through the store tiers: RAM hit, disk
        shard read, or generated (and LRU-cached) on a full miss."""
        cid = int(cid)
        try:
            return (self.store.get("train_x", cid),
                    self.store.get("train_y", cid))
        except KeyError:
            if self.gen is None:
                raise KeyError(
                    f"store-backed population has no shard for client "
                    f"{cid} (corpus incomplete under "
                    f"{self.store.state_dir!r})") from None
        x, y = self.gen(cid)
        self.store.put("train_x", cid, x)
        self.store.put("train_y", cid, y)
        return x, y

    def pack_clients(self, client_idxs, batch_size: Optional[int] = None,
                     n_pad: Optional[int] = None):
        """Streaming cohort materialization: fetch each sampled client's
        shard through the store and place it into the padded-and-masked
        ``[P, n_pad, ...]`` round input. Memory: the cohort block plus
        whatever the LRU holds — never the population."""
        n_pad = n_pad or self.padded_len(batch_size)
        with self.store.pinned("train_x", client_idxs), \
                self.store.pinned("train_y", client_idxs):
            x0, y0 = self._client_shard(client_idxs[0])
            P = len(client_idxs)
            x = np.zeros((P, n_pad) + x0.shape[1:], dtype=x0.dtype)
            y = np.zeros((P, n_pad) + y0.shape[1:], dtype=y0.dtype)
            mask = np.zeros((P, n_pad), dtype=np.float32)
            for i, c in enumerate(client_idxs):
                cx, cy = (x0, y0) if i == 0 else self._client_shard(c)
                n = len(cx)
                if n > n_pad:
                    raise ValueError(
                        f"client {c} has {n} samples > n_pad={n_pad}")
                if n != len(cy):
                    raise ValueError(f"client {c}: {n} samples but "
                                     f"{len(cy)} labels")
                x[i, :n], y[i, :n], mask[i, :n] = cx, cy, 1.0
        return x, y, mask

    def client_weights(self, client_idxs) -> np.ndarray:
        return self.sizes_for(
            np.asarray(client_idxs, dtype=np.int64)).astype(np.float32)

    # -- eval unions (fixed seeded cohort, NOT the full population) --------
    def _eval_ids(self) -> np.ndarray:
        """Evenly strided eval cohort: deterministic, spans the size
        distribution, and independent of the per-round sampling stream."""
        stride = max(1, self.client_num // self._eval_clients)
        return np.arange(self._eval_clients, dtype=np.int64) * stride

    @property
    def train_data_global(self) -> Tuple[np.ndarray, np.ndarray]:
        """At population scale the 'global train union' is a FIXED seeded
        eval cohort's union, capped at ``eval_cap`` samples — evaluating
        10^6 clients' union would cost more than the training it
        measures (the reference subsamples evaluation the same way,
        fedavg_api.py:115)."""
        with self._lock:
            if self._train_global is None:
                xs, ys, left = [], [], self._eval_cap
                for c in self._eval_ids():
                    cx, cy = self._client_shard(int(c))
                    take = min(len(cx), left)
                    xs.append(cx[:take])
                    ys.append(cy[:take])
                    left -= take
                    if left <= 0:
                        break
                self._train_global = (np.concatenate(xs),
                                      np.concatenate(ys))
            return self._train_global

    @property
    def test_data_global(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._test_global is None:
            xg, yg = self.train_data_global
            self._test_global = (xg[:0], yg[:0])
        return self._test_global


def make_virtual_powerlaw_population(
    client_num: int = 1_000_000,
    dim: int = 32,
    class_num: int = 10,
    seed: int = 0,
    min_samples: int = 10,
    max_samples: int = 400,
    alpha: float = 1.3,
    noise: float = 1.0,
    state_dir: Optional[str] = None,
    cache_clients: int = 4096,
    test_samples: int = 1024,
    eval_clients: int = 64,
) -> VirtualFederatedDataset:
    """The virtual twin of ``make_powerlaw_blob_federated``: gaussian-blob
    clients with LEAF-style power-law sizes, at any population, generated
    client-by-client on demand. Content is a pure function of
    (seed, client id), so the same cohort packs the same bytes whether it
    was generated fresh, LRU-cached, or (with ``state_dir``) read back
    from shard files: ``state_dir`` makes the generated shards a
    PERSISTENT cross-run cache — clients a run touches are written back
    on eviction/flush, and a later run with the same ``state_dir`` reads
    them from disk instead of regenerating (bit-identical either way;
    only touched clients occupy disk, never the population)."""
    master = np.random.RandomState(seed)
    centers = master.randn(class_num, dim) * 3.0

    def sizes_for(cids):
        return pareto_sizes(cids, seed, min_samples, max_samples, alpha)

    def gen(cid: int):
        rng = np.random.RandomState(
            int(_mix64(np.asarray([cid], np.uint64)
                       ^ np.uint64(seed * 0x9E3779B9 & _M64))[0]
                % (2 ** 31 - 1)))
        n = int(sizes_for(np.asarray([cid]))[0])
        y = rng.randint(0, class_num, n).astype(np.int32)
        x = (centers[y] + noise * rng.randn(n, dim)).astype(np.float32)
        return x, y

    # held-out test union from the SAME blob distribution, disjoint stream
    trng = np.random.RandomState(seed + 9973)
    yt = trng.randint(0, class_num, test_samples).astype(np.int32)
    xt = (centers[yt] + noise * trng.randn(test_samples, dim)
          ).astype(np.float32)

    # one-client shards: generated entries are sparse over a huge id
    # space, so shard granularity must equal client granularity for the
    # cache budget to mean what the flag says (disk corpora use fat
    # shards instead — there a shard read amortizes one file open).
    # With a state_dir the generated shards persist as a cross-run
    # cache; without one they are a RAM-only LRU (regenerable content,
    # nothing ever written — the bench's O(cache)-RSS configuration)
    store = ClientStateStore(state_dir, shard_clients=1,
                             cache_clients=cache_clients)
    store.register_field("train_x", persist=state_dir is not None)
    store.register_field("train_y", persist=state_dir is not None)
    return VirtualFederatedDataset(
        client_num, class_num, sizes_for, gen=gen, store=store,
        test_global=(xt, yt), eval_clients=eval_clients,
        name=f"virtual_powerlaw_{client_num}")


# -- store-backed corpora (streaming builders write, this loads) -----------
def write_federation_store(
    state_dir: str,
    stream: Iterable[Tuple[int, Tuple[np.ndarray, np.ndarray],
                           Optional[Tuple[np.ndarray, np.ndarray]]]],
    class_num: int,
    shard_clients: int = 256,
    cache_clients: int = 1024,
) -> int:
    """Consume a streaming builder — ``(cid, (xtr, ytr), (xte, yte) |
    None)`` per client — into shard files + a memory-mapped sizes index.
    Peak memory is O(cache), never O(population): the store's LRU
    write-back flushes full shards to disk as the stream advances.
    Returns the client count."""
    import os

    store = ClientStateStore(state_dir, shard_clients=shard_clients,
                             cache_clients=cache_clients)
    for f in ("train_x", "train_y", "test_x", "test_y"):
        store.register_field(f, persist=True)
    sizes = []
    n = 0
    for cid, (xtr, ytr), test in stream:
        if cid != n:
            # sizes.npy is indexed BY CLIENT ID at load time; an
            # out-of-order or gapped stream would silently misalign
            # every weight and pad bound downstream
            raise ValueError(
                f"write_federation_store requires a dense in-order "
                f"stream: expected client {n}, got {cid}")
        store.put("train_x", cid, np.ascontiguousarray(xtr))
        store.put("train_y", cid, np.ascontiguousarray(ytr))
        if test is not None and len(test[0]):
            store.put("test_x", cid, np.ascontiguousarray(test[0]))
            store.put("test_y", cid, np.ascontiguousarray(test[1]))
        sizes.append(len(xtr))
        n += 1
    store.flush()
    np.save(os.path.join(state_dir, "sizes.npy"),
            np.asarray(sizes, dtype=np.int32))
    with open(os.path.join(state_dir, "meta.json"), "w") as f:
        json.dump({"client_num": n, "class_num": int(class_num),
                   "shard_clients": shard_clients}, f)
    return n


def load_federation_store(state_dir: str, cache_clients: int = 4096,
                          eval_clients: int = 64) -> VirtualFederatedDataset:
    """Open a corpus ``write_federation_store`` emitted: shards stay on
    disk behind the LRU, the sizes index is an mmap (the one O(N) file is
    not resident), the test union is the eval cohort's stored test
    shards."""
    import os

    with open(os.path.join(state_dir, "meta.json")) as f:
        meta = json.load(f)
    sizes = np.load(os.path.join(state_dir, "sizes.npy"), mmap_mode="r")
    store = ClientStateStore(state_dir,
                             shard_clients=meta.get("shard_clients", 256),
                             cache_clients=cache_clients)
    ds = VirtualFederatedDataset(
        meta["client_num"], meta["class_num"], sizes, gen=None,
        store=store, eval_clients=eval_clients,
        name=f"store:{os.path.basename(os.path.normpath(state_dir))}")
    # test union: the eval cohort's held-out shards, read once
    xs, ys = [], []
    for c in ds._eval_ids():
        try:
            xs.append(store.get("test_x", int(c)))
            ys.append(store.get("test_y", int(c)))
        except KeyError:
            continue  # single-sample clients have empty test splits
    if xs:
        ds._test_global = (np.concatenate(xs), np.concatenate(ys))
    return ds


# -- measurement harness (bench legs + CI smoke) ---------------------------
def _run_population_leg(population: int, rounds: int, cohort: int,
                        mode: str, batch_size: int, dim: int,
                        cache_clients: int, state_dir: Optional[str],
                        seed: int) -> Dict:
    """One population leg in THIS process: build the dataset, run FedAvg
    rounds, report rounds/sec + peak RSS + store counters. bench.py runs
    each leg in its own subprocess so ru_maxrss high-water marks don't
    bleed across legs."""
    import time

    import jax

    from fedml_tpu.algorithms.fedavg import FedAvgAPI, FedAvgConfig
    from fedml_tpu.models.lr import LogisticRegression
    from fedml_tpu.trainer.functional import TrainConfig
    from fedml_tpu.utils.tracing import RoundTimer

    t_build = time.perf_counter()
    vds = make_virtual_powerlaw_population(
        client_num=population, dim=dim, class_num=10, seed=seed,
        state_dir=state_dir, cache_clients=cache_clients)
    if mode == "resident":
        # the baseline leg: the IDENTICAL population materialized into
        # resident dicts (same per-client bytes, same sampling stream,
        # same packing), so the rounds/sec delta isolates the store
        # machinery — not a dataset-shape difference
        from fedml_tpu.data.base import FederatedDataset
        ds = FederatedDataset.from_client_arrays(
            {c: vds.gen(c) for c in range(population)},
            {c: None for c in range(population)}, vds.class_num)
    else:
        ds = vds
    build_s = time.perf_counter() - t_build

    api = FedAvgAPI(ds, LogisticRegression(num_classes=10),
                    config=FedAvgConfig(
                        comm_round=rounds + 1, client_num_per_round=cohort,
                        frequency_of_the_test=10 ** 9, seed=seed,
                        train=TrainConfig(epochs=1, batch_size=batch_size,
                                          lr=0.05)))
    # warm every cohort bucket shape outside the timed window (bounded:
    # <= log2 distinct shapes), same protocol as bench_powerlaw_1000
    from fedml_tpu.core.sampling import sample_clients
    warmed = set()
    for r in range(rounds + 1):
        n_pad = ds.cohort_padded_len(
            sample_clients(r, ds.client_num, cohort), batch_size)
        if n_pad not in warmed:
            warmed.add(n_pad)
            api.run_round(r)
    jax.block_until_ready(api.variables)
    t0 = time.perf_counter()
    for r in range(1, rounds + 1):
        api.run_round(r)
    jax.block_until_ready(api.variables)
    wall = time.perf_counter() - t0
    api.timer.update_rss()
    store_stats = (ds.store.stats() if hasattr(ds, "store") else {})
    sb = (store_stats.get("state_bytes_read", 0)
          + store_stats.get("state_bytes_written", 0))
    return {
        "population": population,
        "mode": mode,
        "rounds_timed": rounds,
        "rounds_per_sec": round(rounds / max(wall, 1e-9), 3),
        "build_s": round(build_s, 3),
        "host_rss_peak_mb": round(RoundTimer.host_rss_mb(), 1),
        "state_bytes_per_round": round(sb / max(1, rounds), 1),
        **store_stats,
    }


def main(argv=None) -> int:
    import argparse

    from fedml_tpu.utils import force_platform_from_env
    force_platform_from_env()

    p = argparse.ArgumentParser("python -m fedml_tpu.state.population")
    p.add_argument("--population", type=int, default=100_000)
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--cohort", type=int, default=10)
    p.add_argument("--mode", choices=["virtual", "resident"],
                   default="virtual")
    p.add_argument("--batch_size", type=int, default=10)
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--state_cache_clients", type=int, default=4096)
    p.add_argument("--state_dir", type=str, default=None)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    out = _run_population_leg(
        args.population, args.rounds, args.cohort, args.mode,
        args.batch_size, args.dim, args.state_cache_clients,
        args.state_dir, args.seed)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
