"""Tiered client-state subsystem: population >> cohort >> cache.

``store`` — the sharded, disk-backed per-client state store with an LRU
host-RAM cache (EF residuals, optimizer state, data-shard indices, data
shards). ``population`` — virtual federated datasets sampled into
existence per cohort instead of held resident. ``residuals`` — the
cross-silo EF-residual history on the store API (with the PR-4
checkpoint layout's backward-compat reader).
"""

from fedml_tpu.state.population import (VirtualFederatedDataset,
                                        load_federation_store,
                                        make_virtual_powerlaw_population,
                                        pareto_sizes,
                                        write_federation_store)
from fedml_tpu.state.residuals import SiloResidualStore
from fedml_tpu.state.store import (DEFAULT_CACHE_CLIENTS,
                                   DEFAULT_SHARD_CLIENTS, ClientStateStore,
                                   StoreFlusher)

__all__ = [
    "ClientStateStore", "DEFAULT_CACHE_CLIENTS", "DEFAULT_SHARD_CLIENTS",
    "SiloResidualStore", "StoreFlusher", "VirtualFederatedDataset",
    "load_federation_store", "make_virtual_powerlaw_population",
    "pareto_sizes", "write_federation_store",
]
