"""Tiered client-state store: disk shards + LRU host cache, O(cache) RSS.

The paper's production claim is federated training over populations of
*millions* of clients, but every per-client artifact in the repo used to
be a resident in-process Python dict (partition index maps, EF
residuals, data shards) — host memory grew linearly with population and
the largest runnable scenario was 1000 clients. Bonawitz et al.'s system
design (the pace-steering paper) holds only the *cohort* on the server
while the population lives in a selected-on-demand store; this module is
that store, as a host-side subsystem feeding the existing device
pipeline.

Three tiers:

- **disk**: per-field shard files ``<dir>/<field>/shard_<i>.npz``, each
  holding ``shard_clients`` consecutive client ids' arrays. Writes are
  atomic (tmp + ``os.replace``), so a round that dies mid-writeback
  leaves every shard either the old or the new COMPLETE version — never
  a torn file (crash-consistency contract, tested).
- **host RAM**: an LRU of loaded shards bounded by ``cache_clients``
  (budget in clients, rounded up to whole shards). Eviction writes dirty
  shards back first; the budget — not the population — is what bounds
  RSS.
- **HBM (pin tier)**: the active cohort's *packed* device arrays are the
  payload slots the round pipeline already holds (parallel/prefetch.py,
  ≤ depth cohorts in flight) — the store's job ends at handing the pack
  loop host arrays, so the device tier needs no copy of its own.
  ``pinned`` shard refcounts keep the LRU from evicting a shard mid-pack
  while a worker thread gathers from it.

Fields are namespaces ("train_x", "residual", "data_idx", ...); a field
created with ``persist=False`` is a pure RAM LRU over a generator
(``get_or_create``) — the 1M-client synthetic bench uses this so it
never writes a multi-GB corpus to disk, while still exercising the exact
cache/eviction machinery the disk-backed fields use.

Thread-safe (one RLock): the round prefetcher's worker packs cohort
r+1 from the store while the main thread closes round r.

Counters (``stats()``, mirrored into a bound
:class:`~fedml_tpu.utils.tracing.RoundTimer` as ``state_*``):
``state_cache_hits`` / ``state_cache_misses`` / ``state_evictions`` /
``state_bytes_read`` / ``state_bytes_written`` — the memory-flat bench
claim is measured from these plus ``host_rss_peak_mb``, not asserted.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
from collections import OrderedDict
from typing import Callable, Dict, Iterable, Optional, Tuple

import numpy as np

#: default LRU budget, in clients (flag: --state_cache_clients)
DEFAULT_CACHE_CLIENTS = 4096
#: default clients per shard file — small enough that one miss reads
#: ~shard_clients * per-client bytes, big enough that a cohort of
#: hundreds touches few files
DEFAULT_SHARD_CLIENTS = 256


class _Shard:
    """One resident shard: ``entries[cid] -> ndarray`` plus bookkeeping."""

    __slots__ = ("entries", "dirty", "nbytes")

    def __init__(self, entries: Dict[int, np.ndarray], dirty: bool):
        self.entries = entries
        self.dirty = dirty
        self.nbytes = sum(a.nbytes for a in entries.values())


class ClientStateStore:
    """Sharded, disk-backed per-client state with an LRU host-RAM cache.

    ``state_dir=None`` is the pure-RAM mode: every field behaves as
    ``persist=False`` (LRU over generators, nothing touches disk) —
    still bounded by ``cache_clients``, still counted.
    """

    def __init__(self, state_dir: Optional[str] = None,
                 shard_clients: int = DEFAULT_SHARD_CLIENTS,
                 cache_clients: int = DEFAULT_CACHE_CLIENTS,
                 timer=None):
        if shard_clients <= 0:
            raise ValueError(f"shard_clients must be >= 1 "
                             f"(got {shard_clients})")
        self.state_dir = state_dir
        # shard geometry is part of the on-disk format: a reader opening
        # with a different shard_clients would compute wrong shard
        # indices and report existing clients missing — so the dir
        # self-describes and an existing store.json ALWAYS wins
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)
            desc = os.path.join(state_dir, "store.json")
            if os.path.exists(desc):
                import json
                with open(desc) as f:
                    on_disk = int(json.load(f)["shard_clients"])
                if on_disk != shard_clients:
                    logging.debug(
                        "state store %s: using on-disk shard_clients=%d "
                        "(caller asked %d)", state_dir, on_disk,
                        shard_clients)
                shard_clients = on_disk
            else:
                import json
                tmp = f"{desc}.{os.getpid()}.tmp"
                with open(tmp, "w") as f:
                    json.dump({"shard_clients": int(shard_clients)}, f)
                os.replace(tmp, desc)
        self.shard_clients = int(shard_clients)
        self.cache_shards = max(
            1, -(-int(max(1, cache_clients)) // self.shard_clients))
        self._persist: Dict[str, bool] = {}
        self._shards: "OrderedDict[Tuple[str, int], _Shard]" = OrderedDict()
        #: shard-key -> pin refcount; keys are pinned whether or not the
        #: shard is resident yet, so a shard FAULTED IN during a pinned
        #: gather is protected too (at population scale nearly every
        #: cohort member is a first-touch load inside the pack loop)
        self._pins: Dict[Tuple[str, int], int] = {}
        self._lock = threading.RLock()
        self._timer = timer
        self._stats = {"state_cache_hits": 0, "state_cache_misses": 0,
                       "state_evictions": 0, "state_bytes_read": 0,
                       "state_bytes_written": 0}

    # -- field + timer plumbing -------------------------------------------
    def register_field(self, field: str, persist: bool = True) -> None:
        """Declare a field's disk behavior. Unregistered fields default to
        persist-iff-``state_dir``; ``persist=False`` keeps the field a
        RAM-only LRU over its generator (no disk writes ever)."""
        self._persist[field] = bool(persist) and self.state_dir is not None

    def field_registered(self, field: str) -> bool:
        """Whether ``register_field`` has been called for ``field`` —
        layered constructors use this to respect a factory's earlier
        persistence decision instead of overriding it."""
        return field in self._persist

    def bind_timer(self, timer) -> None:
        """Mirror every counter bump into ``timer.count('state_*')`` from
        now on (drivers bind their RoundTimer at construction) and credit
        the counts accumulated before binding, so early misses aren't
        lost to the evidence row."""
        with self._lock:
            self._timer = timer
            if timer is not None:
                for k, v in self._stats.items():
                    if v:
                        timer.count(k, v)

    def _count(self, name: str, n: int = 1) -> None:
        self._stats[name] += n
        if self._timer is not None:
            self._timer.count(name, n)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    # -- shard addressing --------------------------------------------------
    def _field_persists(self, field: str) -> bool:
        return self._persist.get(field, self.state_dir is not None)

    def _shard_path(self, field: str, shard_idx: int) -> str:
        assert self.state_dir is not None
        return os.path.join(self.state_dir, field,
                            f"shard_{shard_idx:08d}.npz")

    def _load_shard(self, field: str, shard_idx: int) -> _Shard:
        """Disk -> RAM: read one shard file (or start it empty)."""
        if self._field_persists(field):
            path = self._shard_path(field, shard_idx)
            if os.path.exists(path):
                with np.load(path) as z:
                    entries = {int(k[1:]): np.asarray(z[k]) for k in z.files}
                shard = _Shard(entries, dirty=False)
                self._count("state_bytes_read", os.path.getsize(path))
                return shard
        return _Shard({}, dirty=False)

    def _write_shard(self, field: str, shard_idx: int,
                     shard: _Shard) -> None:
        """RAM -> disk, atomically: a crash between tmp-write and replace
        leaves the previous complete version in place."""
        path = self._shard_path(field, shard_idx)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        if not shard.entries:
            # a fully-deleted shard removes its file (GC'd residual
            # history must not leave empty npz husks behind)
            with contextlib.suppress(FileNotFoundError):
                os.remove(path)
            shard.dirty = False
            return
        tmp = f"{path}.{os.getpid()}.tmp.npz"  # savez appends .npz itself
        np.savez(tmp, **{f"c{cid}": arr
                         for cid, arr in shard.entries.items()})
        os.replace(tmp, path)
        self._count("state_bytes_written", os.path.getsize(path))
        shard.dirty = False

    def _resident(self, field: str, cid: int) -> _Shard:
        """The shard holding ``cid``, loaded + LRU-promoted; evicts past
        the budget (caller holds the lock)."""
        key = (field, cid // self.shard_clients)
        shard = self._shards.get(key)
        if shard is not None:
            self._count("state_cache_hits")
            self._shards.move_to_end(key)
            return shard
        self._count("state_cache_misses")
        shard = self._load_shard(field, key[1])
        self._shards[key] = shard
        self._evict_over_budget()
        return shard

    def _evict_over_budget(self) -> None:
        """Drop least-recently-used shards past ``cache_shards``, writing
        dirty ones back first. Pinned keys are skipped (a thread is
        mid-gather on them); if everything is pinned the cache
        overshoots temporarily rather than corrupting a pack."""
        while len(self._shards) > self.cache_shards:
            victim = next((k for k in self._shards
                           if self._pins.get(k, 0) == 0), None)
            if victim is None:
                return
            shard = self._shards.pop(victim)
            if shard.dirty and self._field_persists(victim[0]):
                self._write_shard(*victim, shard)
            elif shard.dirty:
                logging.debug("state: dropping dirty non-persistent shard "
                              "%s/%d (regenerable field)", *victim)
            self._count("state_evictions")

    # -- per-client API ----------------------------------------------------
    def get(self, field: str, cid: int) -> np.ndarray:
        """Client ``cid``'s array under ``field``; KeyError if absent."""
        with self._lock:
            shard = self._resident(field, int(cid))
            try:
                return shard.entries[int(cid)]
            except KeyError:
                raise KeyError(f"state {field!r} has no client {cid}") \
                    from None

    def get_or_create(self, field: str, cid: int,
                      create: Callable[[int], np.ndarray]) -> np.ndarray:
        """``get`` with a generator fallback: an entry absent from cache
        AND disk is created by ``create(cid)`` (pure function of the id),
        cached, and — for persistent fields — written back on
        eviction/flush. This is how generative virtual populations ride
        the same LRU machinery as disk corpora."""
        cid = int(cid)
        with self._lock:
            shard = self._resident(field, cid)
            arr = shard.entries.get(cid)
            if arr is None:
                arr = create(cid)
                shard.entries[cid] = arr
                shard.nbytes += arr.nbytes
                shard.dirty = True
            return arr

    def put(self, field: str, cid: int, arr: np.ndarray) -> None:
        cid = int(cid)
        arr = np.asarray(arr)
        with self._lock:
            shard = self._resident(field, cid)
            old = shard.entries.get(cid)
            shard.nbytes += arr.nbytes - (old.nbytes if old is not None
                                          else 0)
            shard.entries[cid] = arr
            shard.dirty = True

    def delete(self, field: str, cid: int) -> bool:
        """Remove one entry (GC of round-keyed residual history). Returns
        whether it existed anywhere; an emptied persistent shard removes
        its file on write-back."""
        cid = int(cid)
        with self._lock:
            shard = self._resident(field, cid)
            old = shard.entries.pop(cid, None)
            if old is not None:
                shard.nbytes -= old.nbytes
                shard.dirty = True
                return True
            return False

    def known_ids(self, field: str) -> Iterable[int]:
        """Every client id present for ``field``, cache AND disk (scans
        shard files without loading arrays — directory metadata only for
        unloaded shards' ids via a header read)."""
        with self._lock:
            seen = set()
            for (f, _), shard in self._shards.items():
                if f == field:
                    seen.update(shard.entries)
            if self._field_persists(field):
                import re
                fdir = os.path.join(self.state_dir, field)
                if os.path.isdir(fdir):
                    for fn in sorted(os.listdir(fdir)):
                        # exact-name match so a crash's stray
                        # shard_*.npz.<pid>.tmp.npz is never parsed
                        m = re.fullmatch(r"shard_(\d+)\.npz", fn)
                        if not m:
                            continue
                        idx = int(m.group(1))
                        if (field, idx) in self._shards:
                            continue  # resident copy is authoritative
                        with np.load(os.path.join(fdir, fn)) as z:
                            seen.update(int(k[1:]) for k in z.files)
            return sorted(seen)

    @contextlib.contextmanager
    def pinned(self, field: str, cids):
        """Pin the shard KEYS covering ``cids`` for the duration
        (refcounted): the pack loop holds this while gathering a cohort
        so a concurrent thread's miss can't evict a shard out from under
        the copy — including shards only faulted in partway through the
        gather (pins are on keys, not on resident shards)."""
        keys = sorted({(field, int(c) // self.shard_clients) for c in cids})
        with self._lock:
            for k in keys:
                self._pins[k] = self._pins.get(k, 0) + 1
        try:
            yield
        finally:
            with self._lock:
                for k in keys:
                    n = self._pins.get(k, 0) - 1
                    if n <= 0:
                        self._pins.pop(k, None)
                    else:
                        self._pins[k] = n
                self._evict_over_budget()

    # -- round-close / lifecycle ------------------------------------------
    def flush(self) -> int:
        """Write every dirty persistent shard back (round close). Returns
        the number of shards written. Each write is individually atomic;
        a crash mid-flush leaves a prefix of shards at the new version
        and the rest at the old — all readable."""
        written = 0
        with self._lock:
            for (field, idx), shard in list(self._shards.items()):
                if shard.dirty and self._field_persists(field):
                    self._write_shard(field, idx, shard)
                    written += 1
        return written

    def drop_cache(self) -> None:
        """Flush, then empty the RAM tier (tests + memory pressure)."""
        with self._lock:
            self.flush()
            self._shards.clear()

    def resident_clients(self) -> int:
        with self._lock:
            return sum(len(s.entries) for s in self._shards.values())

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(s.nbytes for s in self._shards.values())


class StoreFlusher:
    """Writer-thread wrapper over :meth:`ClientStateStore.flush` — the
    same pattern as ``control.checkpoint.AsyncCheckpointWriter``, for the
    state tier's round-close write-back.

    ``request()`` is the cheap half: it marks "a flush is wanted" and
    returns; the dedicated thread runs ``store.flush()`` off the round
    critical path. Requests are depth-1 coalesced — N requests while one
    flush is in flight collapse to ONE follow-up flush (newest state
    wins: ``flush`` always writes whatever is dirty NOW, so skipped
    requests lose no data). Crash consistency is unchanged from the
    synchronous path: every shard write inside ``flush`` is individually
    atomic (tmp + ``os.replace``), so a kill mid-flush leaves each shard
    old-or-new complete — the flusher only changes WHEN flushes run, not
    what a partially-applied one looks like. ``barrier()`` waits for
    everything requested so far to be durable; ``close()`` barriers,
    stops the thread, and runs one final inline flush for any dirt that
    arrived after the last request (FINISH-time semantics identical to
    the old inline call)."""

    def __init__(self, store: ClientStateStore, name: str = "state-flusher"):
        self._store = store
        self._cond = threading.Condition()
        self._requested = False
        self._stopped = False
        self._seq_submitted = 0
        self._seq_done = 0
        self.flushes = 0
        self.coalesced = 0
        self.shards_written = 0
        self.last_flush_ms = 0.0
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def request(self) -> None:
        """Ask for a flush; returns immediately. After ``close()`` the
        store is flushed inline (degrade-to-synchronous, never silent
        data loss)."""
        with self._cond:
            if not self._stopped:
                if self._requested:
                    self.coalesced += 1
                self._requested = True
                self._seq_submitted += 1
                self._cond.notify_all()
                return
        self._store.flush()

    def _run(self) -> None:
        import time
        while True:
            with self._cond:
                while not self._requested and not self._stopped:
                    self._cond.wait()
                if self._stopped and not self._requested:
                    return
                self._requested = False
                target = self._seq_submitted
            t0 = time.perf_counter()
            try:
                written = self._store.flush()
            except Exception:
                logging.exception("state flusher: flush failed")
                written = 0
            finally:
                with self._cond:
                    self.flushes += 1
                    self.shards_written += written
                    self.last_flush_ms = (time.perf_counter() - t0) * 1e3
                    self._seq_done = max(self._seq_done, target)
                    self._cond.notify_all()

    def barrier(self, timeout: float = 60.0) -> bool:
        """Block until every flush requested before this call has run."""
        with self._cond:
            target = self._seq_submitted
            return self._cond.wait_for(
                lambda: self._seq_done >= target or self._stopped,
                timeout=timeout)

    def close(self, timeout: float = 60.0) -> None:
        self.barrier(timeout=timeout)
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)
        # anything dirtied after the last request() still reaches disk
        self._store.flush()

    def stats(self) -> Dict[str, float]:
        with self._cond:
            return {"flushes": self.flushes, "coalesced": self.coalesced,
                    "shards_written": self.shards_written,
                    "last_flush_ms": self.last_flush_ms}
