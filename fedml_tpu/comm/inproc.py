"""In-process backend: a queue per rank inside one shared router.

Replaces the reference's localhost-MPI testing setup (``hostname >
mpi_host_file; mpirun -np N`` — run_fedavg_distributed_pytorch.sh:19-22) for
simulation and tests: ranks are threads, delivery is a queue hand-off of the
*same* Message object (no serialization), and there is no 0.3 s poll — the
receive loop blocks on the queue (the reference polls at
mpi/com_manager.py:78).
"""

from __future__ import annotations

import queue
import threading
from typing import Dict

from fedml_tpu.comm.base import WIRE_JOB_KEY, BaseCommunicationManager
from fedml_tpu.comm.message import Message

_STOP = object()


class InProcRouter:
    """Shared mailbox fabric for one simulated federation."""

    def __init__(self) -> None:
        self._queues: Dict[int, "queue.Queue"] = {}
        self._lock = threading.Lock()

    def mailbox(self, rank: int) -> "queue.Queue":
        with self._lock:
            if rank not in self._queues:
                self._queues[rank] = queue.Queue()
            return self._queues[rank]


class InProcCommManager(BaseCommunicationManager):
    def __init__(self, router: InProcRouter, rank: int, size: int,
                 wire_codec: bool = False):
        """``wire_codec=True`` round-trips every message through the binary
        codec (send = to_bytes, deliver = from_bytes) so protocol tests also
        exercise serialization exactly as the socket backends do."""
        super().__init__()
        self.router = router
        self.rank = rank
        self.size = size
        self.wire_codec = wire_codec
        self._inbox = router.mailbox(rank)
        self._running = False

    def send_message(self, msg: Message) -> None:
        # stamped like the socket backends: the chaos wrapper
        # (comm/faults.py) injects duplicates ABOVE this layer, and the
        # receive-side seq dedup must shed them here too
        self._stamp_seq(msg)
        if self.wire_codec:
            payload = msg.to_bytes()
            self._count_sent(len(payload),
                             msg.msg_params.get(WIRE_JOB_KEY))
        else:
            payload = msg  # object hand-off: no frame, no byte accounting
        self.router.mailbox(msg.get_receiver_id()).put(payload)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            item = self._inbox.get()
            if item is _STOP:
                break
            if isinstance(item, (bytes, bytearray)):
                n = len(item)
                item = Message.from_bytes(item)
                self._count_received(n,
                                     item.msg_params.get(WIRE_JOB_KEY))
            self._notify(item)

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(_STOP)
