"""TCP backend: length-prefixed frames over plain sockets, cross-host.

The role of the reference's gRPC backend (grpc_comm_manager.py) without its
prototype flaws (hardcoded receiver IPs at :51-56, a channel per message):
addresses come from an explicit ``{rank: (host, port)}`` map, connections are
cached per peer, and frames are the binary codec's output (serialization.py)
— so a multi-MB model update is two syscalls, not a JSON encode.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
from typing import Dict, Tuple

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import Message

_LEN = struct.Struct("<Q")
_STOP = object()
_CHUNK = 1 << 20  # per-recv_into slice; bounds kernel copy granularity


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    """Read exactly ``n`` bytes into ONE preallocated buffer.

    ``recv_into`` on memoryview slices replaces the old chunks-list +
    ``b"".join`` pattern, which held a multi-hundred-MB model frame in
    memory TWICE (the chunk list plus the joined copy) at the join point.
    """
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:got + min(n - got, _CHUNK)])
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return buf


def send_frame(sock: socket.socket, frame) -> int:
    """Write a length-prefixed frame; returns the payload byte count.

    ``frame`` is one bytes-like object OR a list of buffers (a
    ``serialization.dumps_parts`` output): parts are written straight to
    the socket, so serialization and socket I/O overlap instead of first
    materializing one contiguous frame copy.
    """
    parts = ((frame,) if isinstance(frame, (bytes, bytearray, memoryview))
             else tuple(frame))
    total = sum(len(p) for p in parts)
    sock.sendall(_LEN.pack(total))
    for p in parts:
        sock.sendall(p)
    return total


def recv_frame(sock: socket.socket) -> bytearray:
    (size,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return _recv_exact(sock, size)


class _Peer:
    """A cached outbound connection with its own I/O lock, so sends to
    different peers never serialize behind each other (or behind one slow
    connect)."""

    def __init__(self, address: Tuple[str, int]):
        self.address = address
        self.lock = threading.Lock()
        self.sock: socket.socket | None = None

    def send(self, frame) -> None:
        """``frame``: bytes-like or a parts list (see ``send_frame``)."""
        with self.lock:
            if self.sock is None:
                self.sock = socket.create_connection(self.address, timeout=30)
            try:
                send_frame(self.sock, frame)
            except OSError:
                # a failed/partial write desyncs the stream — drop the socket
                # so the next send reconnects cleanly
                try:
                    self.sock.close()
                finally:
                    self.sock = None
                raise

    def close(self) -> None:
        with self.lock:
            if self.sock is not None:
                try:
                    self.sock.close()
                except OSError:
                    pass
                self.sock = None


class TcpCommManager(BaseCommunicationManager):
    """One listening socket per rank; outbound connections cached per peer.

    Inbound frames from all connections funnel through one queue drained by
    ``handle_receive_message``, so observers run single-threaded — protocol
    state machines (e.g. the aggregator's all-received barrier) need no
    locking, same as the inproc/gRPC backends.
    """

    def __init__(self, rank: int, addresses: Dict[int, Tuple[str, int]]):
        super().__init__()
        self.rank = rank
        self.addresses = addresses
        host, port = addresses[rank]
        self._server = socket.create_server((host, port), reuse_port=False)
        self._server.listen(16)
        self._inbox: "queue.Queue" = queue.Queue()
        self._peers: Dict[int, _Peer] = {}
        self._peers_lock = threading.Lock()
        self._running = False
        self._accept_thread: threading.Thread | None = None

    def send_message(self, msg: Message) -> None:
        dest = msg.get_receiver_id()
        with self._peers_lock:  # dict access only; I/O under the peer lock
            peer = self._peers.get(dest)
            if peer is None:
                peer = self._peers[dest] = _Peer(self.addresses[dest])
        # parts, not one joined frame: a model update goes header-then-
        # buffers straight to the socket with no contiguous copy
        parts = msg.to_parts()
        peer.send(parts)
        self._count_sent(sum(len(p) for p in parts))

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while self._running:
                frame = recv_frame(conn)
                self._count_received(len(frame))
                self._inbox.put(frame)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _accept_loop(self) -> None:
        self._server.settimeout(0.5)
        while self._running:
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()
        self._server.close()

    def handle_receive_message(self) -> None:
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        while self._running:
            item = self._inbox.get()
            if item is _STOP:
                break
            self._notify(Message.from_bytes(item))

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(_STOP)
        with self._peers_lock:
            for peer in self._peers.values():
                peer.close()
            self._peers.clear()
