"""TCP backend: length-prefixed frames over plain sockets, cross-host.

The role of the reference's gRPC backend (grpc_comm_manager.py) without its
prototype flaws (hardcoded receiver IPs at :51-56, a channel per message):
addresses come from an explicit ``{rank: (host, port)}`` map, connections are
cached per peer, and frames are the binary codec's output (serialization.py)
— so a multi-MB model update is two syscalls, not a JSON encode.

Reliability: sends run under a bounded, seeded exponential-backoff
``RetryPolicy`` (comm/reliable.py) — a failed/partial write drops the
socket, reconnects, and resends the SAME stamped frame; the receive side
dedups by sequence number (comm/base.py), so a retry of a frame that DID
land is shed instead of double-delivered. Exhausted retries raise
``TransportError`` loudly — the old behavior (drop the socket, swallow the
``OSError``, hope the next send reconnects) silently lost the frame.
"""

from __future__ import annotations

import logging
import queue
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

from fedml_tpu.comm.base import WIRE_JOB_KEY, BaseCommunicationManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.reliable import RetryPolicy, TransportError, retry_call

_LEN = struct.Struct("<Q")
_STOP = object()
_CHUNK = 1 << 20  # per-recv_into slice; bounds kernel copy granularity

#: a connect attempt must not block a send slot unboundedly — failed
#: connects feed the retry loop, which owns the waiting
_CONNECT_TIMEOUT_S = 30.0

#: per-peer send-queue bound: deep enough to absorb a round's burst of
#: frames to one peer, shallow enough that a wedged peer sheds loudly
#: (overflow → TransportError → the caller's eviction path) instead of
#: buffering a round's worth of model bytes per dead silo
_SEND_QUEUE_DEPTH = 64


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    """Read exactly ``n`` bytes into ONE preallocated buffer.

    ``recv_into`` on memoryview slices replaces the old chunks-list +
    ``b"".join`` pattern, which held a multi-hundred-MB model frame in
    memory TWICE (the chunk list plus the joined copy) at the join point.
    """
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:got + min(n - got, _CHUNK)])
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return buf


def send_frame(sock: socket.socket, frame) -> int:
    """Write a length-prefixed frame; returns the payload byte count.

    ``frame`` is one bytes-like object OR a list of buffers (a
    ``serialization.dumps_parts`` output): parts are written straight to
    the socket, so serialization and socket I/O overlap instead of first
    materializing one contiguous frame copy.
    """
    parts = ((frame,) if isinstance(frame, (bytes, bytearray, memoryview))
             else tuple(frame))
    total = sum(len(p) for p in parts)
    sock.sendall(_LEN.pack(total))
    for p in parts:
        sock.sendall(p)
    return total


def recv_frame(sock: socket.socket) -> bytearray:
    (size,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return _recv_exact(sock, size)


class _SendItem:
    """One queued frame. Synchronous senders wait on ``done`` and re-raise
    ``error``; broadcast senders pass ``on_error`` instead and never wait."""

    __slots__ = ("frame", "nbytes", "job", "done", "error", "on_error",
                 "receiver")

    def __init__(self, frame, nbytes: int, job, wait: bool,
                 on_error=None, receiver=None):
        self.frame = frame
        self.nbytes = nbytes
        self.job = job
        self.done = threading.Event() if wait else None
        self.error: Optional[BaseException] = None
        self.on_error = on_error
        self.receiver = receiver


class _Peer:
    """A cached outbound connection with its own I/O lock and a bounded
    send queue drained by a dedicated writer thread: sends to different
    peers overlap, and a broadcast's round thread returns after enqueue
    instead of waiting out every peer's TCP backpressure in turn.

    Every send routes through the queue (synchronous senders block on the
    item's ``done`` event), so frames to one peer stay FIFO — a direct
    send can never jump an in-flight broadcast frame on the stream.
    """

    def __init__(self, address: Tuple[str, int], retry: RetryPolicy,
                 bump=None, on_sent=None,
                 queue_depth: int = _SEND_QUEUE_DEPTH):
        self.address = address
        self.retry = retry
        self.lock = threading.Lock()
        self.sock: socket.socket | None = None
        self._bump = bump or (lambda name, n=1, job=None: None)
        self._on_sent = on_sent or (lambda nbytes, job=None: None)
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_depth)
        self._closed = False
        self._writer = threading.Thread(
            target=self._writer_loop, daemon=True,
            name=f"tcp-writer-{address[0]}:{address[1]}")
        self._writer.start()

    def _send_once(self, frame) -> None:
        """One attempt: (re)connect if needed, write the frame. A failed
        or partial write desyncs the length-prefixed stream, so the socket
        is dropped before the error propagates — the NEXT attempt starts
        from a clean connection."""
        if self.sock is None:
            self.sock = socket.create_connection(
                self.address, timeout=_CONNECT_TIMEOUT_S)
        try:
            send_frame(self.sock, frame)
        except OSError:
            try:
                self.sock.close()
            finally:
                self.sock = None
            raise

    def send(self, frame, job=None) -> None:
        """``frame``: bytes-like or a parts list (see ``send_frame``).

        Retried under the peer's policy; raises ``TransportError`` after
        the budget is spent — never a silent drop. The retried frame
        carries the same wire seq (stamped before encoding), so a
        duplicate from a send that failed AFTER delivery is shed by the
        receiver's dedup. ``job`` credits retries to the tenant's
        counter slice on a shared fabric.
        """
        with self.lock:
            retry_call(
                lambda: self._send_once(frame), self.retry,
                describe=f"tcp send to {self.address[0]}:{self.address[1]}",
                is_transient=lambda exc: isinstance(exc, OSError),
                on_retry=lambda attempt, exc: self._bump("retries",
                                                         job=job))

    # -- send queue ---------------------------------------------------------
    def _fail(self, item: _SendItem, exc: BaseException) -> None:
        item.error = exc
        if item.on_error is not None:
            try:
                item.on_error(item.receiver, exc)
            except Exception:
                logging.exception("tcp peer %s: broadcast on_error "
                                  "callback raised", self.address)
        if item.done is not None:
            item.done.set()

    def _process(self, item: _SendItem) -> None:
        try:
            self.send(item.frame, job=item.job)
        except OSError as exc:
            self._fail(item, exc)
        else:
            self._on_sent(item.nbytes, item.job)
            if item.done is not None:
                item.done.set()

    def _writer_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is _STOP:
                break
            self._process(item)
        # shed anything that raced past close(): never strand a waiter
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                self._fail(item, TransportError(
                    f"peer {self.address} closed", transient=False))

    def enqueue(self, frame, job=None) -> None:
        """Synchronous send THROUGH the queue: stays FIFO with any
        in-flight broadcast frames to this peer, then waits out the write
        (blocking if the queue is momentarily full) and re-raises its
        error — same contract as a direct :meth:`send`."""
        if self._closed:
            raise TransportError(f"peer {self.address} closed",
                                 transient=False)
        nbytes = (len(frame)
                  if isinstance(frame, (bytes, bytearray, memoryview))
                  else sum(len(p) for p in frame))
        item = _SendItem(frame, nbytes, job, wait=True)
        self._queue.put(item)
        item.done.wait()
        if item.error is not None:
            raise item.error

    def enqueue_nowait(self, frame, job, on_error, receiver) -> int:
        """Broadcast fan-out: enqueue and return immediately. A full
        queue (wedged peer) or a later exhausted-retry failure surfaces
        through ``on_error(receiver, exc)`` with a ``TransportError`` —
        the same OSError family as the blocking path, so the caller's
        eviction logic is shared. Returns the observed queue depth."""
        nbytes = (len(frame)
                  if isinstance(frame, (bytes, bytearray, memoryview))
                  else sum(len(p) for p in frame))
        item = _SendItem(frame, nbytes, job, wait=False,
                         on_error=on_error, receiver=receiver)
        if self._closed:
            self._fail(item, TransportError(
                f"peer {self.address} closed", transient=False))
            return 0
        try:
            self._queue.put_nowait(item)
        except queue.Full:
            self._bump("send_queue_overflows", job=job)
            self._fail(item, TransportError(
                f"send queue to {self.address[0]}:{self.address[1]} "
                f"overflowed ({self._queue.maxsize} frames pending) — "
                "peer is not draining", transient=True))
        return self._queue.qsize()

    def close(self) -> None:
        # stop the writer first: drain pending items (erroring their
        # waiters — a send queued behind a closing peer must not hang),
        # then the sentinel; the writer's final drain sheds stragglers
        self._closed = True
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                self._fail(item, TransportError(
                    f"peer {self.address} closed", transient=False))
        try:
            self._queue.put_nowait(_STOP)
        except queue.Full:
            pass
        with self.lock:
            if self.sock is not None:
                try:
                    self.sock.close()
                # ft: allow[FT007] best-effort close of a dead socket
                except OSError:
                    pass
                self.sock = None


class TcpCommManager(BaseCommunicationManager):
    """One listening socket per rank; outbound connections cached per peer.

    Inbound frames from all connections funnel through one queue drained by
    ``handle_receive_message``, so observers run single-threaded — protocol
    state machines (e.g. the aggregator's all-received barrier) need no
    locking, same as the inproc/gRPC backends.
    """

    def __init__(self, rank: int, addresses: Dict[int, Tuple[str, int]],
                 retry: Optional[RetryPolicy] = None):
        super().__init__()
        self.rank = rank
        self.addresses = addresses
        #: seeded per rank: deterministic backoff schedules, decorrelated
        #: across ranks so a partitioned federation doesn't retry in
        #: lockstep against the same recovering peer
        self.retry = retry if retry is not None else RetryPolicy(seed=rank)
        host, port = addresses[rank]
        self._server = socket.create_server((host, port), reuse_port=False)
        self._server.listen(16)
        self._inbox: "queue.Queue" = queue.Queue()
        self._peers: Dict[int, _Peer] = {}
        self._peers_lock = threading.Lock()
        self._running = False
        self._accept_thread: threading.Thread | None = None

    def _peer_for(self, dest: int) -> _Peer:
        with self._peers_lock:  # dict access only; I/O under the peer lock
            peer = self._peers.get(dest)
            if peer is None:
                peer = self._peers[dest] = _Peer(
                    self.addresses[dest], self.retry, bump=self.bump,
                    on_sent=self._count_sent)
        return peer

    def send_message(self, msg: Message) -> None:
        peer = self._peer_for(msg.get_receiver_id())
        # stamp BEFORE encoding: every retry ships the identical frame,
        # so the receiver's dedup recognizes the duplicate
        self._stamp_seq(msg)
        # parts, not one joined frame: a model update goes header-then-
        # buffers straight to the socket with no contiguous copy
        parts = msg.to_parts()
        # through the peer's queue (blocking on completion), so frames to
        # one peer stay FIFO with any in-flight broadcast; wire bytes are
        # credited by the writer on successful send
        peer.enqueue(parts, job=msg.msg_params.get(WIRE_JOB_KEY))

    def broadcast(self, msgs, on_error=None) -> Dict[str, int]:
        """Overlapped fan-out: encode (once, via the shared-payload
        cache), stamp, and enqueue every frame on its peer's writer
        thread — this returns after enqueue, while N sends proceed in
        parallel. Per-peer failures (queue overflow, exhausted retries)
        surface through ``on_error`` on the writer thread; without
        ``on_error`` the sequential base implementation runs instead, so
        errors can propagate to the caller."""
        if on_error is None:
            return super().broadcast(msgs)
        enqueued = 0
        max_depth = 0
        for msg in msgs:
            dest = msg.get_receiver_id()
            peer = self._peer_for(dest)
            self._stamp_seq(msg)
            parts = msg.to_parts()
            depth = peer.enqueue_nowait(
                parts, msg.msg_params.get(WIRE_JOB_KEY), on_error, dest)
            max_depth = max(max_depth, depth)
            enqueued += 1
        return {"enqueued": enqueued, "max_queue_depth": max_depth}

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while self._running:
                frame = recv_frame(conn)
                self._count_received(len(frame))
                self._inbox.put(frame)
        except (ConnectionError, OSError) as exc:
            # a torn inbound connection is NOT silent: the sender retries
            # (or raises), but the event is counted and logged so a flaky
            # link shows up in the RoundTimer roll-up, not just in tails
            if self._running:
                self.bump("conn_errors")
                logging.warning("tcp rank %d: inbound connection dropped "
                                "(%r) — sender will retry", self.rank, exc)
        finally:
            conn.close()

    def _accept_loop(self) -> None:
        self._server.settimeout(0.5)
        while self._running:
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()
        self._server.close()

    def handle_receive_message(self) -> None:
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        while self._running:
            item = self._inbox.get()
            if item is _STOP:
                break
            n = len(item)
            msg = Message.from_bytes(item)
            # raw total was counted on the socket thread; the per-job
            # slice needs the decoded tag
            self._credit_job_received(n, msg.msg_params.get(WIRE_JOB_KEY))
            self._notify(msg)

    def stop_receive_message(self) -> None:
        self._running = False
        # close the listener here, not only from the accept loop: a
        # sender-only manager (broadcast without handle_receive_message)
        # never starts that loop, and the bound port must not outlive
        # its owner (EADDRINUSE on relaunch). socket.close() is
        # idempotent, so the accept loop's own close on exit stays safe
        # and so does calling stop twice.
        self._server.close()
        self._inbox.put(_STOP)
        with self._peers_lock:
            for peer in self._peers.values():
                peer.close()
            self._peers.clear()
