"""Compression policy ladder for the cross-silo wire.

Replaces the boolean ``compress`` flag with a named policy selecting how
much of the communication-efficiency stack engages, uplink AND downlink:

========================  ==========================  =====================
policy                    uplink (client -> server)   downlink (server -> clients)
========================  ==========================  =====================
``none``                  full precision              full precision
``delta_int8``            int8 delta vs the held      int8 delta vs the
                          global                      silos' mirror model
``topk_ef``               top-k delta + error         top-k delta vs the
                          feedback (exact values)     mirror
``topk_ef_int8``          top-k + int8 survivors +    top-k + int8 delta vs
                          error feedback              the mirror
========================  ==========================  =====================

Uplink error feedback is an explicit per-silo residual buffer
(ops/sparsify.py); downlink error feedback is implicit — the server
compresses the difference between its exact global model and the *mirror*
(the model state every silo actually holds, advanced by exactly what each
broadcast decodes to), so un-sent mass automatically rides in the next
round's delta. The FedAsync server is excluded with a loud guard: its
global moves every update, so no stable base exists on either direction
(see comm/compression.py).

Selection: launchers expose ``--compression``; the ``FEDML_TPU_COMPRESSION``
environment variable overrides any string/None selection (a kill switch /
fleet-wide experiment knob) but never an explicit
:class:`CompressionPolicy` instance (programmatic callers that already
resolved a policy keep it). ``topk_ef:0.05``-style suffixes set the keep
fraction.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Union

POLICY_NAMES = ("none", "delta_int8", "topk_ef", "topk_ef_int8")
ENV_VAR = "FEDML_TPU_COMPRESSION"


@dataclasses.dataclass(frozen=True)
class CompressionPolicy:
    name: str = "none"
    #: keep-fraction for the top-k policies (both directions)
    topk_frac: float = 0.01
    #: disable the downlink half only (uplink keeps the policy) — the
    #: bit-exact resume-parity mode: downlink deltas quantize against a
    #: mirror a freshly resumed federation cannot reconstruct, so the
    #: first post-resume broadcast degrades to full precision and the
    #: trajectory matches only within quantization noise
    downlink: bool = True

    def __post_init__(self):
        if self.name not in POLICY_NAMES:
            raise ValueError(f"unknown compression policy {self.name!r} "
                             f"(choose from {'|'.join(POLICY_NAMES)})")
        if not 0.0 < self.topk_frac <= 1.0:
            raise ValueError(
                f"topk_frac {self.topk_frac} outside (0, 1]")

    @property
    def enabled(self) -> bool:
        return self.name != "none"

    @property
    def uplink_topk(self) -> bool:
        return self.name in ("topk_ef", "topk_ef_int8")

    @property
    def uplink_int8(self) -> bool:
        return self.name in ("delta_int8", "topk_ef_int8")

    @property
    def downlink_enabled(self) -> bool:
        return self.enabled and self.downlink


def parse_policy(text: str) -> CompressionPolicy:
    """``"topk_ef_int8"`` or ``"topk_ef:0.05"`` -> a policy object."""
    name, _, frac = text.strip().partition(":")
    if frac:
        return CompressionPolicy(name, topk_frac=float(frac))
    return CompressionPolicy(name)


def resolve_compression(
        policy: Union[CompressionPolicy, str, None] = None, *,
        compress: bool = False) -> CompressionPolicy:
    """One resolution path for every launcher and manager.

    Precedence: an explicit :class:`CompressionPolicy` instance wins
    outright (already resolved upstream); otherwise ``$FEDML_TPU_COMPRESSION``
    overrides the string/None selection; otherwise the string; otherwise
    the legacy boolean ``compress`` flag — which maps to ``delta_int8``
    with ``downlink=False``, the EXACT pre-policy behavior (uplink int8
    only, full-precision broadcasts): a script that always passed
    ``--compress`` must not silently start receiving quantized
    broadcasts.
    """
    if isinstance(policy, CompressionPolicy):
        return policy
    env = os.environ.get(ENV_VAR, "").strip()
    if env:
        return parse_policy(env)
    if policy:
        return parse_policy(policy)
    if compress:
        return CompressionPolicy("delta_int8", downlink=False)
    return CompressionPolicy("none")
