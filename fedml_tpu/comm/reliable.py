"""Reliable-delivery primitives shared by the socket/RPC transports.

The reference's transports treat the network as infallible: gRPC calls are
fail-fast one-shots (grpc_comm_manager.py) and a dead peer either hangs the
federation or — worse — silently loses a frame (the old ``tcp._Peer.send``
dropped the socket on ``OSError`` and "hoped" the next send reconnected).
Production federated systems invert that assumption: transient link failure
is the COMMON case (Bonawitz et al., MLSys 2019), so every send is retried
with bounded, seeded exponential backoff, and duplicates created by
retrying an already-delivered frame are shed receive-side by per-stream
sequence numbers (comm/base.py). The contract after this module:

    a frame is delivered to observers exactly once, or the sender raises
    :class:`TransportError` — never a silent drop.

``RetryPolicy`` is deterministic: the backoff jitter comes from its own
seeded RNG, so a chaos run (comm/faults.py) replays the same retry
schedule every time.
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional


class TransportError(ConnectionError):
    """A send failed after exhausting its retry budget.

    ``transient`` distinguishes failures that MIGHT succeed on a fresh
    attempt later (peer restarting, link flap — ``UNAVAILABLE`` /
    ``DEADLINE_EXCEEDED`` / ``ECONNREFUSED``) from permanent ones
    (unknown host, protocol error): callers with their own recovery
    loop (the silo rejoin path) retry the former and surface the
    latter. Subclasses ``ConnectionError`` so pre-existing
    ``except OSError`` call sites still catch it.
    """

    def __init__(self, message: str, *, transient: bool = False):
        super().__init__(message)
        self.transient = transient


@dataclass
class RetryPolicy:
    """Bounded, seeded exponential backoff.

    ``max_attempts`` counts the FIRST try: 4 means 1 try + 3 retries.
    Delay before retry ``i`` (1-based) is ``base_delay_s * 2**(i-1)``
    capped at ``max_delay_s``, scaled by a jitter factor in [0.5, 1.0]
    drawn from the policy's own seeded RNG — deterministic per policy
    instance, so chaos tests replay identical schedules.
    """

    max_attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    seed: int = 0
    _rng: random.Random = field(init=False, repr=False, compare=False,
                                default=None)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got "
                             f"{self.max_attempts}")
        self._rng = random.Random(self.seed)

    def delay_s(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (1 = first retry)."""
        raw = min(self.max_delay_s,
                  self.base_delay_s * (2.0 ** (attempt - 1)))
        return raw * (0.5 + 0.5 * self._rng.random())


def default_retry_policy(seed: int = 0) -> RetryPolicy:
    """The un-configured transport retries by default — callers opt DOWN
    with ``RetryPolicy(max_attempts=1)``, never up to get safety."""
    return RetryPolicy(seed=seed)


def retry_call(fn: Callable[[], None], policy: RetryPolicy, *,
               describe: str,
               is_transient: Callable[[BaseException], bool],
               on_retry: Optional[Callable[[int, BaseException], None]]
               = None,
               sleep: Callable[[float], None] = time.sleep) -> int:
    """Run ``fn`` under ``policy``; returns the number of retries used.

    ``is_transient(exc)`` decides whether an exception is worth another
    attempt; a non-transient exception re-raises as a permanent
    :class:`TransportError` immediately. Exhausting the budget raises a
    transient :class:`TransportError` chained to the last failure — the
    loud path the old silent-drop ``except OSError: pass`` never had.
    ``on_retry(attempt, exc)`` runs before each backoff sleep (counter
    hooks for the transports).
    """
    retries = 0
    while True:
        try:
            fn()
            return retries
        except Exception as exc:  # noqa: BLE001 — classified below
            if isinstance(exc, TransportError):
                raise
            if not is_transient(exc):
                raise TransportError(
                    f"{describe}: permanent failure: {exc!r}",
                    transient=False) from exc
            attempt = retries + 1
            if attempt >= policy.max_attempts:
                raise TransportError(
                    f"{describe}: still failing after "
                    f"{policy.max_attempts} attempts: {exc!r}",
                    transient=True) from exc
            retries = attempt
            if on_retry is not None:
                on_retry(attempt, exc)
            delay = policy.delay_s(attempt)
            logging.warning("%s failed (%r) — retry %d/%d in %.0f ms",
                            describe, exc, attempt,
                            policy.max_attempts - 1, delay * 1e3)
            sleep(delay)
