"""Message envelope — typed header + arbitrary pytree payload.

Parity with the reference's dict-shaped ``Message``
(fedml_core/distributed/communication/message.py:5-74): the same header keys
(``msg_type``/``sender``/``receiver``), ``add``/``get`` payload access, and a
wire codec. Unlike the reference (JSON for gRPC/MQTT, pickle for MPI), the
wire format is one binary frame via the zero-copy codec in
``fedml_tpu/comm/serialization.py``, so model pytrees never get re-encoded
element-wise.
"""

from __future__ import annotations

from typing import Any, Dict

from fedml_tpu.comm import serialization


class Message:
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"

    def __init__(self, type: int = 0, sender_id: int = 0,
                 receiver_id: int = 0):
        self.msg_params: Dict[str, Any] = {
            Message.MSG_ARG_KEY_TYPE: type,
            Message.MSG_ARG_KEY_SENDER: sender_id,
            Message.MSG_ARG_KEY_RECEIVER: receiver_id,
        }

    # -- header ------------------------------------------------------------
    @property
    def type(self) -> int:
        return self.msg_params[Message.MSG_ARG_KEY_TYPE]

    def get_type(self) -> int:
        return self.type

    def get_sender_id(self) -> int:
        return self.msg_params[Message.MSG_ARG_KEY_SENDER]

    def get_receiver_id(self) -> int:
        return self.msg_params[Message.MSG_ARG_KEY_RECEIVER]

    # -- payload -----------------------------------------------------------
    def add(self, key: str, value: Any) -> "Message":
        self.msg_params[key] = value
        return self

    add_params = add

    def get(self, key: str) -> Any:
        value = self.msg_params[key]
        if isinstance(value, serialization.SharedPayload):
            # in-proc object hand-off skips the wire codec, so the
            # broadcast wrapper survives to the receiver — unwrap here
            # so handlers never see the cache layer
            return value.value
        return value

    def get_params(self) -> Dict[str, Any]:
        return self.msg_params

    # -- codec -------------------------------------------------------------
    def to_bytes(self) -> bytes:
        return serialization.dumps(self.msg_params)

    def to_parts(self) -> list:
        """The encoded frame as its constituent buffers (header + raw leaf
        buffers) for chunk-aware transports — the frame is never joined
        into one contiguous copy on the send path."""
        return serialization.dumps_parts(self.msg_params)

    @classmethod
    def from_bytes(cls, frame) -> "Message":
        msg = cls()
        msg.msg_params = serialization.loads(frame)
        return msg

    def __repr__(self) -> str:
        keys = [k for k in self.msg_params
                if k not in (Message.MSG_ARG_KEY_TYPE,
                             Message.MSG_ARG_KEY_SENDER,
                             Message.MSG_ARG_KEY_RECEIVER)]
        return (f"Message(type={self.type}, "
                f"{self.get_sender_id()}->{self.get_receiver_id()}, "
                f"payload={keys})")
