"""MQTT backend — the reference's mobile/IoT transport, protocol-level.

Reference (fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:14-121):
broker pub/sub over paho with the topic scheme

    server: subscribes ``<topic><cid>`` for every client,
            publishes  ``<topic>0_<cid>`` to address client ``cid``
    client: subscribes ``<topic>0_<cid>``, publishes ``<topic><cid>``

and JSON message payloads (message.py:62).

paho-mqtt is not in this environment, so :class:`MiniMqttClient` speaks
MQTT 3.1.1 (OASIS spec) directly over TCP — CONNECT/CONNACK,
SUBSCRIBE/SUBACK, QoS-0 PUBLISH, PINGREQ/PINGRESP, DISCONNECT — which makes
:class:`MqttCommManager` interoperable with any standard broker (mosquitto,
EMQX, a cloud IoT endpoint) AND with reference peers on the same broker,
since both sides agree on topics + JSON. :class:`MiniMqttBroker` is an
in-process QoS-0 broker so tests need no external daemon.

Cross-silo payload caveat: JSON-encoded model lists are ~5× larger than the
binary frame the routed/gRPC backends move; MQTT is for the mobile/IoT
interop story, not the TPU hot path.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Dict, List, Tuple

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.grpc_proto import message_from_json, message_to_json
from fedml_tpu.comm.message import Message

# -- MQTT 3.1.1 control packet types (spec §2.2.1) --------------------------
CONNECT, CONNACK = 0x10, 0x20
PUBLISH = 0x30
SUBSCRIBE, SUBACK = 0x82, 0x90
UNSUBSCRIBE, UNSUBACK = 0xA2, 0xB0
PINGREQ, PINGRESP = 0xC0, 0xD0
DISCONNECT = 0xE0


def _encode_remaining_length(n: int) -> bytes:
    """Spec §2.2.3 variable-length encoding (7 bits per byte, MSB=continue)."""
    out = bytearray()
    while True:
        byte = n % 128
        n //= 128
        out.append(byte | 0x80 if n else byte)
        if not n:
            return bytes(out)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("MQTT peer closed")
        buf += chunk
    return buf


def _read_packet(sock: socket.socket) -> Tuple[int, bytes]:
    """Returns (first header byte, body). Blocks; raises on EOF."""
    first = _read_exact(sock, 1)[0]
    length, mult = 0, 1
    for _ in range(4):
        byte = _read_exact(sock, 1)[0]
        length += (byte & 0x7F) * mult
        if not byte & 0x80:
            break
        mult *= 128
    else:
        raise ValueError("malformed remaining length")
    return first, _read_exact(sock, length) if length else b""


def _utf8(s: str) -> bytes:
    data = s.encode("utf-8")
    return struct.pack(">H", len(data)) + data


class MiniMqttClient:
    """Blocking-connect, threaded-receive MQTT 3.1.1 client (QoS 0)."""

    def __init__(self, host: str, port: int, client_id: str,
                 on_message: Callable[[str, bytes], None],
                 keepalive: int = 0, timeout: float = 10.0):
        self._on_message = on_message
        self._sock = socket.create_connection((host, port), timeout=timeout)
        # the reader is a dedicated blocking thread; close() tears the
        # socket down, and recv raising IS the shutdown signal
        # ft: allow[FT007] dedicated reader thread, shutdown via close()
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._suback = threading.Event()
        self._packet_id = 0
        self._running = True

        # CONNECT: protocol "MQTT" level 4, clean session, client id payload
        var = _utf8("MQTT") + bytes([4, 0x02]) + struct.pack(">H", keepalive)
        body = var + _utf8(client_id)
        self._send(bytes([CONNECT]) + _encode_remaining_length(len(body))
                   + body)
        first, payload = _read_packet(self._sock)
        if first & 0xF0 != CONNACK or len(payload) < 2 or payload[1] != 0:
            raise ConnectionError(f"CONNACK refused: {payload!r}")

        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _send(self, frame: bytes) -> None:
        with self._wlock:
            self._sock.sendall(frame)

    def subscribe(self, topic: str, timeout: float = 10.0) -> None:
        self._packet_id += 1
        body = struct.pack(">H", self._packet_id) + _utf8(topic) + b"\x00"
        self._suback.clear()
        self._send(bytes([SUBSCRIBE]) + _encode_remaining_length(len(body))
                   + body)
        if not self._suback.wait(timeout):
            raise TimeoutError(f"no SUBACK for {topic!r}")

    def publish(self, topic: str, payload: bytes) -> None:
        body = _utf8(topic) + payload  # QoS 0: no packet id
        self._send(bytes([PUBLISH]) + _encode_remaining_length(len(body))
                   + body)

    def ping(self) -> None:
        self._send(bytes([PINGREQ, 0]))

    def _read_loop(self) -> None:
        try:
            while self._running:
                first, body = _read_packet(self._sock)
                ptype = first & 0xF0
                if ptype == PUBLISH:
                    tlen = struct.unpack(">H", body[:2])[0]
                    topic = body[2:2 + tlen].decode("utf-8")
                    pos = 2 + tlen
                    if (first >> 1) & 0x03:  # QoS>0: skip packet id
                        pos += 2
                    self._on_message(topic, body[pos:])
                elif ptype == SUBACK & 0xF0:
                    self._suback.set()
                # PINGRESP and others: ignore
        # ft: allow[FT007] reader-loop exit: the torn socket IS the stop
        except (ConnectionError, OSError, ValueError):
            pass  # socket closed or torn down

    def close(self) -> None:
        self._running = False
        try:
            self._send(bytes([DISCONNECT, 0]))
        # ft: allow[FT007] best-effort courtesy DISCONNECT at shutdown
        except OSError:
            pass
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        # ft: allow[FT007] best-effort shutdown of an already-dead socket
        except OSError:
            pass
        self._sock.close()


class MqttCommManager(BaseCommunicationManager):
    """Reference-compatible MQTT comm manager (topic scheme + JSON payloads).

    client_id 0 is the server (subscribes every client's uplink topic);
    any other id is a client (subscribes its own downlink topic).
    """

    def __init__(self, host: str, port: int, topic: str = "fedml",
                 client_id: int = 0, client_num: int = 0):
        super().__init__()
        self._topic = topic
        self.client_id = client_id
        self.client_num = client_num
        self._inbox: List = []
        self._cv = threading.Condition()
        self._running = False

        self._client = MiniMqttClient(
            host, port, client_id=f"{topic}-node-{client_id}",
            on_message=self._on_raw)
        if client_id == 0:
            for cid in range(1, client_num + 1):
                self._client.subscribe(self._topic + str(cid))
        else:
            self._client.subscribe(f"{self._topic}0_{client_id}")

    def _on_raw(self, topic: str, payload: bytes) -> None:
        with self._cv:
            self._inbox.append(payload.decode("utf-8"))
            self._cv.notify()

    def send_message(self, msg: Message) -> None:
        if self.client_id == 0:
            topic = f"{self._topic}0_{msg.get_receiver_id()}"
        else:
            topic = self._topic + str(self.client_id)
        self._client.publish(topic, message_to_json(msg).encode("utf-8"))

    def handle_receive_message(self) -> None:
        self._running = True
        while True:
            with self._cv:
                while self._running and not self._inbox:
                    self._cv.wait(timeout=0.5)
                if not self._running:
                    return
                payload = self._inbox.pop(0)
            self._notify(message_from_json(payload))

    def stop_receive_message(self) -> None:
        with self._cv:
            self._running = False
            self._cv.notify_all()
        self._client.close()


class MiniMqttBroker:
    """In-process QoS-0 MQTT 3.1.1 broker (exact-match topics) for tests
    and single-box federations — the daemon role mosquitto plays for the
    reference."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(32)
        self.port = self._server.getsockname()[1]
        self._subs: Dict[str, List[socket.socket]] = {}
        self._lock = threading.Lock()
        self._running = True
        self._threads: List[threading.Thread] = []
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        wlock = threading.Lock()
        try:
            first, body = _read_packet(conn)
            if first & 0xF0 != CONNECT:
                conn.close()
                return
            with wlock:
                conn.sendall(bytes([CONNACK, 2, 0, 0]))  # accepted
            while self._running:
                first, body = _read_packet(conn)
                ptype = first & 0xF0
                if ptype == SUBSCRIBE & 0xF0:
                    pid = body[:2]
                    pos, codes = 2, b""
                    while pos < len(body):
                        tlen = struct.unpack(">H", body[pos:pos + 2])[0]
                        topic = body[pos + 2:pos + 2 + tlen].decode("utf-8")
                        pos += 2 + tlen + 1  # + requested qos byte
                        with self._lock:
                            self._subs.setdefault(topic, []).append(conn)
                        codes += b"\x00"
                    ack = pid + codes
                    with wlock:
                        conn.sendall(bytes([SUBACK])
                                     + _encode_remaining_length(len(ack))
                                     + ack)
                elif ptype == PUBLISH:
                    tlen = struct.unpack(">H", body[:2])[0]
                    topic = body[2:2 + tlen].decode("utf-8")
                    frame = (bytes([PUBLISH])
                             + _encode_remaining_length(len(body)) + body)
                    # fan out under the broker lock so two publisher threads
                    # can't interleave bytes on one subscriber socket
                    with self._lock:
                        for t in self._subs.get(topic, ()):
                            try:
                                # ft: allow[FT022] the broker lock IS the per-subscriber interleave guard: sendall must happen under it or two publishers corrupt one sub's byte stream; subs are loopback sockets, worst case bounded by the kernel buffer
                                t.sendall(frame)
                            # ft: allow[FT007] dead sub detaches itself
                            except OSError:
                                pass
                elif ptype == PINGREQ & 0xF0:
                    with wlock:
                        conn.sendall(bytes([PINGRESP, 0]))
                elif ptype == DISCONNECT & 0xF0:
                    break
        # ft: allow[FT007] torn client conn ends its loop; finally detaches
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            with self._lock:
                for subs in self._subs.values():
                    if conn in subs:
                        subs.remove(conn)
            conn.close()

    def stop(self) -> None:
        self._running = False
        try:
            self._server.close()
        # ft: allow[FT007] best-effort close of the broker listener
        except OSError:
            pass
