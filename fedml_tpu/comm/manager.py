"""Rank event loops: ClientManager / ServerManager (reference L2).

Parity with fedml_core/distributed/{client/client_manager.py:13-69,
server/server_manager.py:12-63}: handler-dict dispatch keyed by message type,
``run()`` registers handlers then blocks in the backend's receive loop,
``finish()`` stops cleanly (the reference calls ``MPI.COMM_WORLD.Abort()`` —
a quirk we do not carry forward; SURVEY "fork quirks").
"""

from __future__ import annotations

from typing import Callable, Dict

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import Message


class _ManagerBase(Observer):
    def __init__(self, rank: int, size: int,
                 com_manager: BaseCommunicationManager):
        self.rank = rank
        self.size = size
        self.com_manager = com_manager
        self.com_manager.add_observer(self)
        self.message_handler_dict: Dict[int, Callable[[Message], None]] = {}

    def register_message_receive_handlers(self) -> None:
        """Subclasses register their protocol handlers here."""

    def register_message_receive_handler(
            self, msg_type: int,
            handler: Callable[[Message], None]) -> None:
        self.message_handler_dict[msg_type] = handler

    def receive_message(self, msg_type: int, msg: Message) -> None:
        self.message_handler_dict[msg_type](msg)

    def send_message(self, msg: Message) -> None:
        self.com_manager.send_message(msg)

    def run(self) -> None:
        self.register_message_receive_handlers()
        self.com_manager.handle_receive_message()

    def finish(self) -> None:
        self.com_manager.stop_receive_message()


class ClientManager(_ManagerBase):
    pass


class ServerManager(_ManagerBase):
    pass
