"""Broker-routed backend: silos dial out to the native C++ router.

Complements the peer-to-peer TCP backend (tcp.py) for deployments where
silos cannot accept inbound connections (NAT/firewalled cross-silo — the
scenario the reference serves with an MQTT broker,
fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py): every rank
keeps ONE outbound connection to the router (native/router.cpp) and frames
are addressed by rank. Same Message/Observer contract as every other
backend, so managers and algorithm protocols are transport-agnostic.

Wire protocol (little-endian), mirroring the router:
  HELLO:           u32 magic 'FMLR'  u32 rank
  HELLO+AUTH:      u32 magic 'FMLS'  u32 rank  u32 token_len  token
  DATA (send):     u32 dest_rank     u64 len   payload
  DATA (receive):  u32 src_rank      u64 len   payload

A shared-secret ``token`` authenticates the rank claim against a router
started with the same token; without it any reachable host could register as
any rank. Payloads are still cleartext — run the broker behind TLS
termination or on a trusted network (see native/router.cpp).
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
from typing import Optional, Tuple

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.tcp import _recv_exact

_MAGIC = 0x464D4C52  # 'FMLR'
_MAGIC_AUTH = 0x464D4C53  # 'FMLS'
_HELLO = struct.Struct("<II")
_HELLO_AUTH = struct.Struct("<III")
_HDR = struct.Struct("<IQ")
_STOP = object()


class RoutedCommManager(BaseCommunicationManager):
    """One rank's connection to the message router."""

    def __init__(self, rank: int, router_address: Tuple[str, int],
                 connect_timeout: float = 30.0,
                 token: Optional[bytes] = None):
        super().__init__()
        self.rank = rank
        self._sock = socket.create_connection(router_address,
                                              timeout=connect_timeout)
        # the reader is a dedicated blocking thread; stop tears the socket
        # down and the resulting error is routed to the inbox
        # ft: allow[FT007] dedicated reader thread, shutdown via close()
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if token:
            self._sock.sendall(
                _HELLO_AUTH.pack(_MAGIC_AUTH, rank, len(token)) + token)
        else:
            self._sock.sendall(_HELLO.pack(_MAGIC, rank))
        # Registration handshake: the router sends nothing on success, so a
        # rejected HELLO (token mismatch, duplicate rank) would otherwise
        # only surface later as a generic "connection lost" mid-round. A
        # self-addressed empty frame echoes back iff we were registered.
        try:
            self._sock.sendall(_HDR.pack(rank, 0))
            src, length = _HDR.unpack(_recv_exact(self._sock, _HDR.size))
            if src != rank or length != 0:
                raise ConnectionError(
                    f"rank {rank}: unexpected first frame from router "
                    f"(src={src}, len={length})")
        except (ConnectionError, OSError) as exc:
            self._sock.close()
            raise ConnectionError(
                f"rank {rank}: router at {router_address} closed the "
                "connection during registration — auth token mismatch "
                "(client and router must both set the same token, or "
                "neither) or this rank is already connected") from exc
        self._send_lock = threading.Lock()
        self._inbox: "queue.Queue" = queue.Queue()
        self._running = False
        self._reader: Optional[threading.Thread] = None

    def send_message(self, msg: Message) -> None:
        # parts, not one joined frame: a broadcast's shared payload rides
        # as cached buffer views and a multi-hundred-MB model update never
        # materializes as a contiguous copy on the send path
        parts = msg.to_parts()
        total = sum(len(p) for p in parts)
        with self._send_lock:
            self._sock.sendall(_HDR.pack(msg.get_receiver_id(), total))
            for part in parts:
                self._sock.sendall(part)

    def _read_loop(self) -> None:
        try:
            while self._running:
                hdr = _recv_exact(self._sock, _HDR.size)
                _src, length = _HDR.unpack(hdr)
                self._inbox.put(_recv_exact(self._sock, length))
        except (ConnectionError, OSError) as exc:
            if self._running:
                # broker died mid-protocol: this must surface as an error,
                # not look like a clean stop (the manager would otherwise
                # "finish" with a partial round and no exception)
                self._inbox.put(ConnectionError(
                    f"rank {self.rank}: router connection lost: {exc}"))
            else:
                self._inbox.put(_STOP)

    def handle_receive_message(self) -> None:
        self._running = True
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        while self._running:
            item = self._inbox.get()
            if item is _STOP:
                break
            if isinstance(item, ConnectionError):
                raise item
            msg = Message.from_bytes(item)
            self._notify(msg)

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(_STOP)
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        # ft: allow[FT007] best-effort shutdown of an already-dead socket
        except OSError:
            pass
        self._sock.close()
