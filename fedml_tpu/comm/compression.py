"""Delta compression for the cross-silo wire: int8 and top-k + EF payloads.

The reference ships every model update at full precision (pickled tensors
over MPI, mpi_send_thread.py:27; JSON float lists over MQTT,
fedavg/utils.py:12). Here two payload families compress the DELTA against a
base model both ends hold:

- ``delta_int8`` — int8 block-scaled quantization of the full delta (4x)
  using the Pallas kernels (fedml_tpu/ops/quantize.py). Stochastic rounding
  keeps the quantizer unbiased, so the server's weighted mean of dequantized
  deltas is an unbiased estimate of the uncompressed aggregate.
- ``topk_ef`` / ``topk_ef_int8`` — magnitude top-k sparsification of the
  delta (ops/sparsify.py), optionally int8-quantizing the survivors
  (~10-50x smaller at 1-5% keep fractions). Top-k is biased: callers MUST
  run the error-feedback loop — :func:`compress_topk` returns the un-sent
  residual, and the caller adds it to the next round's delta.

Wire format: a plain dict of arrays/ints (codec-friendly — no treedefs on
the wire). Both ends hold the same model structure: the sender compresses
against a base the receiver also holds (the round's broadcast for uplink,
the silo mirror for downlink), and the receiver rebuilds against its copy.
This only holds for ROUND-based servers (plain + quorum, where stale
replies are dropped); the FedAsync server moves the global model every
update, so its base would drift — the async server enforces full precision
with a loud guard (algorithms/fedavg_async.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np

from fedml_tpu.core import pytree as pt
from fedml_tpu.ops.quantize import dequantize_tree, quantize_tree
from fedml_tpu.ops.sparsify import (k_for, topk_densify, topk_dequantize,
                                    topk_quantize, topk_quantize_donated,
                                    topk_sparsify, topk_sparsify_donated)

COMPRESSED_FLAG = "__delta_int8__"
TOPK_FLAG = "__topk_ef__"


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    # the kernels carry TPU tiling; anything else runs the interpreter
    if interpret is None:
        return jax.devices()[0].platform != "tpu"
    return interpret


def _tree_size(tree) -> int:
    return sum(int(np.prod(l.shape)) if l.shape else 1
               for l in jax.tree.leaves(tree))


def _tree_fingerprint(tree) -> str:
    """Cheap structure hash over the leaf (path, shape, dtype) list.

    A total-parameter-count check alone admits any version skew that
    preserves the count (transposed layer, swapped widths) and silently
    corrupts the rebuilt aggregate; the fingerprint rejects it."""
    import hashlib

    parts = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        # metadata only — leaf.dtype avoids a device->host copy of the tree
        dtype = getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
        parts.append(f"{jax.tree_util.keystr(path)}:"
                     f"{tuple(leaf.shape)}:{np.dtype(dtype).name}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


#: public name — the cross-silo managers exchange base fingerprints on the
#: wire (silo replies report what they hold; the server's downlink falls
#: back to full precision on mismatch)
tree_fingerprint = _tree_fingerprint


def compress_delta(new_tree, base_tree, key,
                   interpret: Optional[bool] = None) -> Dict[str, Any]:
    """int8-quantize (new - base); returns a codec-friendly payload dict
    (no treedef on the wire — the receiver rebuilds against its own base)."""
    delta = pt.tree_sub(new_tree, base_tree)
    vals, scales, _spec = quantize_tree(delta,
                                        key,
                                        interpret=_resolve_interpret(
                                            interpret))
    return {COMPRESSED_FLAG: True, "q": np.asarray(vals),
            "s": np.asarray(scales), "d": _tree_size(delta),
            "fp": _tree_fingerprint(base_tree)}


def decompress_delta(payload: Dict[str, Any], base_tree,
                     interpret: Optional[bool] = None):
    """Rebuild the full model: base + dequantized delta (leaf order/shapes
    from the receiver's own base_tree)."""
    import jax.numpy as jnp
    # count can survive version skew (transposed layer, swapped widths);
    # the structure fingerprint cannot — _check_base guards both
    expected = _check_base(payload, base_tree)
    leaves, treedef = jax.tree.flatten(base_tree)
    spec = (treedef, [(l.shape, np.asarray(l).dtype.name) for l in leaves],
            expected)
    delta = dequantize_tree(jnp.asarray(payload["q"]),
                            jnp.asarray(payload["s"]), spec,
                            interpret=_resolve_interpret(interpret))
    return pt.tree_add(base_tree, delta)


def _flatten_tree(tree):
    """Concatenate leaves to the flat f32 layout ``quantize_tree`` uses."""
    import jax.numpy as jnp
    return jnp.concatenate([jnp.asarray(l).reshape(-1).astype(jnp.float32)
                            for l in jax.tree.leaves(tree)])


def _unflatten_like(flat, base_tree):
    """Inverse of :func:`_flatten_tree` against ``base_tree``'s structure
    (leaf order/shapes/dtypes from the receiver's own copy)."""
    import jax.numpy as jnp
    leaves, treedef = jax.tree.flatten(base_tree)
    out, off = [], 0
    for l in leaves:
        size = int(np.prod(l.shape)) if np.shape(l) else 1
        out.append(jnp.reshape(flat[off:off + size], np.shape(l)).astype(
            np.asarray(l).dtype))
        off += size
    return jax.tree.unflatten(treedef, out)


def _check_base(payload: Dict[str, Any], base_tree) -> int:
    """Shared skew guards: parameter count + structure fingerprint."""
    expected = _tree_size(base_tree)
    if int(payload["d"]) != expected:
        raise ValueError(
            f"compressed delta carries {payload['d']} parameters but the "
            f"receiver's model has {expected} — model-version skew or a "
            "malformed payload; refusing to rebuild")
    if "fp" in payload:
        fp = _tree_fingerprint(base_tree)
        if payload["fp"] != fp:
            raise ValueError(
                f"compressed delta structure fingerprint {payload['fp']} "
                f"does not match the receiver's model ({fp}) — the sender "
                "trained a differently-shaped tree; refusing to rebuild")
    return expected


def compress_topk(new_tree, base_tree, residual, key, *,
                  frac: float = 0.01, quantize: bool = True,
                  interpret: Optional[bool] = None):
    """Top-k (+ optional int8) compress ``(new - base) + residual``.

    Returns ``(payload, new_residual)``: the codec-friendly payload dict
    and the flat f32 error-feedback residual the caller must carry into
    the NEXT call (pass ``None`` the first round). Dropping the residual
    turns the biased top-k into plain (non-converging) truncation.
    """
    import jax.numpy as jnp
    interpret = _resolve_interpret(interpret)
    flat = _flatten_tree(pt.tree_sub(new_tree, base_tree))
    d = int(flat.size)
    if residual is not None:
        flat = flat + jnp.asarray(residual, jnp.float32)
    k = k_for(d, frac)
    payload: Dict[str, Any] = {TOPK_FLAG: True, "d": d,
                               "fp": _tree_fingerprint(base_tree)}
    # `flat` is a freshly built temporary at this point (concat of leaf
    # casts, plus the EF add) — donate it so the residual output aliases
    # its memory on tpu/gpu. Bit-exact with the undonated kernels and the
    # numpy oracle (topk_sparsify_reference); the parity tests pin that.
    if quantize:
        idx, q, scales, res = topk_quantize_donated(flat, key, k,
                                                    interpret=interpret)
        payload.update(i=np.asarray(idx), q=np.asarray(q),
                       s=np.asarray(scales))
    else:
        idx, vals, res = topk_sparsify_donated(flat, k)
        payload.update(i=np.asarray(idx), v=np.asarray(vals))
    return payload, np.asarray(res)


def decompress_topk(payload: Dict[str, Any], base_tree,
                    interpret: Optional[bool] = None):
    """Rebuild the full model from a :func:`compress_topk` payload:
    base + densified sparse delta."""
    import jax.numpy as jnp
    d = _check_base(payload, base_tree)
    idx = np.asarray(payload["i"])
    if idx.size and (int(idx.min()) < 0 or int(idx.max()) >= d):
        # the jnp scatter would silently drop/clamp out-of-bounds
        # indices — a corrupted frame must refuse loudly like every
        # other malformed-payload path in this module
        raise ValueError(
            f"top-k payload carries indices outside [0, {d}) — corrupted "
            "or malformed frame; refusing to rebuild")
    if "q" in payload:
        dense = topk_dequantize(jnp.asarray(payload["i"]),
                                jnp.asarray(payload["q"]),
                                jnp.asarray(payload["s"]), d,
                                interpret=_resolve_interpret(interpret))
    else:
        dense = topk_densify(jnp.asarray(payload["i"]),
                             jnp.asarray(payload["v"]), d)
    return pt.tree_add(base_tree, _unflatten_like(dense, base_tree))


def decompress(payload: Dict[str, Any], base_tree,
               interpret: Optional[bool] = None):
    """Rebuild any compressed payload family against ``base_tree``."""
    if payload.get(TOPK_FLAG):
        return decompress_topk(payload, base_tree, interpret=interpret)
    return decompress_delta(payload, base_tree, interpret=interpret)


def compress_for_policy(new_tree, base_tree, residual, key, policy,
                        interpret: Optional[bool] = None):
    """Encode ``new_tree`` against ``base_tree`` per a CompressionPolicy
    (comm/policy.py). Returns ``(payload, new_residual)`` — residual is
    ``None`` for the non-top-k policies (no error feedback needed: int8
    stochastic rounding is unbiased)."""
    if policy.uplink_topk:
        return compress_topk(new_tree, base_tree, residual, key,
                             frac=policy.topk_frac,
                             quantize=policy.uplink_int8,
                             interpret=interpret)
    if policy.name == "delta_int8":
        return compress_delta(new_tree, base_tree, key,
                              interpret=interpret), None
    return jax.tree.map(np.asarray, new_tree), None


def is_compressed(payload) -> bool:
    return isinstance(payload, dict) and bool(
        payload.get(COMPRESSED_FLAG) or payload.get(TOPK_FLAG))


def wire_bytes(payload) -> int:
    """TRUE payload size on the wire: the encoded frame length, exactly
    what the transport ships (header, scalars, and framing included —
    summing only ndarray values under-reported every ratio)."""
    from fedml_tpu.comm import serialization
    return sum(len(p) for p in serialization.dumps_parts(payload))
