"""Quantized model-delta compression for the cross-silo wire.

The reference ships every model update at full precision (pickled tensors
over MPI, mpi_send_thread.py:27; JSON float lists over MQTT,
fedavg/utils.py:12). Here the client ships an int8 block-scaled DELTA
against the round's global model — 4x smaller — using the Pallas
quantization kernels (fedml_tpu/ops/quantize.py). Stochastic rounding keeps
the quantizer unbiased, so the server's weighted mean of dequantized deltas
is an unbiased estimate of the uncompressed aggregate.

Wire format: a plain dict of arrays/ints (codec-friendly — no treedefs on
the wire). Both ends hold the same model structure: the client compresses
against the global model it just received, the server decompresses against
the model it broadcast for that round. This only holds for ROUND-based
servers (plain + quorum, where stale replies are dropped); the FedAsync
server moves the global model every update, so its base would drift — keep
full precision there.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np

from fedml_tpu.core import pytree as pt
from fedml_tpu.ops.quantize import dequantize_tree, quantize_tree

COMPRESSED_FLAG = "__delta_int8__"


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    # the kernels carry TPU tiling; anything else runs the interpreter
    if interpret is None:
        return jax.devices()[0].platform != "tpu"
    return interpret


def _tree_size(tree) -> int:
    return sum(int(np.prod(l.shape)) if l.shape else 1
               for l in jax.tree.leaves(tree))


def _tree_fingerprint(tree) -> str:
    """Cheap structure hash over the leaf (path, shape, dtype) list.

    A total-parameter-count check alone admits any version skew that
    preserves the count (transposed layer, swapped widths) and silently
    corrupts the rebuilt aggregate; the fingerprint rejects it."""
    import hashlib

    parts = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        # metadata only — leaf.dtype avoids a device->host copy of the tree
        dtype = getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
        parts.append(f"{jax.tree_util.keystr(path)}:"
                     f"{tuple(leaf.shape)}:{np.dtype(dtype).name}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def compress_delta(new_tree, base_tree, key,
                   interpret: Optional[bool] = None) -> Dict[str, Any]:
    """int8-quantize (new - base); returns a codec-friendly payload dict
    (no treedef on the wire — the receiver rebuilds against its own base)."""
    delta = pt.tree_sub(new_tree, base_tree)
    vals, scales, _spec = quantize_tree(delta,
                                        key,
                                        interpret=_resolve_interpret(
                                            interpret))
    return {COMPRESSED_FLAG: True, "q": np.asarray(vals),
            "s": np.asarray(scales), "d": _tree_size(delta),
            "fp": _tree_fingerprint(base_tree)}


def decompress_delta(payload: Dict[str, Any], base_tree,
                     interpret: Optional[bool] = None):
    """Rebuild the full model: base + dequantized delta (leaf order/shapes
    from the receiver's own base_tree)."""
    import jax.numpy as jnp
    expected = _tree_size(base_tree)
    if int(payload["d"]) != expected:
        raise ValueError(
            f"compressed delta carries {payload['d']} parameters but the "
            f"receiver's model has {expected} — model-version skew or a "
            "malformed payload; refusing to rebuild")
    # count can survive version skew (transposed layer, swapped widths);
    # the structure fingerprint cannot
    if "fp" in payload:
        fp = _tree_fingerprint(base_tree)
        if payload["fp"] != fp:
            raise ValueError(
                f"compressed delta structure fingerprint {payload['fp']} "
                f"does not match the receiver's model ({fp}) — the sender "
                "trained a differently-shaped tree; refusing to rebuild")
    leaves, treedef = jax.tree.flatten(base_tree)
    spec = (treedef, [(l.shape, np.asarray(l).dtype.name) for l in leaves],
            expected)
    delta = dequantize_tree(jnp.asarray(payload["q"]),
                            jnp.asarray(payload["s"]), spec,
                            interpret=_resolve_interpret(interpret))
    return pt.tree_add(base_tree, delta)


def is_compressed(payload) -> bool:
    return isinstance(payload, dict) and bool(payload.get(COMPRESSED_FLAG))


def wire_bytes(payload: Dict[str, Any]) -> int:
    """Payload size on the wire (for compression-ratio accounting)."""
    return sum(np.asarray(v).nbytes for k, v in payload.items()
               if isinstance(v, np.ndarray))
