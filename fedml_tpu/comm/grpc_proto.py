"""Reference-wire-compatible gRPC mode.

The reference defines a concrete proto service
(fedml_core/distributed/communication/gRPC/proto/grpc_comm_manager.proto:1-17):

    service gRPCCommManager {
      rpc sendMessage (CommRequest) returns (CommResponse);
      rpc handleReceiveMessage(CommRequest) returns (CommResponse);
    }
    message CommRequest  { int32 client_id = 1; string message = 2; }
    message CommResponse { int32 client_id = 1; string message = 2; }

and ships `request.message = msg.to_json()` through it
(grpc_comm_manager.py:46-72), where the JSON codec is the plain
``json.dumps(msg_params)`` of message.py:62 (tensors pre-converted to nested
lists by the mobile path, fedml_api/distributed/fedavg/utils.py:12).

This module speaks that exact wire format WITHOUT protoc code-gen: the two
messages are trivial proto3 records (field 1 varint, field 2 length-delimited
UTF-8), hand-encoded below, and the service/method names are registered via
grpc's generic handler API. A silo running the reference's generated stubs
can therefore exchange rounds with a ``ProtoGrpcCommManager`` silo unmodified.

The binary-frame backend (grpc_backend.py) remains the default — it moves
model pytrees zero-copy instead of via JSON lists — this codec exists for
interop.
"""

from __future__ import annotations

import json
import queue
import threading
from typing import Any, Dict, Tuple

import numpy as np

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import Message

try:
    import grpc
    HAS_GRPC = True
except ImportError:  # pragma: no cover
    grpc = None
    HAS_GRPC = False

SERVICE = "gRPCCommManager"          # proto has no package ⇒ bare service name
SEND_METHOD = f"/{SERVICE}/sendMessage"
_MAX_LEN = 1 << 30

_STOP = object()


# -- proto3 wire codec (CommRequest / CommResponse share one shape) ---------

def _encode_varint(value: int) -> bytes:
    if value < 0:  # proto3 int32: negatives are 10-byte two's-complement
        value += 1 << 64
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _decode_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        byte = buf[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
        if shift >= 70:
            raise ValueError("varint too long")
    if result >= 1 << 63:  # undo int32-as-uint64 sign extension
        result -= 1 << 64
    return result, pos


def encode_comm_message(client_id: int, message: str) -> bytes:
    """Serialize a CommRequest/CommResponse to proto3 wire bytes."""
    out = bytearray()
    if client_id:  # proto3 omits default-valued fields
        out += b"\x08" + _encode_varint(client_id)      # field 1, varint
    if message:
        data = message.encode("utf-8")
        out += b"\x12" + _encode_varint(len(data)) + data  # field 2, bytes
    return bytes(out)


def decode_comm_message(buf: bytes) -> Tuple[int, str]:
    """Parse proto3 wire bytes into (client_id, message)."""
    client_id, message = 0, ""
    pos = 0
    while pos < len(buf):
        tag, pos = _decode_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == 0:
            client_id, pos = _decode_varint(buf, pos)
        elif field == 2 and wire == 2:
            length, pos = _decode_varint(buf, pos)
            message = buf[pos:pos + length].decode("utf-8")
            pos += length
        elif wire == 0:  # unknown varint field: skip
            _, pos = _decode_varint(buf, pos)
        elif wire == 2:  # unknown length-delimited field: skip
            length, pos = _decode_varint(buf, pos)
            pos += length
        else:
            raise ValueError(f"unsupported wire type {wire}")
    return client_id, message


# -- JSON payload codec (message.py:62 semantics) ---------------------------

def _jsonify(value: Any) -> Any:
    """Arrays → nested lists, the reference's mobile/JSON convention
    (fedml_api/distributed/fedavg/utils.py:12 transform_tensor_to_list)."""
    if isinstance(value, (np.ndarray, np.generic)):
        return value.tolist()
    if hasattr(value, "dtype") and hasattr(value, "tolist"):  # jax arrays
        return np.asarray(value).tolist()
    if isinstance(value, dict):
        return {k: _jsonify(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonify(v) for v in value]
    return value


def _restore_tensors(value: Any) -> Any:
    """Nested lists → ndarrays inside a MODEL-PARAMS payload — the
    reference's receive-side convention
    (fedml_api/distributed/fedavg/utils.py:6 transform_list_to_tensor,
    applied to JSON payloads on the mobile/MQTT path, and like the
    reference scoped to the model payload only: other params keep their
    Python types). float64 drops to float32 exactly as the reference's
    ``.float()`` does. A zero-size leaf comes back as float32 [0] — the
    JSON wire cannot carry its original shape/dtype (use the binary
    backends for models with empty params).

    Coercion is by VALUE SHAPE, not position: ANY homogeneous numeric
    nested list under the model payload becomes an ndarray (so a
    structural int list — e.g. a shape stored inside model_params — comes
    back as int64 ndarray, and float lists as float32). This mirrors
    transform_list_to_tensor, which walks every key of the dict the same
    way; keep non-tensor metadata in other message params (they are left
    untouched), or use the binary backends for exact type round-trips."""
    if isinstance(value, dict):
        return {k: _restore_tensors(v) for k, v in value.items()}
    if isinstance(value, list):
        try:
            arr = np.asarray(value)
        except (ValueError, TypeError):
            return [_restore_tensors(v) for v in value]
        if arr.dtype.kind not in "fiu":
            return [_restore_tensors(v) for v in value]
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        return arr
    return value


def message_from_json(payload: str) -> Message:
    msg = Message()
    params = json.loads(payload)
    key = Message.MSG_ARG_KEY_MODEL_PARAMS
    if isinstance(params, dict) and key in params:
        params[key] = _restore_tensors(params[key])
    msg.msg_params = params
    return msg


def message_to_json(msg: Message) -> str:
    return json.dumps(_jsonify(msg.get_params()))


class ProtoGrpcCommManager(BaseCommunicationManager):
    """Drop-in alternative to GrpcCommManager speaking the reference's wire.

    Same constructor contract (rank + explicit ``{rank: (host, port)}`` map —
    the reference's hardcoded IPs, grpc_comm_manager.py:51-56, are a fork
    quirk not worth reproducing), but every RPC is byte-identical to what the
    reference's generated ``gRPCCommManagerStub.sendMessage`` emits.
    """

    def __init__(self, rank: int, addresses: Dict[int, Tuple[str, int]]):
        if not HAS_GRPC:  # pragma: no cover
            raise ImportError("grpcio is not available in this environment")
        super().__init__()
        self.rank = rank
        self.addresses = addresses
        self._inbox: "queue.Queue" = queue.Queue()
        self._channels: Dict[int, "grpc.Channel"] = {}
        self._lock = threading.Lock()
        self._running = False

        def handle(request: bytes, context) -> bytes:
            _, payload = decode_comm_message(request)
            self._inbox.put(payload)
            return encode_comm_message(self.rank, "message received")

        rpc = grpc.unary_unary_rpc_method_handler(
            handle, request_deserializer=None, response_serializer=None)
        handler = grpc.method_handlers_generic_handler(
            SERVICE, {"sendMessage": rpc})
        opts = [("grpc.max_send_message_length", _MAX_LEN),
                ("grpc.max_receive_message_length", _MAX_LEN)]
        from concurrent import futures
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8),
                                   options=opts)
        self._server.add_generic_rpc_handlers((handler,))
        host, port = addresses[rank]
        self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    def _stub(self, dest: int):
        with self._lock:
            ch = self._channels.get(dest)
            if ch is None:
                host, port = self.addresses[dest]
                opts = [("grpc.max_send_message_length", _MAX_LEN),
                        ("grpc.max_receive_message_length", _MAX_LEN)]
                ch = grpc.insecure_channel(f"{host}:{port}", options=opts)
                self._channels[dest] = ch
            return ch.unary_unary(SEND_METHOD)

    def send_message(self, msg: Message) -> None:
        frame = encode_comm_message(self.rank, message_to_json(msg))
        self._stub(msg.get_receiver_id())(frame, timeout=60)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            item = self._inbox.get()
            if item is _STOP:
                break
            self._notify(message_from_json(item))

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(_STOP)
        self._server.stop(grace=None)
        with self._lock:
            for ch in self._channels.values():
                ch.close()
            self._channels.clear()
