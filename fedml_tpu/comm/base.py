"""Backend and observer ABCs (reference base_com_manager.py:7-27,
observer.py:4-7)."""

from __future__ import annotations

import abc
import os
import threading
from collections import defaultdict
from typing import Dict, Set, Tuple

from fedml_tpu.comm.message import Message

#: per-(sender stream) ``[epoch, seq]`` stamp, written into the message
#: header by the sending backend. Retried frames reuse the stamp (stamping
#: is idempotent), so the receive-side dedup can shed the duplicate a retry
#: of an already-delivered frame creates — the transports' exactly-once
#: contract (comm/reliable.py). The epoch is drawn fresh per endpoint
#: incarnation: a RESTARTED silo's stream starts over at seq 1 under a NEW
#: epoch, so its frames are not mistaken for duplicates of its previous
#: life's.
WIRE_SEQ_KEY = "__wire_seq__"

#: tenancy tag (fedml_tpu/sched/router.py): a frame multiplexed over a
#: shared endpoint carries the job it belongs to. Reliable-delivery
#: streams are keyed per ``(peer, job)`` — two jobs sharing one physical
#: endpoint pair keep INDEPENDENT epoch/seq streams and dedup windows,
#: so job A's restart can never supersede job B's live stream. Absent
#: (the single-tenant default) the stream key degenerates to the peer
#: alone: byte-identical legacy behavior.
WIRE_JOB_KEY = "__wire_job__"

#: dedup window per sender: seqs older than (highest seen - window) are
#: treated as duplicates. 4096 in-flight frames per peer is orders of
#: magnitude beyond the protocol's round-trip pipelining.
_DEDUP_WINDOW = 4096


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type: int, msg: Message) -> None:
        ...


class BaseCommunicationManager(abc.ABC):
    """A transport endpoint for one rank. Backends deliver inbound messages
    by invoking every registered observer (the reference's notify pattern,
    mpi com_manager.py:80-83).

    Wire accounting: backends that encode frames credit
    ``bytes_sent``/``bytes_received`` with the ACTUAL encoded frame
    lengths (header + framing included), so compression ratios are
    measured at the wire, not estimated from array sizes. Backends that
    hand off objects in memory (inproc without the wire codec) have no
    frames and report 0.

    Reliability: sending backends stamp each message with a per-stream
    sequence number (:meth:`_stamp_seq`); :meth:`_notify` drops frames
    whose ``(sender, seq)`` was already delivered, so a transport retry
    (comm/reliable.py) can never double-deliver. Fault-tolerance event
    counts land in :attr:`counters` (``retries``, ``dedup_drops``,
    ``conn_errors``, ...) for the launcher's RoundTimer roll-up.
    """

    def __init__(self) -> None:
        self._observers = []
        self._bytes_lock = threading.Lock()
        self.bytes_sent = 0
        self.bytes_received = 0
        #: fault-tolerance event counters (retries, dedup_drops, ...)
        self.counters: Dict[str, int] = defaultdict(int)
        self._seq_lock = threading.Lock()
        #: this endpoint incarnation's stream epoch (see WIRE_SEQ_KEY)
        self._seq_epoch = int.from_bytes(os.urandom(4), "big")
        #: stream key is (peer, job tag) — see WIRE_JOB_KEY; job is None
        #: on every single-tenant frame
        self._send_seq: Dict[Tuple, int] = defaultdict(int)
        #: (sender, job) -> (epoch, seen seq set, highest seq seen) —
        #: receive dedup
        self._seen: Dict[Tuple, Tuple[int, Set[int], int]] = {}
        #: (sender, job) -> superseded incarnation epochs (late frames
        #: from a previous life must stay dropped, not reopen a window)
        self._old_epochs: Dict[Tuple, Set[int]] = defaultdict(set)
        #: (job, "tx"/"rx") -> bytes: the per-tenant slice of the wire
        #: totals on a shared endpoint (sched/router.py) — what each
        #: JobChannel reports as ITS bytes_sent/bytes_received, so the
        #: per-job SLO/billing accounting is real frame lengths, not
        #: zeros. Two ints per job ever seen; deliberately NOT purged
        #: with the job's streams (the launcher's final wire credit
        #: runs after FINISH stops the channel).
        self._job_bytes: Dict[Tuple, int] = defaultdict(int)
        #: (job, counter name) -> count: the per-tenant slice of the
        #: fault-tolerance event counters, credited at the sites where
        #: the frame (and so its job tag) is in hand — send retries,
        #: dedup drops. Same non-purged lifetime as _job_bytes.
        self._job_counters: Dict[Tuple, int] = defaultdict(int)

    def _count_sent(self, n: int, job=None) -> None:
        with self._bytes_lock:
            self.bytes_sent += int(n)
            if job is not None:
                self._job_bytes[(job, "tx")] += int(n)

    def _count_received(self, n: int, job=None) -> None:
        with self._bytes_lock:
            self.bytes_received += int(n)
            if job is not None:
                self._job_bytes[(job, "rx")] += int(n)

    def _credit_job_received(self, n: int, job) -> None:
        """Per-job slice ONLY — for backends whose raw inbound frames
        are counted on the socket thread, before decode reveals the
        job tag (tcp/grpc)."""
        if job is None:
            return
        with self._bytes_lock:
            self._job_bytes[(job, "rx")] += int(n)

    def job_bytes(self, job) -> Tuple[int, int]:
        """(sent, received) bytes carried for ``job`` on this endpoint."""
        with self._bytes_lock:
            return (self._job_bytes.get((job, "tx"), 0),
                    self._job_bytes.get((job, "rx"), 0))

    def bump(self, name: str, n: int = 1, job=None) -> None:
        """Increment a fault-tolerance event counter; ``job`` (when the
        event's frame is in hand) also credits the tenant's slice."""
        with self._bytes_lock:
            self.counters[name] += int(n)
            if job is not None:
                self._job_counters[(job, name)] += int(n)

    def job_counters(self, job) -> Dict[str, int]:
        """``job``'s slice of the fault-tolerance event counters."""
        with self._bytes_lock:
            return {name: v for (j, name), v in self._job_counters.items()
                    if j == job}

    # -- reliable-delivery bookkeeping --------------------------------------
    def _stamp_seq(self, msg: Message) -> None:
        """Assign the next per-destination-stream sequence number.

        Idempotent: a message that already carries a stamp keeps it, so a
        retried or fault-injected duplicate ships the SAME seq and the
        receiver's dedup drops the extra copy.
        """
        if WIRE_SEQ_KEY in msg.msg_params:
            return
        stream = (msg.get_receiver_id(), msg.msg_params.get(WIRE_JOB_KEY))
        with self._seq_lock:
            self._send_seq[stream] += 1
            seq = self._send_seq[stream]
        msg.add(WIRE_SEQ_KEY, [self._seq_epoch, seq])

    def _accept(self, msg: Message) -> bool:
        """Receive-side dedup: True iff this ``(sender, epoch, seq)`` has
        not been delivered before (unstamped legacy messages always pass).
        A new epoch from a sender — a restarted silo — resets that
        sender's window; frames from its previous incarnation still in
        flight are dropped as stale."""
        stamp = msg.msg_params.get(WIRE_SEQ_KEY)
        if stamp is None:
            return True
        epoch, seq = int(stamp[0]), int(stamp[1])
        stream = (msg.get_sender_id(), msg.msg_params.get(WIRE_JOB_KEY))
        with self._seq_lock:
            cur_epoch, seen, high = self._seen.get(stream,
                                                   (None, set(), 0))
            if epoch in self._old_epochs[stream]:
                return False  # late frame from a superseded incarnation
            if cur_epoch is not None and epoch != cur_epoch:
                # fresh incarnation: supersede the old stream, reset window
                self._old_epochs[stream].add(cur_epoch)
                seen, high = set(), 0
            if seq in seen or seq <= high - _DEDUP_WINDOW:
                return False
            seen.add(seq)
            high = max(high, seq)
            # prune the window so long federations stay O(window) memory
            if len(seen) > 2 * _DEDUP_WINDOW:
                floor = high - _DEDUP_WINDOW
                seen = {s for s in seen if s > floor}
            self._seen[stream] = (epoch, seen, high)
        return True

    def purge_streams(self, job) -> None:
        """Drop the heavy reliable-delivery stream state whose job tag
        equals ``job`` — a finished tenant on a shared endpoint
        (sched/router.py). A relaunched job opens fresh streams under a
        new channel epoch, so the seq windows are never consulted
        again; keeping them would leak one dedup window per
        ``(peer, job)`` ever seen on a persistent fabric. The purged
        incarnation's epoch is folded into ``_old_epochs`` (ints only)
        rather than dropped: a late transport-retried frame from the
        dead incarnation must stay dropped — if its epoch were
        forgotten, ``_accept`` would treat the RELAUNCHED job's live
        epoch as the superseded one and wedge the new stream."""
        with self._seq_lock:
            for k in [k for k in self._send_seq if k[1] == job]:
                del self._send_seq[k]
            for k in [k for k in self._seen if k[1] == job]:
                epoch = self._seen[k][0]
                if epoch is not None:
                    self._old_epochs[k].add(epoch)
                del self._seen[k]

    @abc.abstractmethod
    def send_message(self, msg: Message) -> None:
        ...

    def broadcast(self, msgs, on_error=None) -> Dict[str, int]:
        """Send one message per peer, surfacing per-peer failures without
        aborting the rest of the fan-out.

        Contract: with ``on_error`` set, a peer's failure (``OSError``
        family, which includes ``TransportError``) is reported as
        ``on_error(receiver_id, exc)`` and the remaining sends proceed —
        the caller's eviction path replaces the raise. ``on_error`` MAY be
        invoked on a writer thread (overlapped backends) and MAY arrive
        after this call returns; callers synchronize their own state.
        Without ``on_error`` the first failure propagates, matching a
        plain ``send_message`` loop.

        This default runs sequentially (correct for object hand-off and
        wrapper backends); overlapped transports override it to enqueue on
        per-peer writer threads and return after enqueue. Returns fan-out
        stats: ``enqueued`` (messages accepted) and ``max_queue_depth``
        (peak per-peer send-queue depth observed; 0 when sends complete
        inline).
        """
        enqueued = 0
        for msg in msgs:
            try:
                self.send_message(msg)
            except OSError as exc:
                if on_error is None:
                    raise
                on_error(msg.get_receiver_id(), exc)
            enqueued += 1
        return {"enqueued": enqueued, "max_queue_depth": 0}

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def _notify(self, msg: Message) -> None:
        if not self._accept(msg):
            self.bump("dedup_drops", job=msg.msg_params.get(WIRE_JOB_KEY))
            return
        for obs in list(self._observers):
            obs.receive_message(msg.get_type(), msg)

    @abc.abstractmethod
    def handle_receive_message(self) -> None:
        """Block, dispatching inbound messages to observers, until stopped."""
        ...

    @abc.abstractmethod
    def stop_receive_message(self) -> None:
        ...
