"""Backend and observer ABCs (reference base_com_manager.py:7-27,
observer.py:4-7)."""

from __future__ import annotations

import abc
import threading

from fedml_tpu.comm.message import Message


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type: int, msg: Message) -> None:
        ...


class BaseCommunicationManager(abc.ABC):
    """A transport endpoint for one rank. Backends deliver inbound messages
    by invoking every registered observer (the reference's notify pattern,
    mpi com_manager.py:80-83).

    Wire accounting: backends that encode frames credit
    ``bytes_sent``/``bytes_received`` with the ACTUAL encoded frame
    lengths (header + framing included), so compression ratios are
    measured at the wire, not estimated from array sizes. Backends that
    hand off objects in memory (inproc without the wire codec) have no
    frames and report 0.
    """

    def __init__(self) -> None:
        self._observers = []
        self._bytes_lock = threading.Lock()
        self.bytes_sent = 0
        self.bytes_received = 0

    def _count_sent(self, n: int) -> None:
        with self._bytes_lock:
            self.bytes_sent += int(n)

    def _count_received(self, n: int) -> None:
        with self._bytes_lock:
            self.bytes_received += int(n)

    @abc.abstractmethod
    def send_message(self, msg: Message) -> None:
        ...

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def _notify(self, msg: Message) -> None:
        for obs in list(self._observers):
            obs.receive_message(msg.get_type(), msg)

    @abc.abstractmethod
    def handle_receive_message(self) -> None:
        """Block, dispatching inbound messages to observers, until stopped."""
        ...

    @abc.abstractmethod
    def stop_receive_message(self) -> None:
        ...
