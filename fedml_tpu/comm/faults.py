"""Seeded chaos harness: declarative fault injection for any comm backend.

Production federated systems treat device failure as the common case
(Bonawitz et al., MLSys 2019) — but a recovery path that is never
EXERCISED is a recovery path that does not work. ``FaultyCommManager``
wraps any ``BaseCommunicationManager`` and injects faults from a seeded,
declarative :class:`FaultPlan`:

- ``drop``       — the message never reaches the transport
- ``delay``      — the send is deferred ``delay_ms`` (reorders streams)
- ``duplicate``  — the message is sent twice with the SAME wire seq
                   (the receive-side dedup must shed the copy)
- ``corrupt``    — array bytes of the encoded frame are bit-flipped
                   (header/scalars stay intact, so the payload decodes
                   into garbage the payload-level guards must catch)
- ``disconnect`` — the endpoint goes dark for ``duration_ms`` (both
                   directions), emulating a link partition

Every draw comes from one ``random.Random`` seeded from
``(plan.seed, rank)``, so a chaos run replays bit-identically. An EMPTY
plan is a pure pass-through: no RNG draws, no copies — bit-exact with the
unwrapped backend (tested). Exposed as ``--fault_plan`` on the launchers
(a DSL string, inline JSON, or a .json path) and as the
``cross_silo_faults`` bench stage.

DSL: rules separated by ``;``, each ``op:key=val,key=val``; a bare
``seed=N`` token sets the plan seed. Example::

    seed=7;drop:p=0.1,msg_type=4;delay:p=0.2,delay_ms=50;duplicate:p=0.3

Self-addressed messages (the quorum/deadline servers' timer ticks) are
exempt unless a rule sets ``include_self=1`` — faulting the server's own
clock would test the harness, not the protocol.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field, fields
from typing import Dict, List, Optional, Sequence, Union

from fedml_tpu.comm.message import Message

_OPS = ("drop", "delay", "duplicate", "corrupt", "disconnect")


@dataclass(frozen=True)
class FaultRule:
    """One declarative fault: WHAT (`op`), WHEN (`p`/`after`/`max_count`),
    and WHICH messages (sender/receiver/msg_type/direction filters;
    ``None`` matches everything)."""

    op: str
    p: float = 1.0
    delay_ms: float = 0.0        # delay op
    duration_ms: float = 100.0   # disconnect op
    msg_type: Optional[int] = None
    sender: Optional[int] = None
    receiver: Optional[int] = None
    direction: str = "send"      # send | recv
    after: int = 0               # skip the first N matching messages
    max_count: Optional[int] = None  # stop injecting after N faults
    include_self: bool = False   # match self-addressed (timer) messages

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(f"unknown fault op {self.op!r} "
                             f"(one of {', '.join(_OPS)})")
        if self.direction not in ("send", "recv"):
            raise ValueError(f"fault direction must be send|recv, "
                             f"got {self.direction!r}")
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(f"fault p must be in [0, 1], got {self.p}")

    def matches(self, msg: Message, direction: str) -> bool:
        if self.direction != direction:
            return False
        if not self.include_self \
                and msg.get_sender_id() == msg.get_receiver_id():
            return False
        if self.msg_type is not None and msg.get_type() != self.msg_type:
            return False
        if self.sender is not None and msg.get_sender_id() != self.sender:
            return False
        return self.receiver is None \
            or msg.get_receiver_id() == self.receiver


_RULE_FIELDS = {f.name for f in fields(FaultRule)}
_INT_FIELDS = {"msg_type", "sender", "receiver", "after", "max_count"}
_BOOL_FIELDS = {"include_self"}


@dataclass(frozen=True)
class FaultPlan:
    """A seeded set of fault rules. ``empty`` plans wrap to a pure
    pass-through."""

    seed: int = 0
    rules: Sequence[FaultRule] = field(default_factory=tuple)

    def __post_init__(self):
        object.__setattr__(self, "rules", tuple(self.rules))

    @property
    def empty(self) -> bool:
        return not self.rules

    def rng_for(self, rank: int) -> random.Random:
        """One deterministic stream per endpoint: decorrelated across
        ranks, replayable across runs."""
        return random.Random((int(self.seed) * 1_000_003
                              + int(rank)) ^ 0x5EED)


def _coerce(key: str, val: str):
    if key not in _RULE_FIELDS:
        raise ValueError(f"unknown fault-rule key {key!r} "
                         f"(known: {sorted(_RULE_FIELDS)})")
    if key in ("op", "direction"):
        return val
    if key in _BOOL_FIELDS:
        return str(val).strip().lower() in ("1", "true", "yes")
    if key in _INT_FIELDS:
        return int(val)
    return float(val)


def parse_fault_plan(spec: Union[None, str, dict, list, FaultPlan],
                     seed: int = 0) -> Optional[FaultPlan]:
    """``--fault_plan`` front door: accepts an existing plan, inline JSON
    (``{"seed":1,"rules":[...]}`` or a bare rule list), a path to a .json
    file, or the compact DSL (module docstring). Returns ``None`` for
    no-plan specs so launchers can skip the wrapper entirely."""
    if spec is None or isinstance(spec, FaultPlan):
        return spec
    if isinstance(spec, (dict, list)):
        return _plan_from_obj(spec, seed)
    s = str(spec).strip()
    if not s:
        return None
    if s.startswith("{") or s.startswith("["):
        return _plan_from_obj(json.loads(s), seed)
    if s.endswith(".json"):
        if not os.path.exists(s):
            raise FileNotFoundError(f"--fault_plan file not found: {s}")
        with open(s, "r", encoding="utf-8") as fh:
            return _plan_from_obj(json.load(fh), seed)
    rules: List[FaultRule] = []
    for token in s.split(";"):
        token = token.strip()
        if not token:
            continue
        if token.startswith("seed="):
            seed = int(token.split("=", 1)[1])
            continue
        op, _, rest = token.partition(":")
        kw = {"op": op.strip()}
        for pair in filter(None, (p.strip() for p in rest.split(","))):
            k, _, v = pair.partition("=")
            kw[k.strip()] = _coerce(k.strip(), v.strip())
        rules.append(FaultRule(**kw))
    return FaultPlan(seed=seed, rules=rules)


def merge_plans(a: Union[None, str, dict, list, FaultPlan],
                b: Union[None, str, dict, list, FaultPlan]
                ) -> Optional[FaultPlan]:
    """Compose two fault plans into ONE schedule — message-level chaos
    and population-level churn running together (the WAN layer,
    ``fedml_tpu/wan``, merges its trace-driven outage rules into a
    user's ``--fault_plan`` through here). Rules concatenate in order
    (first plan's rules match first, same as within one plan); the
    first non-empty plan's seed keys every endpoint's RNG stream.
    ``None``/empty operands pass through, so composing with nothing is
    the identity."""
    a = parse_fault_plan(a)
    b = parse_fault_plan(b)
    if a is None or a.empty:
        return b
    if b is None or b.empty:
        return a
    return FaultPlan(seed=a.seed, rules=(*a.rules, *b.rules))


def _plan_from_obj(obj, seed: int) -> FaultPlan:
    if isinstance(obj, list):
        obj = {"rules": obj}
    rules = [FaultRule(**{k: r[k] for k in r}) for r in obj.get("rules", ())]
    return FaultPlan(seed=int(obj.get("seed", seed)), rules=rules)


def server_kill_plan(seed: int = 0, after_broadcasts: int = 2,
                     down_ms: float = 2000.0,
                     extra_rules: Sequence[FaultRule] = ()) -> FaultPlan:
    """The canonical SERVER-KILL chaos scenario (in-process leg): after
    ``after_broadcasts`` SYNC broadcasts leave the server, its endpoint
    goes completely dark for ``down_ms`` — nothing in, nothing out — the
    fleet's view of a server crash. Self-addressed deadline ticks stay
    exempt (the server's own clock survives a network death; a REAL
    process death is the failover harness's SIGKILL leg,
    ``fedml_tpu/control/failover_harness.py``, which also exercises
    checkpoint restore). ``extra_rules`` compose silo flap/duplicate
    noise into the same seeded plan."""
    kill = FaultRule(op="disconnect", direction="send", sender=0,
                     msg_type=2, after=after_broadcasts, max_count=1,
                     duration_ms=down_ms)
    return FaultPlan(seed=seed, rules=(kill, *extra_rules))


def _corrupt_frame(msg: Message, rng: random.Random) -> Optional[Message]:
    """Bit-flip array bytes of the encoded frame; header + scalars stay
    intact so the frame still DECODES — into garbage the payload-level
    guards (compression fingerprints, top-k index bounds) must reject.
    Returns None when the message carries no array bytes to corrupt."""
    import struct
    frame = bytearray(msg.to_bytes())
    (hlen,) = struct.unpack_from("<I", frame, 0)
    body_start = 4 + hlen
    body_len = len(frame) - body_start
    if body_len <= 0:
        return None
    n_flips = max(8, body_len // 64)
    for _ in range(n_flips):
        frame[body_start + rng.randrange(body_len)] ^= 0xFF
    out = Message.from_bytes(bytes(frame))
    return out


class FaultyCommManager:
    """Duck-typed ``BaseCommunicationManager`` wrapper injecting faults.

    Not a subclass: byte accounting and seq dedup belong to the INNER
    backend (the wrapper sits above the reliability layer, where a chaos
    plan can exercise it); the wrapper only owns fault state and its own
    observer list. An empty plan forwards every call untouched.
    """

    def __init__(self, inner, plan: FaultPlan, rank: int):
        self.inner = inner
        self.plan = plan
        self.rank = rank
        self._rng = plan.rng_for(rank)
        self._rng_lock = threading.Lock()
        self._observers: list = []
        self._matched = defaultdict(int)   # rule idx -> messages matched
        self._fired = defaultdict(int)     # rule idx -> faults injected
        self._down_until = 0.0
        self.counters: Dict[str, int] = defaultdict(int)
        #: (job, counter name) -> count: the wrapper's own per-tenant
        #: slice (see BaseCommunicationManager._job_counters)
        self._job_counters: Dict[tuple, int] = defaultdict(int)
        inner.add_observer(_InnerTap(self))

    # -- byte accounting: the inner backend owns the wire ------------------
    @property
    def bytes_sent(self) -> int:
        return self.inner.bytes_sent

    @property
    def bytes_received(self) -> int:
        return self.inner.bytes_received

    def job_bytes(self, job):
        return self.inner.job_bytes(job)

    def job_counters(self, job):
        out = dict(self.inner.job_counters(job))
        with self._rng_lock:
            for (j, name), v in self._job_counters.items():
                if j == job:
                    out[name] = out.get(name, 0) + int(v)
        return out

    def purge_streams(self, job) -> None:
        self.inner.purge_streams(job)

    def bump(self, name: str, n: int = 1, job=None) -> None:
        with self._rng_lock:
            self.counters[name] += int(n)
            if job is not None:
                self._job_counters[(job, name)] += int(n)

    def all_counters(self) -> Dict[str, int]:
        """Wrapper fault counts merged with the inner backend's transport
        counters (retries, dedup_drops, ...)."""
        out = dict(getattr(self.inner, "counters", {}))
        for k, v in self.counters.items():
            out[k] = out.get(k, 0) + v
        return out

    # -- fault engine ------------------------------------------------------
    def _pick(self, msg: Message, direction: str) -> Optional[FaultRule]:
        if self.plan.empty:
            return None
        # ft: allow[FT015] chaos outage windows are wall-clock by design; determinism comes from the seeded FaultPlan, not the clock
        if time.monotonic() < self._down_until \
                and msg.get_sender_id() != msg.get_receiver_id():
            # inside a disconnect window: everything on the WIRE is lost,
            # both ways — but self-addressed messages (the deadline
            # servers' timer ticks) never leave the process, so the same
            # exemption FaultRule.matches applies holds here: eating the
            # tick would hang exactly the round the deadline exists to
            # close
            return FaultRule(op="drop", direction=direction)
        for i, rule in enumerate(self.plan.rules):
            if not rule.matches(msg, direction):
                continue
            with self._rng_lock:
                self._matched[i] += 1
                if self._matched[i] <= rule.after:
                    continue
                if rule.max_count is not None \
                        and self._fired[i] >= rule.max_count:
                    continue
                if rule.p < 1.0 and self._rng.random() >= rule.p:
                    continue
                self._fired[i] += 1
            return rule
        return None

    def _apply_send(self, msg: Message, rule: FaultRule) -> None:
        self.bump("faults_injected")
        self.bump(f"fault_{rule.op}")
        if rule.op == "drop":
            return
        if rule.op == "disconnect":
            self._down_until = time.monotonic() + rule.duration_ms / 1e3
            return  # the triggering message is the partition's first loss
        if rule.op == "delay":
            t = threading.Timer(rule.delay_ms / 1e3,
                                self._late_send, args=(msg,))
            t.daemon = True
            t.start()
            return
        if rule.op == "duplicate":
            # inner stamps the seq on the FIRST send and stamping is
            # idempotent — the copy ships the same seq and the receiver's
            # dedup must shed it
            self.inner.send_message(msg)
            self.inner.send_message(msg)
            return
        if rule.op == "corrupt":
            with self._rng_lock:
                bad = _corrupt_frame(msg, self._rng)
            self.inner.send_message(bad if bad is not None else msg)
            return

    def _late_send(self, msg: Message) -> None:
        try:
            self.inner.send_message(msg)
        except Exception:  # delayed past shutdown: log, don't kill the timer thread
            logging.warning("fault-injected delayed send failed "
                            "(backend shut down?)", exc_info=True)

    # -- BaseCommunicationManager surface ----------------------------------
    def send_message(self, msg: Message) -> None:
        rule = self._pick(msg, "send")
        if rule is None:
            self.inner.send_message(msg)
            return
        self._apply_send(msg, rule)

    def broadcast(self, msgs, on_error=None) -> Dict[str, int]:
        """Fan-out THROUGH the fault engine: each per-peer message takes
        the wrapper's own send path (so drop/delay/corrupt rules apply per
        peer), sequentially — chaos runs trade fan-out overlap for
        deterministic fault application. Same per-peer error contract as
        ``BaseCommunicationManager.broadcast``."""
        enqueued = 0
        for msg in msgs:
            try:
                self.send_message(msg)
            except OSError as exc:
                if on_error is None:
                    raise
                on_error(msg.get_receiver_id(), exc)
            enqueued += 1
        return {"enqueued": enqueued, "max_queue_depth": 0}

    def add_observer(self, observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer) -> None:
        self._observers.remove(observer)

    def _deliver(self, msg: Message) -> None:
        """Inbound path (called by the inner backend's notify): apply
        recv-side rules, then dispatch to this wrapper's observers."""
        rule = self._pick(msg, "recv")
        if rule is not None:
            self.bump("faults_injected")
            self.bump(f"fault_{rule.op}")
            if rule.op == "drop":
                return
            if rule.op == "disconnect":
                self._down_until = (time.monotonic()
                                    + rule.duration_ms / 1e3)
                return
            if rule.op == "duplicate":
                # injected ABOVE the transport dedup, so observers see the
                # copy — exercises protocol-level idempotence
                self._dispatch(msg)
            elif rule.op == "corrupt":
                with self._rng_lock:
                    bad = _corrupt_frame(msg, self._rng)
                if bad is not None:
                    msg = bad
            elif rule.op == "delay":
                time.sleep(rule.delay_ms / 1e3)
        self._dispatch(msg)

    def _dispatch(self, msg: Message) -> None:
        for obs in list(self._observers):
            obs.receive_message(msg.get_type(), msg)

    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        self.inner.stop_receive_message()


class _InnerTap:
    """Observer bridging the inner backend's notify to the wrapper's
    recv-side fault path (kept tiny: the wrapper itself must not BE the
    observer so user observers added to the wrapper are isolated from the
    inner backend's list)."""

    def __init__(self, wrapper: FaultyCommManager):
        self._wrapper = wrapper

    def receive_message(self, msg_type: int, msg: Message) -> None:
        self._wrapper._deliver(msg)
