"""Backend selection by string — reference ``--backend MPI|GRPC|MQTT``
switch (client_manager.py:22-35) re-keyed to the TPU-era transports."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.inproc import InProcCommManager, InProcRouter


def create_comm_manager(
        backend: str, rank: int, size: int,
        router: Optional[InProcRouter] = None,
        addresses: Optional[Dict[int, Tuple[str, int]]] = None,
        wire_codec: bool = False,
        token: Optional[bytes] = None,
        fault_plan=None) -> BaseCommunicationManager:
    """``backend``: "INPROC" (simulation/tests), "TCP" (framed sockets,
    cross-host), "GRPC" (cross-silo RPC), "ROUTED" (dial-out frames through
    the native C++ broker, native/router.cpp — the NAT-friendly star
    topology of the reference's MQTT path). The reference's "MPI" maps to
    INPROC for single-host and TCP for multi-host.

    ``fault_plan`` (a ``comm.faults.FaultPlan``, DSL string, or JSON —
    see ``parse_fault_plan``) wraps the endpoint in the seeded chaos
    harness; ``None``/empty specs return the bare backend untouched."""
    from fedml_tpu.comm.faults import FaultyCommManager, parse_fault_plan
    plan = parse_fault_plan(fault_plan)

    def wrap(inner):
        if plan is None or plan.empty:
            return inner
        return FaultyCommManager(inner, plan, rank)

    key = backend.upper()
    if key in ("ROUTED", "BROKER"):
        if addresses is None or "router" not in addresses:
            raise ValueError(
                'ROUTED backend needs addresses={"router": (host, port)}')
        from fedml_tpu.comm.routed import RoutedCommManager
        return wrap(RoutedCommManager(rank, addresses["router"],
                                      token=token))
    if key in ("INPROC", "MPI"):
        if router is None:
            raise ValueError("INPROC backend needs a shared InProcRouter")
        return wrap(InProcCommManager(router, rank, size,
                                      wire_codec=wire_codec))
    if key == "TCP":
        if addresses is None:
            raise ValueError("TCP backend needs {rank: (host, port)}")
        from fedml_tpu.comm.tcp import TcpCommManager
        return wrap(TcpCommManager(rank, addresses))
    if key == "GRPC":
        if addresses is None:
            raise ValueError("GRPC backend needs {rank: (host, port)}")
        from fedml_tpu.comm.grpc_backend import GrpcCommManager
        return wrap(GrpcCommManager(rank, addresses))
    if key == "GRPC_PROTO":
        # reference-wire-compatible mode (grpc_comm_manager.proto)
        if addresses is None:
            raise ValueError("GRPC_PROTO backend needs {rank: (host, port)}")
        from fedml_tpu.comm.grpc_proto import ProtoGrpcCommManager
        return wrap(ProtoGrpcCommManager(rank, addresses))
    if key == "MQTT":
        # broker pub/sub with the reference topic scheme + JSON payloads
        if addresses is None or "broker" not in addresses:
            raise ValueError(
                'MQTT backend needs addresses={"broker": (host, port)}')
        from fedml_tpu.comm.mqtt import MqttCommManager
        host, port = addresses["broker"]
        return wrap(MqttCommManager(host, port, client_id=rank,
                                    client_num=size - 1))
    raise ValueError(f"unknown backend: {backend!r}")
