"""Backend selection by string — reference ``--backend MPI|GRPC|MQTT``
switch (client_manager.py:22-35) re-keyed to the TPU-era transports."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.inproc import InProcCommManager, InProcRouter


def create_comm_manager(
        backend: str, rank: int, size: int,
        router: Optional[InProcRouter] = None,
        addresses: Optional[Dict[int, Tuple[str, int]]] = None,
        wire_codec: bool = False,
        token: Optional[bytes] = None) -> BaseCommunicationManager:
    """``backend``: "INPROC" (simulation/tests), "TCP" (framed sockets,
    cross-host), "GRPC" (cross-silo RPC), "ROUTED" (dial-out frames through
    the native C++ broker, native/router.cpp — the NAT-friendly star
    topology of the reference's MQTT path). The reference's "MPI" maps to
    INPROC for single-host and TCP for multi-host."""
    key = backend.upper()
    if key in ("ROUTED", "BROKER"):
        if addresses is None or "router" not in addresses:
            raise ValueError(
                'ROUTED backend needs addresses={"router": (host, port)}')
        from fedml_tpu.comm.routed import RoutedCommManager
        return RoutedCommManager(rank, addresses["router"], token=token)
    if key in ("INPROC", "MPI"):
        if router is None:
            raise ValueError("INPROC backend needs a shared InProcRouter")
        return InProcCommManager(router, rank, size, wire_codec=wire_codec)
    if key == "TCP":
        if addresses is None:
            raise ValueError("TCP backend needs {rank: (host, port)}")
        from fedml_tpu.comm.tcp import TcpCommManager
        return TcpCommManager(rank, addresses)
    if key in ("GRPC", "MQTT"):
        if addresses is None:
            raise ValueError("GRPC backend needs {rank: (host, port)}")
        from fedml_tpu.comm.grpc_backend import GrpcCommManager
        return GrpcCommManager(rank, addresses)
    raise ValueError(f"unknown backend: {backend!r}")
