"""Cross-silo communication layer (reference L1: fedml_core/distributed/communication).

Intra-slice federated rounds need no messages at all — they compile to XLA
collectives over ICI (fedml_tpu/parallel/spmd.py). This package exists for the
cases where collectives cannot reach: different trust domains (cross-silo FL),
different hosts without a shared mesh, and on-device/mobile-style actors. It
keeps the reference's contracts (Message / Observer /
BaseCommunicationManager / ClientManager / ServerManager — SURVEY §1 L1/L2)
so algorithm protocol code is backend-agnostic, and replaces the reference's
three transports (mpi4py / gRPC-with-hardcoded-IPs / MQTT) with:

- ``inproc``  — zero-copy in-process router (tests, standalone multi-actor)
- ``tcp``     — length-prefixed framed sockets, cross-host
- ``grpc``    — insecure-channel gRPC with addresses from config, wire-
                compatible in spirit with the reference proto
                (gRPC/proto/grpc_comm_manager.proto)

Payloads are pytrees of arrays serialized with a zero-copy binary codec
(fedml_tpu/comm/serialization.py) rather than pickled dicts (the reference's
MPI path) or JSON-ified float lists (its MQTT path).
"""

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.faults import (FaultPlan, FaultRule, FaultyCommManager,
                                   parse_fault_plan)
from fedml_tpu.comm.manager import ClientManager, ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.registry import create_comm_manager
from fedml_tpu.comm.reliable import RetryPolicy, TransportError

__all__ = [
    "BaseCommunicationManager", "Observer", "Message", "ClientManager",
    "ServerManager", "create_comm_manager", "FaultPlan", "FaultRule",
    "FaultyCommManager", "parse_fault_plan", "RetryPolicy",
    "TransportError",
]
