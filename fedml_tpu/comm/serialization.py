"""Zero-copy pytree <-> bytes codec for cross-silo transport.

The reference ships model state as pickled torch ``state_dict``s over MPI
(mpi_send_thread.py:27) or JSON float-lists over MQTT
(fedavg/utils.py:12 ``transform_tensor_to_list``) — both copy and re-encode
every float. Here a payload pytree of numpy/jax arrays becomes:

    [u32 header_len][msgpack header][raw buffer 0][raw buffer 1]...

where the header records the treedef (as a nested spec with leaf slots) and
each leaf's dtype/shape. Decoding builds numpy views straight into the
received buffer — no per-element work, no copies beyond the socket read.
Scalars, strings, bools and None ride in the header itself.
"""

from __future__ import annotations

import struct
import threading
from typing import Any, List, Optional, Tuple

import msgpack
import numpy as np

_LEAF = "__leaf__"  # marker: {"__leaf__": buffer_index, "dtype", "shape"}


class SharedPayload:
    """Encode-once wrapper for a payload fanned out to N peers.

    A broadcast sends ONE model pytree to every silo, but each per-peer
    ``Message`` re-walked and re-encoded the whole tree — O(N * model)
    header/copy work per round. Wrapping the payload in a SharedPayload
    makes ``_encode`` splice the cached (spec, buffers) pair instead of
    re-walking: the tree is encoded exactly once per wrapper instance,
    per-peer frames differ only in their small envelope keys, and the
    emitted bytes are identical to the uncached encoder's output (the
    dedup/replay layer keys on frame content, so byte-parity is load-
    bearing, not cosmetic). Cache invalidation is by construction: each
    round's ``_broadcast_model`` wraps a fresh instance.

    Thread-safe: concurrent ``to_parts`` calls (per-peer writer threads)
    race to encode; the lock makes the first one win and the rest reuse.
    """

    __slots__ = ("value", "_lock", "_spec", "_buffers", "encode_count")

    def __init__(self, value: Any):
        self.value = value
        self._lock = threading.Lock()
        self._spec: Optional[Any] = None
        self._buffers: Optional[List[bytes]] = None
        self.encode_count = 0  # test hook: encodes actually performed

    def _encoded(self) -> Tuple[Any, List[bytes]]:
        with self._lock:
            if self._spec is None:
                buffers: List[bytes] = []
                self._spec = _encode(self.value, buffers)
                self._buffers = buffers
                self.encode_count += 1
            return self._spec, self._buffers


def _rebase(spec: Any, base: int) -> Any:
    """Copy of ``spec`` with every ``_LEAF`` buffer index shifted by
    ``base`` — needed when a cached subtree is spliced into a frame that
    already emitted buffers before it."""
    t = spec["t"]
    if t == "d":
        return {"t": "d", "k": spec["k"],
                "v": [_rebase(v, base) for v in spec["v"]]}
    if t in ("l", "u"):
        return {"t": t, "v": [_rebase(v, base) for v in spec["v"]]}
    if t == "a":
        out = dict(spec)
        out[_LEAF] = spec[_LEAF] + base
        return out
    return spec


def _encode(obj: Any, buffers: List[bytes]) -> Any:
    if isinstance(obj, SharedPayload):
        spec, bufs = obj._encoded()
        base = len(buffers)
        buffers.extend(bufs)
        # envelope keys are scalars, so base is 0 in practice and the
        # cached spec embeds as-is; rebase covers arrays-before-payload
        return spec if base == 0 else _rebase(spec, base)
    if isinstance(obj, dict):
        return {"t": "d", "k": list(obj.keys()),
                "v": [_encode(v, buffers) for v in obj.values()]}
    if isinstance(obj, (list, tuple)):
        return {"t": "l" if isinstance(obj, list) else "u",
                "v": [_encode(v, buffers) for v in obj]}
    if hasattr(obj, "__array__") and not np.isscalar(obj):
        arr = np.asarray(obj)
        # the TRUE shape, captured before ascontiguousarray (which
        # promotes 0-d to (1,)) — shape fidelity feeds the compression
        # layer's structure fingerprints
        shape = list(arr.shape)
        arr = np.ascontiguousarray(arr)
        # flat byte view (len == nbytes even for ndim>1), no copy; a
        # zero-size leaf has no castable view — ship an empty buffer slot
        buffers.append(arr.data.cast("B") if arr.size else b"")
        return {"t": "a", _LEAF: len(buffers) - 1, "dtype": arr.dtype.str,
                "shape": shape}
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return {"t": "s", "v": obj}
    if isinstance(obj, (np.integer, np.floating, np.bool_)):
        return {"t": "s", "v": obj.item()}
    raise TypeError(f"unserializable payload leaf: {type(obj)}")


def _decode(spec: Any, buffers: List[memoryview]) -> Any:
    t = spec["t"]
    if t == "d":
        return {k: _decode(v, buffers)
                for k, v in zip(spec["k"], spec["v"])}
    if t == "l":
        return [_decode(v, buffers) for v in spec["v"]]
    if t == "u":
        return tuple(_decode(v, buffers) for v in spec["v"])
    if t == "a":
        buf = buffers[spec[_LEAF]]
        return np.frombuffer(buf, dtype=np.dtype(spec["dtype"])).reshape(
            spec["shape"])
    return spec["v"]


#: the frame header is length-prefixed with a u32 — a header that does not
#: fit would silently truncate its own length field and desync every
#: subsequent frame on the stream, so refuse loudly instead. (Raw array
#: buffers are NOT subject to this cap: they ride after the header and the
#: transports use u64 frame lengths.)
_MAX_HEADER = (1 << 32) - 1


def dumps_parts(tree: Any) -> List[Any]:
    """Serialize to the frame's constituent buffers WITHOUT joining them:
    ``[u32 len][msgpack header][raw buffer 0][raw buffer 1]...`` as a list.

    Chunk-aware transports (tcp.send_frame, the gRPC streaming call) write
    the parts straight to the socket, so a multi-hundred-MB model update
    never materializes as one contiguous copy on the send path.
    """
    buffers: List[bytes] = []
    spec = _encode(tree, buffers)
    header = msgpack.packb(
        {"spec": spec, "sizes": [len(b) for b in buffers]})
    if len(header) > _MAX_HEADER:
        raise ValueError(
            f"serialized header is {len(header)} bytes — larger than the "
            "u32 length prefix can carry; refusing to emit a torn frame "
            "(payload metadata this large means a pathological tree, not "
            "a big model: array bytes don't count against this cap)")
    return [struct.pack("<I", len(header)), header, *buffers]


def dumps(tree: Any) -> bytes:
    """Serialize a pytree of arrays/scalars into one contiguous frame."""
    return b"".join(dumps_parts(tree))


def loads(frame) -> Any:
    """Decode a frame produced by ``dumps`` with numpy views into ``frame``
    (any buffer type: bytes, bytearray, memoryview)."""
    view = memoryview(frame)
    (hlen,) = struct.unpack_from("<I", view, 0)
    header = msgpack.unpackb(bytes(view[4:4 + hlen]))
    buffers: List[memoryview] = []
    off = 4 + hlen
    for size in header["sizes"]:
        buffers.append(view[off:off + size])
        off += size
    return _decode(header["spec"], buffers)
