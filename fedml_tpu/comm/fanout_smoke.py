"""CI fan-out smoke: the round hot path against a genuinely slow peer.

Two stages, both over REAL TCP sockets on localhost, exit non-zero on
any violated contract:

1. **Transport backpressure** — a 4-peer broadcast of one multi-MB
   shared payload where one peer accepts its connection but does not
   read for ``STALL_S`` seconds (kernel socket buffers fill; a
   sequential fan-out would sit in ``sendall`` for the whole stall).
   Required: the ``broadcast()`` call returns in a fraction of the
   stall, every FAST peer holds its complete frame while the slow peer
   is still stalled, the slow peer's frame fully drains only after the
   stall, the payload was encoded exactly ONCE, and all four frames
   decode to bit-identical payloads.

2. **Federation ledger parity** — a 4-silo federation (deadline rounds,
   so the server takes the parallel fan-out path) where the chaos
   harness (comm/faults.py) delays every model-broadcast delivery at
   one silo by ``DELAY_MS``. Required: the full schedule completes (the
   slow silo is never evicted), the server's round-open fan-out gauge
   stays far under the injected delay, the per-round reported sets
   match a fault-free reference run, and the final model is
   BIT-identical to the reference (the sorted-index fold makes arrival
   timing irrelevant).

Run: ``python -m fedml_tpu.comm.fanout_smoke [--port_base N]``
"""

from __future__ import annotations

import argparse
import socket
import struct
import sys
import threading
import time

import numpy as np

_HOST = "127.0.0.1"
STALL_S = 2.0        # stage 1: how long the slow peer refuses to read
PAYLOAD_MB = 8       # stage 1: big enough to overflow loopback buffers
DELAY_MS = 1200.0    # stage 2: chaos recv-delay at the slow silo


def _fail(msg: str) -> None:
    print(f"FANOUT SMOKE FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


# -- stage 1: transport backpressure ----------------------------------------
class _RawPeer:
    """A minimal frame sink: accepts one connection and records when its
    first frame finished arriving. ``stall_s`` delays the FIRST read —
    with the socket unread, the sender's TCP window closes and a
    blocking fan-out would wedge on this peer."""

    def __init__(self, port: int, stall_s: float = 0.0):
        self.stall_s = stall_s
        self.frames = []
        self.done_t: float | None = None
        self._server = socket.create_server((_HOST, port))
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        from fedml_tpu.comm.tcp import recv_frame
        try:
            conn, _ = self._server.accept()
        except OSError:
            # close() tore down the listener before any connect
            # arrived — nothing to sink
            return
        try:
            if self.stall_s:
                time.sleep(self.stall_s)
            self.frames.append(recv_frame(conn))
            self.done_t = time.monotonic()
        # ft: allow[FT007] smoke fixture teardown: a torn socket just leaves done_t unset and the main thread fails the stage on that
        except OSError:
            pass
        finally:
            conn.close()
            self._server.close()

    def close(self) -> None:
        """Idempotent: releases the listener (unblocking a
        never-connected ``accept()``) so the port can be rebound by the
        next stage immediately instead of leaking for the process
        lifetime."""
        self._server.close()
        self._thread.join(timeout=1.0)


def stage_transport(port_base: int) -> None:
    from fedml_tpu.comm.tcp import TcpCommManager

    n_peers = 4
    slow_rank = n_peers  # the last peer stalls
    addresses = {r: (_HOST, port_base + r) for r in range(n_peers + 1)}
    peers = {r: _RawPeer(port_base + r,
                         stall_s=STALL_S if r == slow_rank else 0.0)
             for r in range(1, n_peers + 1)}
    try:
        com = TcpCommManager(0, addresses)
        try:
            _stage_transport_run(com, peers, n_peers, slow_rank)
        finally:
            # a _fail() mid-stage must not strand the bound listener:
            # stage 2 rebinds the same port range in this process
            com.stop_receive_message()
    finally:
        for peer in peers.values():
            peer.close()


def _stage_transport_run(com, peers, n_peers: int, slow_rank: int) -> None:
    from fedml_tpu.comm.message import Message
    from fedml_tpu.comm.serialization import SharedPayload

    rng = np.random.default_rng(0)
    tree = {"w": rng.standard_normal(
        (PAYLOAD_MB * (1 << 20) // 4,)).astype(np.float32)}
    shared = SharedPayload(tree)
    msgs = []
    for r in range(1, n_peers + 1):
        msg = Message(2, 0, r)
        msg.add("model_params", shared)
        msg.add("round_idx", 0)
        msgs.append(msg)

    errors = []
    t0 = time.monotonic()
    stats = com.broadcast(msgs, on_error=lambda r, e: errors.append((r, e)))
    bcast_wall = time.monotonic() - t0

    # fast peers must finish while the slow peer is still stalled
    deadline = t0 + STALL_S * 0.75
    for r in range(1, n_peers):
        # ft: allow[FT015] smoke timing probe: the stall window IS the experiment — no schedule or RNG state derives from this wait
        while peers[r].done_t is None and time.monotonic() < deadline:
            time.sleep(0.005)
    # slow peer drains only after the stall
    slow_deadline = t0 + STALL_S + 30.0
    # ft: allow[FT015] liveness deadline on the stalled peer's drain — real time is the only signal kernel backpressure releases on
    while time.monotonic() < slow_deadline \
            and peers[slow_rank].done_t is None:
        time.sleep(0.01)

    if errors:
        _fail(f"stage 1: broadcast surfaced errors: {errors}")
    if stats["enqueued"] != n_peers:
        _fail(f"stage 1: enqueued {stats['enqueued']} != {n_peers}")
    # ft: allow[FT015] the smoke's whole contract is this wall-clock bound: broadcast() must return in a fraction of the injected stall
    if bcast_wall >= STALL_S / 4:
        _fail(f"stage 1: broadcast() took {bcast_wall:.3f}s — blocked on "
              f"the stalled peer (stall {STALL_S}s)")
    fast_done = [peers[r].done_t for r in range(1, n_peers)]
    if any(t is None for t in fast_done):
        _fail("stage 1: a fast peer never received its frame while the "
              "slow peer stalled — fan-out is serialized")
    # ft: allow[FT015] wall-clock assertion again: fast peers must drain inside the stall window or the fan-out is serialized
    if max(t - t0 for t in fast_done) >= STALL_S * 0.75:
        _fail("stage 1: fast peers drained only near/after the stall — "
              "fan-out is serialized behind the slow peer")
    if peers[slow_rank].done_t is None:
        _fail("stage 1: slow peer never drained")
    slow_took = peers[slow_rank].done_t - t0
    if slow_took < STALL_S - 0.1:
        _fail(f"stage 1: slow peer drained in {slow_took:.3f}s — the "
              f"stall never produced backpressure; the stage proves "
              f"nothing")
    if shared.encode_count != 1:
        _fail(f"stage 1: payload encoded {shared.encode_count}x, want 1")
    from fedml_tpu.comm.message import Message as M
    for r, peer in peers.items():
        got = M.from_bytes(peer.frames[0]).get("model_params")
        if got["w"].dtype != tree["w"].dtype \
                or not np.array_equal(np.asarray(got["w"]), tree["w"]):
            _fail(f"stage 1: peer {r} frame decoded to a different "
                  "payload")
    print(f"stage 1 OK: broadcast {n_peers}x{PAYLOAD_MB}MB returned in "
          f"{bcast_wall * 1e3:.1f} ms; fast peers drained in "
          f"{max(t - t0 for t in fast_done):.2f}s; slow peer in "
          f"{slow_took:.2f}s (stall {STALL_S}s); one encode")


# -- stage 2: federation ledger parity under a chaos-delayed silo -----------
def _run_federation(port_base: int, fault_plan=None):
    from fedml_tpu.algorithms.fedavg_cross_silo import (
        run_fedavg_cross_silo)
    from fedml_tpu.data.synthetic import make_blob_federated
    from fedml_tpu.models.lr import LogisticRegression
    from fedml_tpu.trainer.functional import TrainConfig
    from fedml_tpu.utils.tracing import RoundTimer

    worker_num = 4
    ds = make_blob_federated(client_num=worker_num, dim=8, class_num=3,
                             n_samples=128, seed=11)
    addresses = {r: (_HOST, port_base + r)
                 for r in range(worker_num + 1)}
    timer = RoundTimer()
    ledger = []
    model, history = run_fedavg_cross_silo(
        ds, LogisticRegression(num_classes=3), worker_num=worker_num,
        comm_round=3, train_cfg=TrainConfig(epochs=1, batch_size=8,
                                            lr=0.1),
        backend="TCP", addresses=addresses, timer=timer,
        round_record_hook=ledger.append, fault_plan=fault_plan,
        round_deadline_s=30.0, min_quorum_frac=0.5)
    return model, history, ledger, timer


def stage_federation(port_base: int) -> None:
    import jax

    ref_model, ref_hist, ref_ledger, ref_timer = _run_federation(port_base)
    # every model broadcast to silo rank 4 is delivered DELAY_MS late
    plan = (f"seed=3;delay:p=1.0,delay_ms={DELAY_MS:.0f},msg_type=2,"
            f"receiver=4,direction=recv")
    model, hist, ledger, timer = _run_federation(port_base + 16,
                                                 fault_plan=plan)

    if len(hist) != len(ref_hist) or len(hist) != 3:
        _fail(f"stage 2: chaos run finished {len(hist)}/3 rounds")
    got_rep = [sorted(r.get("reported", [])) for r in ledger]
    ref_rep = [sorted(r.get("reported", [])) for r in ref_ledger]
    if got_rep != ref_rep:
        _fail(f"stage 2: reported-set ledger diverged: {got_rep} vs "
              f"{ref_rep} — the slow silo fell out of the round")
    fanout_ms = timer.gauges.get("bcast_fanout_ms")
    if fanout_ms is None:
        _fail("stage 2: no bcast_fanout_ms gauge — the fan-out path "
              "never ran")
    if fanout_ms >= DELAY_MS / 2:
        _fail(f"stage 2: round-open fan-out took {fanout_ms:.1f} ms "
              f"against a {DELAY_MS:.0f} ms slow peer — the round "
              f"thread waited out the straggler")
    if ref_timer.gauges.get("send_queue_depth", 0) < 1:
        _fail("stage 2: reference run never rode the per-peer send "
              "queues (send_queue_depth gauge empty)")
    faults = timer.counters.get("ft_faults_injected", 0)
    if faults < 2:
        _fail(f"stage 2: only {faults} faults injected — the chaos "
              "delay never fired; the parity claim is untested")
    la = jax.tree.leaves(jax.tree.map(np.asarray, ref_model))
    lb = jax.tree.leaves(jax.tree.map(np.asarray, model))
    if len(la) != len(lb) or not all(
            np.array_equal(a, b) for a, b in zip(la, lb)):
        _fail("stage 2: final model diverged from the fault-free "
              "reference — the fold is arrival-order sensitive")
    print(f"stage 2 OK: 3/3 rounds, ledger parity, bit-identical model; "
          f"round-open fan-out {fanout_ms:.1f} ms vs {DELAY_MS:.0f} ms "
          f"injected delay; {faults} faults injected")


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--port_base", type=int, default=40610)
    args = ap.parse_args(argv)
    stage_transport(args.port_base)
    stage_federation(args.port_base + 32)
    print("FANOUT SMOKE PASS")


if __name__ == "__main__":
    main()
