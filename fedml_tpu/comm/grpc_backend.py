"""gRPC backend — chunked streaming transport for the cross-silo wire.

The reference defines ``service gRPCCommManager { rpc sendMessage
(CommRequest) returns (CommResponse) }`` with ``(client_id, message)`` fields
(gRPC/proto/grpc_comm_manager.proto:1-17) but hardcodes two receiver IPs
(grpc_comm_manager.py:51-56). Earlier revisions here kept the unary-RPC
shape with a lifted-but-hard ``max_message_length`` ceiling (1 GiB): one
oversized model update would fail the whole federation, and gRPC buffered
each frame contiguously on both ends. Now ``sendMessage`` is a
CLIENT-STREAMING rpc: the sender walks the frame's constituent buffers
(``Message.to_parts`` — header + raw leaf buffers, never joined) and ships
~``_CHUNK``-byte messages, so the per-message limit only needs to clear one
chunk and total frame size is unbounded. No protoc code-gen needed: chunks
are raw bytes of our self-describing binary frame. Import is gated so
environments without grpcio still load the package.

Reliability: transient stream failures (``UNAVAILABLE``,
``DEADLINE_EXCEEDED``) are retried under a seeded backoff policy
(comm/reliable.py). Each retry restarts the stream FROM CHUNK 0 with the
same wire seq — a partial first attempt never reaches the inbox (the
server drops torn streams), and a complete-but-unacknowledged first
attempt is shed by the receiver's seq dedup (comm/base.py). Permanent
failures raise a non-transient ``TransportError`` immediately so callers
can tell a restarting peer from a misconfigured address.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Dict, Iterator, Optional, Tuple

from fedml_tpu.comm.base import WIRE_JOB_KEY, BaseCommunicationManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.reliable import RetryPolicy, retry_call

try:
    import grpc
    HAS_GRPC = True
except ImportError:  # pragma: no cover
    grpc = None
    HAS_GRPC = False

_SERVICE = "fedml_tpu.CommManager"
_METHOD = f"/{_SERVICE}/sendMessage"
#: stream chunk size — the ONLY per-message budget the transport needs;
#: total frame size is unbounded (the old unary backend's 1 GiB _MAX_LEN
#: ceiling is gone)
_CHUNK = 4 << 20
#: per-message cap: one chunk + protobuf/framing slack
_MSG_LEN = _CHUNK + (1 << 20)

_STOP = object()


def _iter_chunks(parts, chunk: int = _CHUNK) -> Iterator[bytes]:
    """Walk a ``dumps_parts`` buffer list as ~chunk-byte bytes messages.

    Small parts (the length prefix, the header, scalar-only payloads) are
    coalesced into one chunk; large array buffers are sliced. Only the
    per-chunk ``bytes()`` copies are ever materialized — never the frame.
    """
    pending: list = []
    pending_n = 0
    for p in parts:
        view = memoryview(p)
        off = 0
        while off < len(view):
            take = min(chunk - pending_n, len(view) - off)
            pending.append(view[off:off + take])
            pending_n += take
            off += take
            if pending_n == chunk:
                yield b"".join(pending)
                pending, pending_n = [], 0
    if pending:
        yield b"".join(pending)


def _is_transient_rpc(exc: BaseException) -> bool:
    """UNAVAILABLE (peer down/restarting, link flap) and DEADLINE_EXCEEDED
    (congestion, a stalled stream) are worth a fresh stream; every other
    status (UNIMPLEMENTED, INVALID_ARGUMENT, resolution failures) is a
    configuration or protocol error a retry cannot fix."""
    if grpc is None or not isinstance(exc, grpc.RpcError):
        return False
    code = exc.code() if callable(getattr(exc, "code", None)) else None
    return code in (grpc.StatusCode.UNAVAILABLE,
                    grpc.StatusCode.DEADLINE_EXCEEDED)


class GrpcCommManager(BaseCommunicationManager):
    def __init__(self, rank: int, addresses: Dict[int, Tuple[str, int]],
                 retry: Optional[RetryPolicy] = None):
        if not HAS_GRPC:  # pragma: no cover
            raise ImportError("grpcio is not available in this environment")
        super().__init__()
        self.rank = rank
        self.addresses = addresses
        self.retry = retry if retry is not None else RetryPolicy(seed=rank)
        self._inbox: "queue.Queue" = queue.Queue()
        self._channels: Dict[int, "grpc.Channel"] = {}
        self._lock = threading.Lock()
        self._running = False

        def handle(request_iterator, context) -> bytes:
            # reassemble into ONE growing buffer (no chunk list + join)
            buf = bytearray()
            try:
                for chunk in request_iterator:
                    buf.extend(chunk)
            except grpc.RpcError:
                # torn client stream (sender died / retried): the partial
                # frame must never reach the inbox — the sender's retry
                # restarts from chunk 0 and delivers a whole frame
                self.bump("torn_streams")
                logging.warning("grpc rank %d: inbound stream torn after "
                                "%d bytes — dropping partial frame",
                                self.rank, len(buf))
                raise
            self._count_received(len(buf))
            self._inbox.put(buf)
            return b"ok"

        rpc = grpc.stream_unary_rpc_method_handler(
            handle, request_deserializer=None, response_serializer=None)
        handler = grpc.method_handlers_generic_handler(
            _SERVICE, {"sendMessage": rpc})
        from concurrent import futures
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8),
                                   options=self._options())
        self._server.add_generic_rpc_handlers((handler,))
        host, port = addresses[rank]
        self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    @staticmethod
    def _options():
        return [("grpc.max_send_message_length", _MSG_LEN),
                ("grpc.max_receive_message_length", _MSG_LEN)]

    def _stub(self, dest: int):
        with self._lock:
            ch = self._channels.get(dest)
            if ch is None:
                host, port = self.addresses[dest]
                ch = grpc.insecure_channel(f"{host}:{port}",
                                           options=self._options())
                self._channels[dest] = ch
            return ch.stream_unary(_METHOD)

    def send_message(self, msg: Message) -> None:
        # stamp BEFORE encoding: every stream attempt ships the identical
        # frame/seq, so a duplicate from a completed-but-unacked first
        # attempt is shed by the receiver's dedup
        self._stamp_seq(msg)
        parts = msg.to_parts()
        n = sum(len(p) for p in parts)
        # deadline scales with frame size (floor 8 MB/s): a fixed 60 s
        # would re-cap exactly the huge-model frames streaming unlocked
        timeout = 60 + n / (8 << 20)
        dest = msg.get_receiver_id()

        def attempt() -> None:
            # a FRESH chunk generator per attempt: the retried stream
            # restarts from chunk 0 (the server drops torn partials)
            self._stub(dest)(_iter_chunks(parts), timeout=timeout)

        host, port = self.addresses[dest]
        retry_call(
            attempt, self.retry,
            describe=f"grpc sendMessage to rank {dest} ({host}:{port})",
            is_transient=_is_transient_rpc,
            on_retry=lambda a, exc: self.bump(
                "retries", job=msg.msg_params.get(WIRE_JOB_KEY)))
        self._count_sent(n, msg.msg_params.get(WIRE_JOB_KEY))

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            item = self._inbox.get()
            if item is _STOP:
                break
            n = len(item)
            msg = Message.from_bytes(item)
            # raw total was counted on the servicer thread; the per-job
            # slice needs the decoded tag
            self._credit_job_received(n, msg.msg_params.get(WIRE_JOB_KEY))
            self._notify(msg)

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(_STOP)
        self._server.stop(grace=None)
        with self._lock:
            for ch in self._channels.values():
                ch.close()
            self._channels.clear()
