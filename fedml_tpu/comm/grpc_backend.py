"""gRPC backend — cross-silo transport mirroring the reference's proto.

The reference defines ``service gRPCCommManager { rpc sendMessage
(CommRequest) returns (CommResponse) }`` with ``(client_id, message)`` fields
(gRPC/proto/grpc_comm_manager.proto:1-17) but hardcodes two receiver IPs
(grpc_comm_manager.py:51-56). Here the same unary-RPC shape is registered as
a *generic* RPC handler (no protoc code-gen needed: the message field is our
binary frame, already self-describing), and peer addresses come from an
explicit ``{rank: (host, port)}`` map. Import is gated so environments
without grpcio still load the package.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Tuple

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import Message

try:
    import grpc
    HAS_GRPC = True
except ImportError:  # pragma: no cover
    grpc = None
    HAS_GRPC = False

_SERVICE = "fedml_tpu.CommManager"
_METHOD = f"/{_SERVICE}/sendMessage"
_MAX_LEN = 1 << 30  # model updates are large; lift the 4 MB default

_STOP = object()


class GrpcCommManager(BaseCommunicationManager):
    def __init__(self, rank: int, addresses: Dict[int, Tuple[str, int]]):
        if not HAS_GRPC:  # pragma: no cover
            raise ImportError("grpcio is not available in this environment")
        super().__init__()
        self.rank = rank
        self.addresses = addresses
        self._inbox: "queue.Queue" = queue.Queue()
        self._channels: Dict[int, "grpc.Channel"] = {}
        self._lock = threading.Lock()
        self._running = False

        def handle(request: bytes, context) -> bytes:
            self._inbox.put(request)
            return b"ok"

        rpc = grpc.unary_unary_rpc_method_handler(
            handle, request_deserializer=None, response_serializer=None)
        handler = grpc.method_handlers_generic_handler(
            _SERVICE, {"sendMessage": rpc})
        opts = [("grpc.max_send_message_length", _MAX_LEN),
                ("grpc.max_receive_message_length", _MAX_LEN)]
        from concurrent import futures
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8),
                                   options=opts)
        self._server.add_generic_rpc_handlers((handler,))
        host, port = addresses[rank]
        self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    def _stub(self, dest: int):
        with self._lock:
            ch = self._channels.get(dest)
            if ch is None:
                host, port = self.addresses[dest]
                opts = [("grpc.max_send_message_length", _MAX_LEN),
                        ("grpc.max_receive_message_length", _MAX_LEN)]
                ch = grpc.insecure_channel(f"{host}:{port}", options=opts)
                self._channels[dest] = ch
            return ch.unary_unary(_METHOD)

    def send_message(self, msg: Message) -> None:
        self._stub(msg.get_receiver_id())(msg.to_bytes(), timeout=60)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            item = self._inbox.get()
            if item is _STOP:
                break
            self._notify(Message.from_bytes(item))

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(_STOP)
        self._server.stop(grace=None)
        with self._lock:
            for ch in self._channels.values():
                ch.close()
            self._channels.clear()
