"""The inference endpoint: a hot-swappable, jit-warmed predict slot.

Training produces a new aggregated global model every round; serving
must pick it up WITHOUT ever making a request wait on either an XLA
compile or a device transfer:

- **double-buffered params** — the endpoint holds one immutable
  :class:`ServedModel` per variant (the global model is variant
  ``None``); ``install`` stages the incoming round's params — D2H-safe
  numpy in, ``jax.device_put`` + ``block_until_ready`` OUTSIDE any
  request — and then publishes it with ONE atomic reference flip.
  Requests read the reference once and keep serving round ``r``'s
  params until the flip, so a swap never happens inside a request and
  the previous round's buffer stays alive exactly as long as in-flight
  requests need it;
- **bucketed jit warmup** — the predict program is compiled once per
  batch bucket (the power-of-2 ladder the cohort packing code uses,
  ``data/base.py cohort_padded_len``) when the FIRST model installs;
  every later swap reuses those lowerings (same shapes, same dtypes),
  so swap cost is the device transfer plus a reference assignment —
  measured and exported as ``serve_swap_ms``.

The endpoint serializes its device work through the SAME mutex as
training (``_DEVICE_LOCK``, or a per-job ``JobDeviceGate`` under the
federation scheduler), so serving is a co-tenant of the chip, never a
second uncoordinated dispatch queue.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np


def bucket_ladder(max_batch: int) -> List[int]:
    """The power-of-2 batch buckets up to ``max_batch`` (always included
    even when not a power of 2) — one compiled predict program per rung,
    O(log2(max_batch)) lowerings total."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    ladder = []
    b = 1
    while b < max_batch:
        ladder.append(b)
        b <<= 1
    ladder.append(int(max_batch))
    return ladder


def bucket_for(n: int, ladder: List[int]) -> int:
    """Smallest rung holding ``n`` rows (the padding target)."""
    for b in ladder:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} rows exceeds the top bucket "
                     f"{ladder[-1]} — the coalescer must split first")


class ServedModel:
    """One immutable served slot: the params on device plus identity.
    Publishing a new round = constructing a fresh instance and flipping
    the endpoint's reference — existing requests keep the instance they
    already read."""

    __slots__ = ("round_idx", "variables", "variant", "installed_at")

    def __init__(self, round_idx: int, variables, variant=None):
        self.round_idx = int(round_idx)
        self.variables = variables
        self.variant = variant
        self.installed_at = time.monotonic()


class ModelEndpoint:
    """Hot-swapped, bucket-warmed inference over the federation's model.

    ``variant=None`` is the aggregated GLOBAL model; string variants are
    personalized models (per-silo / per-cohort deltas applied by the
    rollout layer) served from the same warmed programs — all variants
    share one model structure, so one ladder of lowerings serves all.
    """

    def __init__(self, module, task: str = "classification", *,
                 sample_input: np.ndarray, max_batch: int = 8,
                 device_lock=None, timer=None, obs=None):
        import jax

        from fedml_tpu.trainer.functional import make_forward
        if device_lock is None:
            from fedml_tpu.algorithms.fedavg_cross_silo import _DEVICE_LOCK
            device_lock = _DEVICE_LOCK
        self._device_lock = device_lock
        self._timer = timer
        self._obs = obs
        self.task = task
        self.ladder = bucket_ladder(max_batch)
        self.max_batch = int(max_batch)
        #: feature shape/dtype every request must match (from one sample
        #: row of the training data — the contract the warmup compiled)
        sample = np.asarray(sample_input)
        self.feature_shape: Tuple[int, ...] = tuple(sample.shape[1:])
        self.feature_dtype = sample.dtype
        forward = make_forward(module)
        self._predict = jax.jit(lambda v, x: forward(v, x, False)[0])
        #: variant -> ServedModel; reads take ONE snapshot reference,
        #: writes flip under _swap_lock (install is never concurrent
        #: with itself; requests never take the lock)
        self._models: Dict[Optional[str], ServedModel] = {}
        self._swap_lock = threading.Lock()
        self._warmed = False
        self.swaps = 0
        self.last_swap_ms: Optional[float] = None
        #: recent swap costs (ms), bounded — the bench/report read the
        #: steady-state distribution from here (first-install warmup
        #: compile already excluded by ``install``'s measurement)
        self.swap_ms_history: collections.deque = collections.deque(
            maxlen=256)

    # -- swap path (NEVER inside a request) ---------------------------------
    def install(self, round_idx: int, variables, *,
                variant: Optional[str] = None) -> float:
        """Stage ``variables`` (host numpy tree) onto the device, warm
        the bucket ladder on first install, then atomically flip the
        served reference. Returns the measured swap cost in ms.

        Runs on the rollout's swap thread — requests in flight keep the
        previous reference; the flip is one Python assignment."""
        import jax
        with self._swap_lock:
            if not self._warmed:
                # first install only: stage + compile the bucket
                # ladder. A one-off XLA cost, deliberately OUTSIDE the
                # measured swap — every later swap is transfer + flip,
                # which is the recurring figure serve_swap_ms reports
                with self._device_lock:
                    pre = jax.device_put(variables)
                    jax.block_until_ready(pre)
                # ft: allow[FT022] first-install only: the bucket ladder must be compiled under the swap gate so no request observes a half-warmed endpoint; every later install skips this branch
                self._warm(pre)
                self._warmed = True
                t0 = time.perf_counter()
                dev = pre
            else:
                t0 = time.perf_counter()
                with self._device_lock:
                    dev = jax.device_put(variables)
                    jax.block_until_ready(dev)
            model = ServedModel(round_idx, dev, variant=variant)
            # THE atomic publish: dict item assignment under the GIL —
            # a request's snapshot read sees the old or the new slot,
            # never a half-installed one
            self._models[variant] = model
            self.swaps += 1
        ms = (time.perf_counter() - t0) * 1000.0
        self.last_swap_ms = ms
        self.swap_ms_history.append(ms)
        if self._timer is not None:
            self._timer.gauge("serve_swap_ms", ms)
        if self._obs is not None:
            self._obs.recorder.append({
                "kind": "serve", "event": "swap",
                "round": int(round_idx),
                "variant": variant, "swap_ms": round(ms, 3)})
        return ms

    def _warm(self, dev_variables) -> None:
        """Compile the predict program at every bucket rung so no request
        ever eats an XLA compile. First-install only; swaps reuse the
        lowerings (identical shapes and dtypes)."""
        import jax
        t0 = time.perf_counter()
        for b in self.ladder:
            x = np.zeros((b,) + self.feature_shape, self.feature_dtype)
            with self._device_lock:
                out = self._predict(dev_variables, x)
                jax.block_until_ready(out)
        logging.info("serve endpoint: warmed %d bucket shapes %s in %.2fs",
                     len(self.ladder), self.ladder,
                     time.perf_counter() - t0)

    # -- request path --------------------------------------------------------
    def served(self, variant: Optional[str] = None
               ) -> Optional[ServedModel]:
        """The current slot for ``variant`` (one atomic reference read);
        unknown variants fall back to the global model."""
        model = self._models.get(variant)
        if model is None and variant is not None:
            model = self._models.get(None)
        return model

    def variants(self) -> List[str]:
        return sorted(k for k in self._models if k is not None)

    def predict(self, x: np.ndarray,
                variant: Optional[str] = None
                ) -> Tuple[np.ndarray, int]:
        """Run the warmed predict on ``x`` ([n, *feature_shape], n <= the
        top bucket), padding to the bucket rung. Returns ``(outputs[:n],
        served_round)``. Raises ``RuntimeError`` before the first
        install (nothing to serve yet)."""
        model = self.served(variant)
        if model is None:
            raise RuntimeError("endpoint has no installed model yet — "
                               "the first rollout publish has not landed")
        x = np.asarray(x, self.feature_dtype)
        if x.shape[1:] != self.feature_shape:
            raise ValueError(
                f"request features {x.shape[1:]} do not match the served "
                f"model's input contract {self.feature_shape}")
        n = x.shape[0]
        b = bucket_for(n, self.ladder)
        if b != n:
            pad = np.zeros((b - n,) + self.feature_shape,
                           self.feature_dtype)
            x = np.concatenate([x, pad])
        with self._device_lock:
            out = np.asarray(self._predict(model.variables, x))
        return out[:n], model.round_idx
