"""The serving front: a threaded TCP/JSON endpoint + the tier bundle.

Wire format reuses the ``comm/`` framing primitives verbatim
(``tcp.send_frame`` / ``tcp.recv_frame``: u64 length-prefixed frames),
with UTF-8 JSON payloads — a serving request is small and structured,
so the zero-copy array codec would buy nothing while JSON keeps the
endpoint curl-able from any language. One frame per request, one frame
per reply, many requests per connection.

Request::

    {"op": "predict", "inputs": [[...], ...],      # [n, *feature_shape]
     "variant": null | "<id>", "deadline_ms": 50}
    {"op": "stats"}

Reply::

    {"status": "ok", "outputs": [[...], ...], "pred": [...],
     "round": 7, "staleness": 0, "stale": false}
    {"status": "shed", "reason": "..."}            # the 429 analogue
    {"status": "error", "reason": "..."}

``ServingTier`` is the bundle a launcher owns: endpoint + coalescer +
rollout + (optionally) this TCP front, with ``publish_hook`` bound into
the training server's round close and ``slo_report()`` as the SLO
artifact. Serving is a PURE OBSERVER of training: it reads published
model copies and shares the device mutex; it never writes training
state, so trajectories are bit-exact with serving on or off (pinned in
``tests/test_serve.py``).
"""

from __future__ import annotations

import json
import logging
import socket
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from fedml_tpu.comm.tcp import recv_frame, send_frame
from fedml_tpu.serve.batcher import BatchCoalescer, ShedError
from fedml_tpu.serve.endpoint import ModelEndpoint
from fedml_tpu.serve.rollout import RolloutManager

#: accept/read timeouts so shutdown is prompt and a half-open client
#: can never pin a handler thread forever
_ACCEPT_TIMEOUT_S = 0.5
_CONN_TIMEOUT_S = 60.0


class ServingServer:
    """Threaded TCP front over a :class:`ServingTier`'s submit path."""

    def __init__(self, tier: "ServingTier", host: str = "127.0.0.1",
                 port: int = 0):
        self._tier = tier
        self._sock = socket.create_server((host, port))
        self._sock.listen(64)
        self._sock.settimeout(_ACCEPT_TIMEOUT_S)
        self.address = self._sock.getsockname()
        self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="serve-accept")
        self._accept_thread.start()
        logging.info("serving endpoint listening on %s:%d", *self.address)

    @property
    def port(self) -> int:
        return int(self.address[1])

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.settimeout(_CONN_TIMEOUT_S)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()
        try:
            self._sock.close()
        except OSError:
            pass

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while self._running:
                try:
                    frame = recv_frame(conn)
                except (ConnectionError, socket.timeout, OSError):
                    break
                reply = self._handle(bytes(frame))
                try:
                    send_frame(conn, json.dumps(reply).encode())
                except OSError:
                    break
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, frame: bytes) -> Dict[str, Any]:
        try:
            req = json.loads(frame.decode())
        except (UnicodeDecodeError, json.JSONDecodeError):
            return {"status": "error", "reason": "malformed JSON frame"}
        op = req.get("op", "predict")
        if op == "stats":
            return {"status": "ok", **self._tier.slo_report()}
        if op != "predict":
            return {"status": "error", "reason": f"unknown op {op!r}"}
        try:
            return self._tier.handle_predict(req)
        except ShedError as exc:
            return {"status": "shed", "reason": str(exc)}
        except Exception as exc:  # keep serving on a bad request
            logging.debug("serve request failed: %r", exc)
            return {"status": "error", "reason": str(exc)}

    def stop(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass


class ServeClient:
    """Minimal blocking client over the same framing (tests, bench
    traffic drivers, the smoke CLI)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout_s: float = 30.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)

    def request(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        send_frame(self._sock, json.dumps(obj).encode())
        return json.loads(bytes(recv_frame(self._sock)).decode())

    def predict(self, inputs, variant: Optional[str] = None,
                deadline_ms: Optional[float] = None) -> Dict[str, Any]:
        req: Dict[str, Any] = {"op": "predict",
                               "inputs": np.asarray(inputs).tolist()}
        if variant is not None:
            req["variant"] = variant
        if deadline_ms is not None:
            req["deadline_ms"] = float(deadline_ms)
        return self.request(req)

    def stats(self) -> Dict[str, Any]:
        return self.request({"op": "stats"})

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class ServingTier:
    """Endpoint + coalescer + rollout (+ TCP front), one bundle.

    ``build_serving`` is the constructor every launcher shares; the
    training server's round loop drives :meth:`publish_hook` and the
    front (or an in-process caller) drives :meth:`submit`.
    """

    def __init__(self, module, task: str, sample_input, *,
                 max_batch: int = 8, linger_us: int = 2000,
                 queue_depth: int = 64, staleness_rounds: int = 2,
                 checkpoint_dir: Optional[str] = None,
                 checkpointer=None, store=None,
                 device_gate=None, timer=None, obs=None,
                 port: Optional[int] = None, host: str = "127.0.0.1"):
        self.timer = timer
        self._obs = obs
        self.endpoint = ModelEndpoint(module, task,
                                      sample_input=sample_input,
                                      max_batch=max_batch,
                                      device_lock=device_gate,
                                      timer=timer, obs=obs)
        self.batcher = BatchCoalescer(self.endpoint.predict,
                                      max_batch=max_batch,
                                      linger_us=linger_us,
                                      queue_depth=queue_depth,
                                      timer=timer)
        self.rollout = RolloutManager(self.endpoint,
                                      staleness_rounds=staleness_rounds,
                                      checkpoint_dir=checkpoint_dir,
                                      checkpointer=checkpointer,
                                      store=store, timer=timer, obs=obs)
        self.server: Optional[ServingServer] = None
        if port is not None:
            self.server = ServingServer(self, host=host, port=port)

    @property
    def port(self) -> Optional[int]:
        return self.server.port if self.server is not None else None

    # -- trainer side --------------------------------------------------------
    def publish_hook(self, round_idx: int, payload) -> None:
        """Bound into the training server's round close/broadcast: hands
        the rollout this round's model (full tree or compressed delta).
        Non-blocking, never raises (pure observer)."""
        self.rollout.publish(round_idx, payload)

    # -- request side --------------------------------------------------------
    def submit(self, x, variant: Optional[str] = None,
               deadline_s: Optional[float] = None):
        """In-process predict through the coalescer: ``(outputs,
        served_round)``. Shape-checked HERE, before the queue: a
        malformed request must fail alone — inside a coalesced batch
        its concat error would fail every well-formed co-batched
        request."""
        x = np.asarray(x, self.endpoint.feature_dtype)
        if x.shape[1:] != self.endpoint.feature_shape:
            raise ValueError(
                f"request features {x.shape[1:]} do not match the "
                f"served model's input contract "
                f"{self.endpoint.feature_shape}")
        return self.batcher.submit(x, variant=variant,
                                   deadline_s=deadline_s)

    def handle_predict(self, req: Dict[str, Any]) -> Dict[str, Any]:
        inputs = np.asarray(req["inputs"], self.endpoint.feature_dtype)
        if inputs.ndim == len(self.endpoint.feature_shape):
            inputs = inputs[None]  # single-row convenience
        deadline_ms = req.get("deadline_ms")
        out, round_idx = self.submit(
            inputs, variant=req.get("variant"),
            deadline_s=(float(deadline_ms) / 1e3
                        if deadline_ms is not None else None))
        reply: Dict[str, Any] = {
            "status": "ok",
            "outputs": np.asarray(out).tolist(),
            "round": int(round_idx),
            "staleness": int(self.rollout.staleness()),
            "stale": bool(self.rollout.stale()),
        }
        if np.asarray(out).ndim == 2:  # classification logits
            reply["pred"] = np.argmax(out, axis=-1).astype(int).tolist()
        return reply

    # -- reporting -----------------------------------------------------------
    def slo_report(self) -> Dict[str, Any]:
        """The SLO/billing snapshot: coalescer counters + latency
        quantiles + rollout/swap state. Mirrored into the registry
        gauges and appended as a ``serve``/``slo`` flight record, so
        ``obs report``'s serving section folds the same rows."""
        snap = self.batcher.slo_snapshot()
        snap.update(self.rollout.counters())
        snap["swaps"] = int(self.endpoint.swaps)
        if self.endpoint.last_swap_ms is not None:
            snap["last_swap_ms"] = round(self.endpoint.last_swap_ms, 3)
        snap["variants"] = self.endpoint.variants()
        if self._obs is not None:
            self._obs.recorder.append({
                "kind": "serve", "event": "slo",
                "round": int(max(0, self.rollout.served_round)), **snap})
        return snap

    def close(self) -> None:
        """Orderly shutdown: flush one last SLO record, stop the front,
        drain the swap worker, stop the coalescer."""
        try:
            self.slo_report()
        except Exception:
            logging.warning("final serve SLO snapshot failed",
                            exc_info=True)
        if self.server is not None:
            self.server.stop()
        self.rollout.close()
        self.batcher.close()


def build_serving(module, task: str, sample_input, **kw) -> ServingTier:
    """The single serving constructor every launcher shares (mirrors
    ``obs.build_observability``). ``sample_input`` is one batch row of
    the model's input (``dataset.train_data_global[0][:1]``) — it pins
    the feature shape/dtype the bucket warmup compiles."""
    return ServingTier(module, task, sample_input, **kw)


def drive_traffic(port: int, inputs, *, requests: int = 50,
                  concurrency: int = 4,
                  deadline_ms: Optional[float] = None) -> Dict[str, Any]:
    """Closed-loop synthetic traffic against a serving port (bench +
    smoke): ``concurrency`` client connections issue ``requests`` total
    single-row predicts as fast as replies land. Returns counts and
    client-observed latency quantiles."""
    import time
    rows = np.asarray(inputs)
    results: List[Dict[str, Any]] = []
    lock = threading.Lock()
    idx = [0]

    def worker():
        client = ServeClient(port=port)
        try:
            while True:
                with lock:
                    if idx[0] >= requests:
                        return
                    i = idx[0]
                    idx[0] += 1
                t0 = time.perf_counter()
                rep = client.predict(rows[i % len(rows):i % len(rows) + 1],
                                     deadline_ms=deadline_ms)
                ms = (time.perf_counter() - t0) * 1e3
                with lock:
                    results.append({"status": rep.get("status"),
                                    "round": rep.get("round"),
                                    "stale": rep.get("stale"),
                                    "ms": ms})
        finally:
            client.close()

    t_start = time.perf_counter()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(1, concurrency))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    ok = [r for r in results if r["status"] == "ok"]
    lat = [r["ms"] for r in ok]

    def q(p):
        # the obs stack's one quantile definition — the bench artifact,
        # the SLO gauges, and the obs report must agree on p50/p99
        from fedml_tpu.obs.tail import _quantile
        v = _quantile(lat, p)
        return round(v, 3) if v is not None else None

    return {"requests": len(results), "ok": len(ok),
            "shed": sum(1 for r in results if r["status"] == "shed"),
            "errors": sum(1 for r in results
                          if r["status"] not in ("ok", "shed")),
            "stale_replies": sum(1 for r in ok if r.get("stale")),
            "rounds_served": sorted({r["round"] for r in ok
                                     if r["round"] is not None}),
            "wall_s": round(wall, 4),
            # ft: allow[FT015] divide-by-zero guard on a measured wall-clock duration (reporting arithmetic, not schedule state)
            "requests_per_sec": (round(len(ok) / wall, 2) if wall > 0
                                 else None),
            "latency_p50_ms": q(0.50), "latency_p99_ms": q(0.99)}
