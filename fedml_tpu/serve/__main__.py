"""CLI: ``python -m fedml_tpu.serve --smoke`` — the serving smoke.

The ci/run_fast.sh front for the serving tier (~10 s on a CPU host):
launch a small cross-silo federation WITH a serving endpoint attached,
hammer the endpoint with closed-loop traffic while (and after) training
runs, then assert the zero->aha contract:

- at least one hot swap landed (the endpoint is serving a trained
  round, not an init artifact);
- ZERO requests were shed (the coalescer kept up with the smoke load);
- the SLO report is populated (latency quantiles measured, served
  round/staleness tracked).

Prints the SLO report as one JSON object on stdout; exit 0 iff every
assertion holds. ``--requests`` / ``--rounds`` / ``--workers`` scale
the smoke; defaults match the CI budget.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import tempfile
import threading
import time


def _build_fixture(workers: int):
    from fedml_tpu.data.synthetic import make_blob_federated
    from fedml_tpu.models.lr import LogisticRegression
    from fedml_tpu.trainer.functional import TrainConfig
    ds = make_blob_federated(client_num=workers, dim=8, class_num=3,
                             n_samples=24 * workers, seed=5)
    return ds, LogisticRegression(num_classes=3), TrainConfig(
        epochs=1, batch_size=8, lr=0.1)


def run_smoke(rounds: int = 4, workers: int = 3, requests: int = 50,
              root: str = "") -> int:
    import os

    from fedml_tpu.algorithms.fedavg_cross_silo import run_fedavg_cross_silo
    from fedml_tpu.serve import build_serving, drive_traffic
    from fedml_tpu.utils.tracing import RoundTimer

    own_root = not root
    root = root or tempfile.mkdtemp(prefix="fedml_serve_smoke_")
    os.makedirs(root, exist_ok=True)
    ds, module, tcfg = _build_fixture(workers)
    timer = RoundTimer()
    tier = build_serving(module, "classification",
                         ds.train_data_global[0][:1], max_batch=8,
                         timer=timer, port=0,
                         checkpoint_dir=os.path.join(root, "ctrl"))
    ok = True
    try:
        trainer = threading.Thread(
            target=lambda: run_fedavg_cross_silo(
                ds, module, worker_num=workers, comm_round=rounds,
                train_cfg=tcfg, seed=3,
                server_checkpoint_dir=os.path.join(root, "ctrl"),
                serving=tier),
            daemon=True, name="serve-smoke-trainer")
        t0 = time.time()
        trainer.start()
        # first swap = the INIT broadcast's publish; traffic only makes
        # sense once something serves
        while tier.rollout.served_round < 0 and time.time() - t0 < 120:  # ft: allow[FT015] smoke startup budget — a wall-clock cap on waiting for the first swap
            time.sleep(0.02)
        if tier.rollout.served_round < 0:
            print(json.dumps({"error": "no model served within 120s"}))
            return 1
        traffic = drive_traffic(tier.port, ds.test_data_global[0][:8],
                                requests=requests, concurrency=4)
        trainer.join(timeout=300)
        tier.rollout.drain()
        report = tier.slo_report()
        out = {"traffic": traffic, "slo": report,
               "swaps": int(tier.endpoint.swaps),
               "gauges": {k: round(float(v), 3)
                          for k, v in timer.gauges.items()},
               "wall_s": round(time.time() - t0, 2)}
        problems = []
        if tier.endpoint.swaps < 1:
            problems.append("no hot swap landed")
        if traffic["shed"] or report.get("shed"):
            problems.append(f"load shed during the smoke "
                            f"(traffic={traffic['shed']}, "
                            f"tier={report.get('shed')})")
        if traffic["ok"] != requests:
            problems.append(f"only {traffic['ok']}/{requests} requests "
                            "answered ok")
        if report.get("latency_p50_ms") is None:
            problems.append("SLO report has no latency quantiles")
        if report.get("served_round", -1) < 0:
            problems.append("SLO report has no served round")
        out["problems"] = problems
        ok = not problems
        print(json.dumps(out, indent=2))
        return 0 if ok else 1
    finally:
        tier.close()
        if own_root:
            import shutil
            shutil.rmtree(root, ignore_errors=True)


def main(argv=None) -> int:
    from fedml_tpu.utils import force_platform_from_env
    force_platform_from_env()
    logging.basicConfig(level=logging.WARNING)
    parser = argparse.ArgumentParser(
        "python -m fedml_tpu.serve",
        description="federated serving smoke (see module docstring)")
    parser.add_argument("--smoke", action="store_true",
                        help="run the serving smoke: train + serve + "
                             "traffic, assert zero sheds and a "
                             "populated SLO report")
    parser.add_argument("--rounds", type=int, default=4,
                        help="training rounds for the smoke federation")
    parser.add_argument("--workers", type=int, default=3,
                        help="silos in the smoke federation")
    parser.add_argument("--requests", type=int, default=50,
                        help="synthetic requests to drive")
    parser.add_argument("--root", type=str, default="",
                        help="work dir (default: a fresh temp dir, "
                             "removed afterwards)")
    args = parser.parse_args(argv)
    if not args.smoke:
        parser.error("nothing to do: pass --smoke")
    return run_smoke(rounds=args.rounds, workers=args.workers,
                     requests=args.requests, root=args.root)


if __name__ == "__main__":
    sys.exit(main())
