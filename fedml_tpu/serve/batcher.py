"""Bounded-queue batch coalescing for the inference endpoint.

Single-row dispatches waste the chip: the predict program is warmed per
batch bucket (``endpoint.bucket_ladder``), so the cheapest way to serve
heavy traffic is to coalesce concurrent requests into one bucketed
batch. The coalescer is deliberately boring and bounded:

- **bounded queue** — ``queue_depth`` pending requests max; a submit
  against a full queue is SHED immediately (the 429 analogue, counted
  as ``serve_shed``), never parked on an unbounded list. Load beyond
  the chip's throughput degrades to fast rejections, not to a latency
  collapse;
- **max batch + max linger** — the dispatch loop takes the first
  waiting request, then drains more until the batch holds
  ``max_batch`` rows or ``linger_us`` has passed since the first row
  arrived. Low traffic pays at most the linger; saturated traffic
  fills buckets without waiting;
- **per-request deadline** — a request whose deadline expired while it
  queued is answered with a shed instead of burning a device slot on
  an answer nobody is waiting for;
- **per-variant batches** — rows for different personalized variants
  never share a batch (different params); the drain takes the longest
  same-variant prefix so mixed traffic still coalesces.

Latency accounting rides a bounded ring; ``slo_snapshot()`` derives
p50/p99 and mirrors them into the metric registry's ``serve_p50_ms`` /
``serve_p99_ms`` gauges.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


class ShedError(RuntimeError):
    """Request rejected by load-shedding (full queue or dead deadline) —
    the transport front maps this to its 429-style reply."""


class _Request:
    __slots__ = ("x", "variant", "deadline", "done", "outputs", "round_idx",
                 "error", "t_submit")

    def __init__(self, x, variant, deadline):
        self.x = x
        self.variant = variant
        self.deadline = deadline  # monotonic seconds, or None
        self.done = threading.Event()
        self.outputs = None
        self.round_idx: Optional[int] = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.monotonic()


class BatchCoalescer:
    """Coalesce concurrent predict calls into bucketed batches.

    ``predict(x, variant) -> (outputs, round_idx)`` is the endpoint's
    request path; the coalescer owns the one worker thread that calls
    it, so device dispatch order is single-threaded by construction.
    """

    def __init__(self, predict, *, max_batch: int = 8,
                 linger_us: int = 2000, queue_depth: int = 64,
                 timer=None, latency_window: int = 4096):
        self._predict = predict
        self.max_batch = max(1, int(max_batch))
        self.linger_s = max(0, int(linger_us)) / 1e6
        self._queue: "queue.Queue[_Request]" = queue.Queue(
            maxsize=max(1, int(queue_depth)))
        self._timer = timer
        #: guards the counters + the latency ring (submit threads and
        #: the dispatch worker both write)
        self._lock = threading.Lock()
        self.requests = 0
        self.batches = 0
        self.shed = 0
        self.batched_rows = 0
        self._latencies_ms = collections.deque(maxlen=latency_window)
        #: a different-variant request popped mid-drain, held as the
        #: NEXT batch's head (worker-thread only). Never pushed back
        #: into the shared queue: a blocking put into our own full
        #: queue would deadlock the lone consumer, and a tail re-queue
        #: would restart the request's wait behind everyone else
        self._carry: Optional[_Request] = None
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._dispatch_loop,
                                        daemon=True, name="serve-batcher")
        self._worker.start()

    # -- submit side ---------------------------------------------------------
    def submit(self, x, *, variant: Optional[str] = None,
               deadline_s: Optional[float] = None,
               timeout_s: float = 30.0):
        """Enqueue one request and block for its result. Returns
        ``(outputs, served_round)``; raises :class:`ShedError` when the
        queue is full or the deadline died in the queue, and re-raises
        the endpoint's error (bad shape, nothing installed) as-is."""
        with self._lock:
            self.requests += 1
            if self._timer is not None:
                self._timer.count("serve_requests")
        if self._stop.is_set():
            # no worker will ever drain this — shed NOW instead of
            # letting a straggler connection block out its full timeout
            self._note_shed()
            raise ShedError("coalescer closed — load shed")
        deadline = (time.monotonic() + float(deadline_s)
                    if deadline_s is not None else None)
        req = _Request(x, variant, deadline)
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self._note_shed()
            raise ShedError(
                f"serve queue full ({self._queue.maxsize} pending) — "
                "load shed") from None
        if not req.done.wait(timeout_s):
            # the caller gave up; the worker will still answer the slot
            # (discarded), and the deadline check sheds it if one is set
            raise TimeoutError(f"no serve result within {timeout_s}s")
        if req.error is not None:
            raise req.error
        return req.outputs, req.round_idx

    def _note_shed(self) -> None:
        with self._lock:
            self.shed += 1
            if self._timer is not None:
                self._timer.count("serve_shed")

    # -- dispatch side -------------------------------------------------------
    def _drain_batch(self, first: _Request) -> List[_Request]:
        """``first`` plus same-variant requests drained until the batch
        is full or the linger window (measured from ``first``'s arrival)
        closes. Differently-variant heads are put back for the next
        batch."""
        batch = [first]
        rows = int(np.shape(first.x)[0]) if hasattr(first.x, "shape") \
            else len(first.x)
        # linger from the FIRST row's arrival: a saturated queue fills
        # the bucket instantly; a trickle waits at most linger_s
        until = first.t_submit + self.linger_s
        while rows < self.max_batch:
            remaining = until - time.monotonic()
            try:
                # window closed: take only what is ALREADY waiting (free
                # rows), never wait more — the first request's latency
                # budget is spent
                nxt = (self._queue.get(timeout=remaining)
                       # ft: allow[FT015] the linger window is a wall-clock serving contract (max added latency per request), not schedule state
                       if remaining > 0 else self._queue.get_nowait())
            except queue.Empty:
                break
            if nxt.variant != first.variant:
                # different params: carry it as the next batch's head
                self._carry = nxt
                break
            batch.append(nxt)
            rows += int(np.shape(nxt.x)[0]) if hasattr(nxt.x, "shape") \
                else len(nxt.x)
        return batch

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            if self._carry is not None:
                first, self._carry = self._carry, None
            else:
                try:
                    first = self._queue.get(timeout=0.1)
                except queue.Empty:
                    continue
            batch = self._drain_batch(first)
            now = time.monotonic()
            live: List[_Request] = []
            for req in batch:
                # ft: allow[FT015] per-request deadlines are real wall-clock SLOs — an expired request must be shed, not served late
                if req.deadline is not None and now > req.deadline:
                    req.error = ShedError("deadline expired in queue")
                    self._note_shed()
                    req.done.set()
                else:
                    live.append(req)
            if not live:
                continue
            self._run_batch(live)

    def _run_batch(self, live: List[_Request]) -> None:
        t0 = time.perf_counter()
        xs = np.concatenate([np.asarray(r.x) for r in live])
        try:
            if xs.shape[0] > self.max_batch:
                # oversized concat (several multi-row requests): split on
                # the ladder's top rung so every chunk stays warm-compiled
                outs, round_idx = [], None
                for off in range(0, xs.shape[0], self.max_batch):
                    o, round_idx = self._predict(
                        xs[off:off + self.max_batch], live[0].variant)
                    outs.append(o)
                out = np.concatenate(outs)
            else:
                out, round_idx = self._predict(xs, live[0].variant)
        except Exception as exc:  # surface per-request, keep serving
            for req in live:
                req.error = exc
                req.done.set()
            logging.warning("serve batch failed (%d requests)", len(live),
                            exc_info=True)
            return
        ms_total = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            self.batches += 1
            self.batched_rows += int(xs.shape[0])
            if self._timer is not None:
                self._timer.count("serve_batches")
        off = 0
        now = time.monotonic()
        for req in live:
            n = int(np.shape(req.x)[0]) if hasattr(req.x, "shape") \
                else len(req.x)
            req.outputs = out[off:off + n]
            req.round_idx = round_idx
            off += n
            with self._lock:
                self._latencies_ms.append(
                    (now - req.t_submit) * 1000.0)
            req.done.set()
        logging.debug("serve batch: %d requests/%d rows in %.2fms",
                      len(live), xs.shape[0], ms_total)

    # -- accounting ----------------------------------------------------------
    def slo_snapshot(self) -> Dict[str, Any]:
        """Cumulative counters + latency quantiles; mirrors p50/p99 into
        the registry gauges. The serving tier appends this as a
        ``serve``/``slo`` flight record so live tail == offline report
        fold the same rows."""
        from fedml_tpu.obs.tail import _quantile
        with self._lock:
            lat = list(self._latencies_ms)
            snap = {"requests": int(self.requests),
                    "batches": int(self.batches),
                    "shed": int(self.shed),
                    "batched_rows": int(self.batched_rows)}
        p50, p99 = _quantile(lat, 0.50), _quantile(lat, 0.99)
        if p50 is not None:
            snap["latency_p50_ms"] = round(p50, 3)
            snap["latency_p99_ms"] = round(p99, 3)
            if self._timer is not None:
                self._timer.gauge("serve_p50_ms", p50)
                self._timer.gauge("serve_p99_ms", p99)
        return snap

    def close(self) -> None:
        self._stop.set()
        self._worker.join(timeout=5)
        # answer anything still queued (or carried) with a shed, so no
        # submitter blocks on a dead worker
        if self._carry is not None:
            self._carry.error = ShedError("coalescer closed")
            self._carry.done.set()
            self._carry = None
        # two passes with a beat between them: a submit that passed the
        # closed check just before _stop was set may still be putting —
        # its request must get a shed reply, not a 30 s timeout
        for _ in range(2):
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                req.error = ShedError("coalescer closed")
                req.done.set()
            time.sleep(0.05)
