"""fedml_tpu.serve — the federated serving tier.

Closes the train->serve loop: every round's aggregated global model is
hot-swapped into a jitted, batch-coalescing inference endpoint that
serves WHILE the next round trains, sharing the one device through the
same mutex (or per-job ``JobDeviceGate``) as training.

Layers (one module each):

- :mod:`fedml_tpu.serve.endpoint` — double-buffered param slots,
  atomic reference-flip swap, bucket-laddered jit warmup (no request
  ever eats an XLA compile);
- :mod:`fedml_tpu.serve.batcher` — bounded-queue batch coalescing
  (max batch + max linger), per-request deadlines, load shedding;
- :mod:`fedml_tpu.serve.rollout` — staleness-bounded rollout fed by
  full ``ServerControlCheckpointer`` blobs or compression-mirror
  deltas (shared ``comm/compression.py`` decode path, full-precision
  fallback on fingerprint mismatch), plus personalized variants from
  the tiered client-state store;
- :mod:`fedml_tpu.serve.server` — the threaded TCP/JSON front reusing
  ``comm/`` framing, the :class:`ServingTier` bundle, and the
  synthetic-traffic driver the bench/smoke use.

``python -m fedml_tpu.serve --smoke`` is the CI front; launchers wire
serving with ``--serve_port`` / ``--serve_staleness_rounds``. Serving
is a PURE OBSERVER of training — trajectories are bit-exact with it on
or off (pinned in ``tests/test_serve.py``).
"""

from __future__ import annotations

from fedml_tpu.serve.batcher import BatchCoalescer, ShedError
from fedml_tpu.serve.endpoint import (ModelEndpoint, ServedModel,
                                      bucket_for, bucket_ladder)
from fedml_tpu.serve.rollout import PERSONAL_FIELD, RolloutManager
from fedml_tpu.serve.server import (ServeClient, ServingServer,
                                    ServingTier, build_serving,
                                    drive_traffic)

__all__ = [
    "BatchCoalescer", "ModelEndpoint", "PERSONAL_FIELD",
    "RolloutManager", "ServeClient", "ServedModel", "ServingServer",
    "ServingTier", "ShedError", "bucket_for", "bucket_ladder",
    "build_serving", "drive_traffic",
]
