"""Staleness-bounded rollout: aggregated checkpoints -> the endpoint.

The rollout layer is the only writer of the endpoint's served slots.
It is fed two ways, mirroring how the training tier itself moves
models:

- **full checkpoints** — ``ServerControlCheckpointer`` blobs (the
  elastic control plane's durable snapshots): ``watch_checkpoints``
  polls the directory and installs each new round's
  ``global_model``. This path needs no live trainer at all — it is
  what keeps the endpoint serving across a SIGKILLed training server
  (the blobs outlive the process) and what lets a standalone serving
  process follow a training run it never shares memory with;
- **compression-mirror deltas** — the cross-silo server's broadcast
  payloads double as checkpoint deltas: ``publish`` accepts either a
  full numpy tree or a compressed payload and decodes the latter
  against the PREVIOUS served params with the SAME
  ``comm/compression.py`` decode path the silos use — structure
  fingerprint checked, and any mismatch falls back to a full model
  (checkpoint blob when available) exactly like the silo JOIN resync
  rule.

**Personalized variants**: per-silo / per-cohort fine-tuned deltas held
in the PR-6 tiered client-state store (field ``serve_delta``, one flat
f32 delta per variant id) are applied to the served global so the
endpoint serves fine-tuned models, not just the one global
(``refresh_personalized``).

**Staleness bound**: the rollout tracks the newest TRAINED round it has
seen vs the round actually serving; the gap is the staleness the
``serve_staleness_rounds`` gauge high-waters, and responses past
``staleness_rounds`` are flagged stale (the transport front surfaces
the flag; requests are still answered — a bounded-stale answer beats a
refused one, the same judgment the deadline-partial aggregate makes).

Swaps run on ONE rollout worker thread: ``publish`` only enqueues, so
the training round loop never blocks on a device transfer for serving
(pure-observer discipline), and the endpoint's reference flip stays
out of every request AND out of every round close.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Any, Dict, Optional

import numpy as np

#: client-state store field holding per-variant personalization deltas
#: (flat f32, quantize_tree layout — the same flat layout the top-k EF
#: residuals use)
PERSONAL_FIELD = "serve_delta"


def _apply_flat_delta(tree, flat_delta: np.ndarray):
    """tree + delta, delta in the flat f32 layout over tree's leaves —
    decoded by the compression layer's OWN layout inverse
    (``comm/compression._unflatten_like``), so a personalization delta
    rebuilds exactly like a top-k EF payload would and the two layouts
    can never drift apart."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.comm.compression import _unflatten_like
    from fedml_tpu.core import pytree as pt
    total = sum(int(np.prod(np.shape(l)) or 1)
                for l in jax.tree.leaves(tree))
    if int(flat_delta.size) != total:
        raise ValueError(
            f"personalization delta has {flat_delta.size} params but the "
            f"served model has {total} — refusing a silently wrong "
            "variant")
    return jax.tree.map(np.asarray, pt.tree_add(
        tree, _unflatten_like(jnp.asarray(flat_delta, jnp.float32),
                              tree)))


class RolloutManager:
    """Feeds the endpoint; owns the swap worker and the staleness gauge."""

    def __init__(self, endpoint, *, staleness_rounds: int = 2,
                 checkpoint_dir: Optional[str] = None,
                 checkpointer=None, store=None, timer=None, obs=None):
        self.endpoint = endpoint
        self.staleness_rounds = max(0, int(staleness_rounds))
        self._timer = timer
        self._obs = obs
        self._ckpt = checkpointer
        if checkpoint_dir and checkpointer is None:
            from fedml_tpu.control import ServerControlCheckpointer
            self._ckpt = ServerControlCheckpointer(checkpoint_dir)
        self._store = store
        #: last FULL params actually serving (numpy) — the delta decode
        #: base; advanced by exactly what each publish decodes to, the
        #: same chain discipline as the silo mirror
        self._base = None
        #: True once a delta was refused/skipped: the base has drifted
        #: off the sender's mirror at the VALUE level, which the
        #: structure fingerprint cannot see — every further delta must
        #: be refused (fallback or skip) until a FULL model rebases
        #: the chain, exactly as a resynced silo waits for its full
        #: mirror before decoding shared deltas again
        self._chain_broken = False
        self._lock = threading.Lock()
        self.served_round = -1
        self.trained_round = -1
        self.delta_swaps = 0
        self.full_swaps = 0
        self.fallbacks = 0
        #: FIFO swap queue, applied strictly in publish order: delta
        #: payloads decode against the base the PREVIOUS payload
        #: produced (the silo-mirror chain discipline), so a skipped
        #: intermediate delta would silently corrupt every later
        #: rebuild — the structure fingerprint cannot see value-level
        #: base drift. The worker keeps up trivially (one device_put
        #: per round); a deep queue is logged, never dropped.
        self._pending: "queue.Queue" = queue.Queue()
        #: published-but-not-yet-installed count — drain()'s real
        #: completion signal (queue emptiness goes true the moment the
        #: worker DEQUEUES the last item, before its install lands)
        self._inflight = 0
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._swap_loop,
                                        daemon=True, name="serve-rollout")
        self._worker.start()

    # -- publish (trainer side: enqueue only, never block) -------------------
    def publish(self, round_idx: int, payload, *,
                rebase: bool = True) -> None:
        """Hand the rollout one trained round: a full numpy model tree
        or a compressed broadcast payload (``comm/compression.py``
        dict). Called from the training server's round loop — must not
        block, must not raise (pure observer).

        ``rebase`` (full payloads only): True means this full IS the
        sender's mirror rebase (a live full broadcast), so it
        re-licenses delta decoding after a chain break. Checkpoint-fed
        fulls pass False — a blob holds the exact GLOBAL, which under
        lossy downlink is not the mirror the next delta is encoded
        against."""
        try:
            with self._lock:
                self.trained_round = max(self.trained_round,
                                         int(round_idx))
            self._mirror_staleness()
            with self._lock:
                self._inflight += 1
            self._pending.put((int(round_idx), payload, bool(rebase)))
            depth = self._pending.qsize()
            if depth > 8:
                logging.warning(
                    "serve rollout swap queue depth %d — the swap worker "
                    "is falling behind training", depth)
        except Exception:
            logging.warning("serve publish for round %s failed — the "
                            "endpoint keeps its current model", round_idx,
                            exc_info=True)

    def _mirror_staleness(self) -> None:
        st = self.staleness()
        if self._timer is not None:
            self._timer.gauge("serve_staleness_rounds", float(st))

    def staleness(self) -> int:
        """Trained-vs-serving round gap (0 while the endpoint is
        current; requests past ``staleness_rounds`` get flagged)."""
        with self._lock:
            if self.trained_round < 0 or self.served_round < 0:
                return 0
            return max(0, self.trained_round - self.served_round)

    def stale(self) -> bool:
        return self.staleness() > self.staleness_rounds

    # -- swap worker ---------------------------------------------------------
    def _swap_loop(self) -> None:
        while not self._stop.is_set():
            try:
                round_idx, payload, rebase = self._pending.get(
                    timeout=0.1)
            except queue.Empty:
                continue
            try:
                self._install(round_idx, payload, rebase=rebase)
            except Exception:
                with self._lock:
                    kept = self.served_round
                logging.warning("serve swap for round %d failed — "
                                "endpoint keeps round %d", round_idx,
                                kept, exc_info=True)
            finally:
                with self._lock:
                    self._inflight -= 1

    def _install(self, round_idx: int, payload, *,
                 rebase: bool = True) -> None:
        from fedml_tpu.comm.compression import decompress, is_compressed
        if is_compressed(payload):
            base = self._base
            try:
                if base is None:
                    raise ValueError("no served base for a delta payload")
                if self._chain_broken:
                    # the base drifted off the sender's mirror at the
                    # VALUE level (a refused/skipped delta) — the
                    # fingerprint check below would pass and silently
                    # rebuild a wrong model; refuse until a full lands
                    raise ValueError(
                        "delta chain broken by an earlier refusal — "
                        "waiting for a full-model rebase")
                import jax
                # delta rebuild is device compute: hold the SAME mutex
                # (or per-job gate) as every other dispatch — the server
                # gates its identical decompress call, and an ungated
                # decode here would be a second dispatch queue racing
                # training (and would dodge the scheduler's fair-share
                # accounting)
                with self.endpoint._device_lock:
                    full = jax.tree.map(np.asarray,
                                        decompress(payload, base))
                self.delta_swaps += 1
            except Exception as exc:
                # fingerprint/count mismatch, no base, or a broken
                # chain: the silo-resync rule — fall back to a FULL
                # model (checkpoint blob when one exists) rather than
                # installing a wrong rebuild, and mark the chain broken
                # so LATER deltas (encoded against the mirror we no
                # longer track) are refused too
                self.fallbacks += 1
                self._chain_broken = True
                logging.warning(
                    "serve delta for round %d refused (%s) — falling "
                    "back to a full checkpoint", round_idx, exc)
                got = self._full_from_checkpoint()
                if got is None:
                    return  # keep serving the last good round
                round_idx, full = got  # the blob's OWN round labels it
                rebase = False  # a blob is the GLOBAL, not the mirror
        else:
            import jax
            full = jax.tree.map(np.asarray, payload)
            self.full_swaps += 1
        with self._lock:
            if int(round_idx) < self.served_round:
                # a stale source (an old checkpoint blob after a
                # fallback, a late re-publish) must never regress the
                # endpoint to an earlier round. A refused DELTA or
                # refused live rebase still moved the SENDER's mirror
                # past our base — mark the chain broken so the next
                # delta is refused instead of decoded against the
                # wrong base; a refused stale blob touched neither
                # side, so the chain state stands.
                if is_compressed(payload) or rebase:
                    self._chain_broken = True
                logging.warning(
                    "serve install for round %d refused — already "
                    "serving round %d", round_idx, self.served_round)
                return
        self.endpoint.install(round_idx, full)
        with self._lock:
            self._base = full
            # _chain_broken tracks ONE invariant: does _base equal the
            # sender's current mirror? A LIVE full broadcast rebases
            # the mirror to exactly this tree — intact again; a blob
            # full (rebase=False) installs the exact GLOBAL, which
            # under lossy downlink is NOT the mirror the next delta is
            # encoded against — broken until the server's next full
            # rebase lands (silo resync, failover restore, or FINISH).
            # A decoded delta advanced base exactly as the mirror
            # advanced, so it preserves whichever state held — and it
            # only decodes at all when the chain was intact.
            if not is_compressed(payload):
                self._chain_broken = not rebase
            self.served_round = int(round_idx)
            self.trained_round = max(self.trained_round, int(round_idx))
        self._mirror_staleness()

    def _full_from_checkpoint(self):
        """``(round_idx, global_model)`` from the newest complete blob,
        or None."""
        if self._ckpt is None:
            return None
        try:
            snap = self._ckpt.load_latest()
        except Exception:
            logging.warning("serve checkpoint read failed", exc_info=True)
            return None
        if snap is None:
            return None
        return int(snap["round_idx"]), snap["global_model"]

    # -- checkpoint-fed rollout (standalone / failover serving) --------------
    def refresh_from_checkpoint(self) -> bool:
        """Install the newest full checkpoint blob if it is newer than
        what is serving. Returns True when a swap was enqueued. The
        poll half of the ``watch_checkpoints`` loop; also the delta
        path's fallback source."""
        if self._ckpt is None:
            return False
        try:
            latest = self._ckpt.latest_round()
        except Exception:
            logging.warning("serve checkpoint poll failed", exc_info=True)
            return False
        if latest is None:
            return False
        with self._lock:
            self.trained_round = max(self.trained_round, int(latest))
            have = self.served_round
        self._mirror_staleness()
        if latest <= have:
            return False
        got = self._full_from_checkpoint()
        if got is None:
            return False
        blob_round, full = got
        # blob = the exact GLOBAL, not the sender's mirror — it must
        # not re-license delta decoding after a chain break
        self.publish(blob_round, full, rebase=False)
        return True

    def watch_checkpoints(self, poll_s: float = 0.25) -> threading.Event:
        """Background poller: follow the checkpoint directory until the
        returned event is set. The standalone serving mode — survives
        the training process dying entirely (blobs are durable)."""
        stop = threading.Event()

        def loop():
            while not stop.wait(poll_s):
                self.refresh_from_checkpoint()

        threading.Thread(target=loop, daemon=True,
                         name="serve-ckpt-watch").start()
        return stop

    # -- personalized variants (PR-6 tiered client-state store) --------------
    def publish_personal(self, variant: str, round_idx: int,
                         flat_delta: np.ndarray) -> None:
        """Install one personalized variant: served global + delta.
        Deltas ride the flat f32 layout (the EF-residual layout), so a
        fine-tuning job can write them straight into the store."""
        with self._lock:
            base = self._base
        if base is None:
            raise RuntimeError("no global model served yet — personalized "
                               "variants apply deltas to the served base")
        with self.endpoint._device_lock:  # delta apply is device compute
            rebuilt = _apply_flat_delta(base, flat_delta)
        self.endpoint.install(round_idx, rebuilt, variant=str(variant))

    def refresh_personalized(self, round_idx: Optional[int] = None
                             ) -> int:
        """Read every variant delta from the client-state store's
        ``serve_delta`` field and (re)install the variants against the
        CURRENT served global. Returns the number installed."""
        if self._store is None:
            return 0
        with self._lock:
            base_round = self.served_round
        r = int(round_idx) if round_idx is not None else base_round
        n = 0
        for vid in sorted(self._store.known_ids(PERSONAL_FIELD)):
            try:
                delta = self._store.get(PERSONAL_FIELD, int(vid))
                self.publish_personal(str(vid), r,
                                      np.asarray(delta, np.float32))
                n += 1
            except (KeyError, ValueError, RuntimeError):
                logging.warning("personal variant %s refused", vid,
                                exc_info=True)
        return n

    # -- lifecycle / reporting ----------------------------------------------
    def counters(self) -> Dict[str, Any]:
        with self._lock:
            return {"served_round": int(self.served_round),
                    "trained_round": int(self.trained_round),
                    "staleness": int(max(0, self.trained_round
                                         - self.served_round))
                    if self.served_round >= 0 else 0,
                    "delta_swaps": int(self.delta_swaps),
                    "full_swaps": int(self.full_swaps),
                    "fallbacks": int(self.fallbacks)}

    def drain(self, timeout_s: float = 10.0) -> None:
        """Block until every PUBLISHED swap has been fully applied
        (tests and orderly shutdown; the live path never waits). Waits
        on the in-flight count, not queue emptiness — the queue drains
        one dequeue BEFORE the last install lands."""
        import time as _time
        deadline = _time.monotonic() + timeout_s
        while True:
            with self._lock:
                if self._inflight <= 0:
                    return
            # ft: allow[FT015] bounded shutdown drain — a wall-clock cap on how long close() waits for the swap worker
            if _time.monotonic() >= deadline:
                return
            _time.sleep(0.01)

    def close(self) -> None:
        self.drain(timeout_s=5.0)
        self._stop.set()
        self._worker.join(timeout=5)
