"""Layer 2 — jaxpr audit of registered hot entry points.

Where the AST lint sees spelling, this layer sees the program XLA will
actually receive: each registered entry point (analysis/registry.py) is
traced with ``jax.make_jaxpr`` over its declared argument sweep and the
closed jaxpr is walked recursively (through pjit / scan / while /
custom-vjp sub-jaxprs) for the hazard classes the project has been
bitten by:

- **FT101** — a float64 aval anywhere under x64-off intent: under
  x64-off jax truncates it silently (an intent bug wearing f32
  clothes); under x64-on it is a 2x bandwidth tax.
- **FT102** — ``pure_callback`` / ``io_callback`` / ``debug_callback``
  inside a ``scan``/``while`` body: a host round-trip per iteration,
  i.e. a fused R-round scan degenerates to R host syncs.
- **FT103** — ``convert_element_type`` float upcasts inside a
  grad-declared program (accidental mixed-precision promotion on the
  backward path; checked more strictly than forward-only entries,
  which only flag upcasts landing in f64).
- **FT104** — distinct lowering keys across the declared sweep: the
  r5 bench artifact class. The key is the tuple of input avals
  (shape, dtype, weak_type) — exactly what jit caches on — so a weak
  vs strong scalar, a flipped dtype, or a shape drift between rounds
  shows up as key count > ``max_lowerings`` and fails CI instead of a
  bench window.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from fedml_tpu.analysis.finding import Finding, audit_finding
from fedml_tpu.analysis.registry import AuditSpec, load_entry_points

try:  # jax >= 0.4.x exposes the stable aliases under jax.extend
    from jax.extend import core as _jcore
except ImportError:  # pragma: no cover - very old jax
    from jax import core as _jcore  # type: ignore

LOOP_PRIMITIVES = frozenset({"scan", "while"})
CALLBACK_PRIMITIVES = frozenset(
    {"pure_callback", "io_callback", "debug_callback"})


def _sub_jaxprs(eqn) -> List[Any]:
    """Every Jaxpr/ClosedJaxpr nested in an eqn's params (pjit's
    ``jaxpr``, scan's ``jaxpr``, while's ``cond_jaxpr``/``body_jaxpr``,
    custom-vjp's ``fun_jaxpr``, branches tuples, ...)."""
    out: List[Any] = []
    for val in eqn.params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            if isinstance(v, (_jcore.Jaxpr, _jcore.ClosedJaxpr)):
                out.append(v)
    return out


def _as_jaxpr(j):
    return j.jaxpr if isinstance(j, _jcore.ClosedJaxpr) else j


def _walk(jaxpr, in_loop: bool, visit) -> None:
    """DFS over eqns; ``visit(eqn, in_loop)``; loop flag set below
    scan/while."""
    for eqn in _as_jaxpr(jaxpr).eqns:
        visit(eqn, in_loop)
        child_in_loop = in_loop or eqn.primitive.name in LOOP_PRIMITIVES
        for sub in _sub_jaxprs(eqn):
            _walk(sub, child_in_loop, visit)


def _aval_key(aval) -> Tuple:
    return (str(getattr(aval, "shape", None)),
            str(getattr(aval, "dtype", None)),
            bool(getattr(aval, "weak_type", False)))


def signature_key(closed) -> Tuple:
    """The lowering key of a traced call: input avals incl. weak_type —
    the same equivalence jit's compile cache uses."""
    return tuple(_aval_key(v.aval) for v in _as_jaxpr(closed).invars)


def _is_f64(aval) -> bool:
    return str(getattr(aval, "dtype", "")) == "float64"


def _float_width(dtype) -> Optional[int]:
    s = str(dtype)
    if s in ("float16", "bfloat16"):
        return 16
    if s == "float32":
        return 32
    if s == "float64":
        return 64
    return None


def audit_spec(name: str, spec: AuditSpec) -> Tuple[List[Finding], Dict]:
    """Trace + walk one entry point. Returns (findings, report) where
    report carries the evidence CI artifacts and tests assert on:
    ``n_lowering_keys``, ``n_eqns``, ``sweep_len``."""
    findings: List[Finding] = []
    keys = []
    jaxprs = []
    for args in spec.sweep:
        closed = jax.make_jaxpr(spec.fn)(*args)
        jaxprs.append(closed)
        keys.append(signature_key(closed))
    distinct = sorted(set(keys), key=keys.index)
    if len(distinct) > spec.max_lowerings:
        findings.append(audit_finding(
            "FT104", name,
            f"{len(distinct)} distinct lowering keys across the declared "
            f"{len(spec.sweep)}-point sweep (contract: "
            f"<= {spec.max_lowerings}) — each extra key is a recompile "
            "landing at an uncontrolled moment",
            hint="align the callers' arg dtypes/weak-types (jnp-typed "
                 "scalars) or mark program-variant args static",
            detail="; ".join(repr(k) for k in distinct[:4])))

    f64_seen: List[str] = []
    callback_in_loop: List[str] = []
    upcasts: List[str] = []

    def visit(eqn, in_loop: bool) -> None:
        prim = eqn.primitive.name
        if prim in CALLBACK_PRIMITIVES and in_loop:
            callback_in_loop.append(prim)
        if not spec.allow_f64:
            for v in eqn.outvars:
                if _is_f64(getattr(v, "aval", None)):
                    f64_seen.append(prim)
                    break
        if prim == "convert_element_type":
            old = _float_width(getattr(eqn.invars[0].aval, "dtype", None))
            new = _float_width(eqn.params.get("new_dtype"))
            if old and new and new > old and (spec.grad_path or new == 64):
                upcasts.append(
                    f"{eqn.invars[0].aval.dtype}->{eqn.params['new_dtype']}")

    # hazard-walk ONE representative jaxpr per distinct lowering key —
    # with max_lowerings > 1 a hazard may live only in the program a
    # later sweep point traces (different branch/shape), and walking
    # only jaxprs[0] would report the entry clean
    walked_keys = set()
    for key, closed in zip(keys, jaxprs):
        if key in walked_keys:
            continue
        walked_keys.add(key)
        _walk(closed, False, visit)
        if not spec.allow_f64:
            for v in _as_jaxpr(closed).invars + _as_jaxpr(closed).outvars:
                if _is_f64(getattr(v, "aval", None)):
                    f64_seen.append("(entry boundary)")
                    break
    closed = jaxprs[0]  # report shape metadata from the first trace

    if f64_seen:
        findings.append(audit_finding(
            "FT101", name,
            f"float64 result(s) in the traced program (first at: "
            f"{f64_seen[0]}) under x64-off intent — silently truncated "
            "today, a 2x bandwidth tax the day x64 is enabled",
            hint="pin the literal/dtype to f32, or set allow_f64=True on "
                 "the AuditSpec if this entry means it",
            detail=",".join(f64_seen[:6])))
    if callback_in_loop:
        findings.append(audit_finding(
            "FT102", name,
            f"host callback ({callback_in_loop[0]}) inside a scan/while "
            "body — one host round-trip per iteration defeats the fused "
            "round scan",
            hint="hoist the callback out of the loop body, or debug with "
                 "jax.debug.print only in non-fused paths",
            detail=",".join(sorted(set(callback_in_loop)))))
    if upcasts:
        findings.append(audit_finding(
            "FT103", name,
            f"float upcast(s) on the traced path of a grad-declared "
            f"entry: {', '.join(sorted(set(upcasts))[:4])}",
            hint="make the accumulation dtype explicit at the cast site "
                 "(preferred) or declare the entry forward-only",
            detail=",".join(sorted(set(upcasts)))))

    report = {"entry": name, "sweep_len": len(spec.sweep),
              "n_lowering_keys": len(distinct),
              "max_lowerings": spec.max_lowerings,
              "n_eqns": len(_as_jaxpr(closed).eqns),
              "grad_path": spec.grad_path}
    return findings, report


def run_audit(only: Optional[Sequence[str]] = None
              ) -> Tuple[List[Finding], List[Dict]]:
    """Build + audit every registered entry point (or the ``only``
    subset). A builder/trace crash is a loud FT100 finding, never a
    silently shorter audit."""
    entries = load_entry_points()
    findings: List[Finding] = []
    reports: List[Dict] = []
    for name in sorted(entries):
        if only and name not in only:
            continue
        try:
            spec = entries[name]()
            got, report = audit_spec(name, spec)
        except Exception as exc:
            logging.exception("jaxpr audit: entry %s failed", name)
            findings.append(audit_finding(
                "FT100", name,
                f"entry point failed to build/trace: {type(exc).__name__}: "
                f"{exc}",
                hint="an auditable entry must stay traceable on the CPU CI "
                     "backend; fix the builder or the program"))
            continue
        findings.extend(got)
        reports.append(report)
    return findings, reports
