"""Layer 2 — jaxpr audit of registered hot entry points.

Where the AST lint sees spelling, this layer sees the program XLA will
actually receive: each registered entry point (analysis/registry.py) is
traced with ``jax.make_jaxpr`` over its declared argument sweep and the
closed jaxpr is walked recursively (through pjit / scan / while /
custom-vjp sub-jaxprs) for the hazard classes the project has been
bitten by:

- **FT101** — a float64 aval anywhere under x64-off intent: under
  x64-off jax truncates it silently (an intent bug wearing f32
  clothes); under x64-on it is a 2x bandwidth tax.
- **FT102** — ``pure_callback`` / ``io_callback`` / ``debug_callback``
  inside a ``scan``/``while`` body: a host round-trip per iteration,
  i.e. a fused R-round scan degenerates to R host syncs.
- **FT103** — ``convert_element_type`` float upcasts inside a
  grad-declared program (accidental mixed-precision promotion on the
  backward path; checked more strictly than forward-only entries,
  which only flag upcasts landing in f64).
- **FT104** — distinct lowering keys across the declared sweep: the
  r5 bench artifact class. The key is the tuple of input avals
  (shape, dtype, weak_type) — exactly what jit caches on — so a weak
  vs strong scalar, a flipped dtype, or a shape drift between rounds
  shows up as key count > ``max_lowerings`` and fails CI instead of a
  bench window.
- **FT105/FT106** — collective-signature drift: each entry's traced
  program yields a *collective signature* — every ``psum`` /
  ``all_gather`` / ``ppermute`` / ``reduce_scatter`` / ... eqn with its
  axis names, eqn count, and estimated output bytes — checked against
  the fingerprinted ``ci/collective_baseline.json``. A new unsolicited
  collective, a changed axis, or a changed count is FT105; a bytes
  estimate drifting beyond ``BYTES_TOLERANCE`` is FT106. This is the
  ROADMAP SPMD item's CI guard: when the multi-chip mesh lands, a
  sharded lowering that silently grows an all-gather fails lint, not a
  bench. Regenerate deliberately with ``--write-collective-baseline``.
"""

from __future__ import annotations

import hashlib
import json
import logging
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from fedml_tpu.analysis.finding import Finding, audit_finding
from fedml_tpu.analysis.registry import AuditSpec, load_entry_points

try:  # jax >= 0.4.x exposes the stable aliases under jax.extend
    from jax.extend import core as _jcore
except ImportError:  # pragma: no cover - very old jax
    from jax import core as _jcore  # type: ignore

LOOP_PRIMITIVES = frozenset({"scan", "while"})
CALLBACK_PRIMITIVES = frozenset(
    {"pure_callback", "io_callback", "debug_callback"})

#: cross-device communication primitives (the collective signature)
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pgather"})

#: FT106 fires when an entry's per-(op, axes) bytes estimate grows or
#: shrinks beyond this factor vs the baseline (shape-tolerant: model or
#: batch tweaks within 1.5x pass; a 4x all-gather blowup does not)
BYTES_TOLERANCE = 1.5

COLLECTIVE_BASELINE_VERSION = 1


def _sub_jaxprs(eqn) -> List[Any]:
    """Every Jaxpr/ClosedJaxpr nested in an eqn's params (pjit's
    ``jaxpr``, scan's ``jaxpr``, while's ``cond_jaxpr``/``body_jaxpr``,
    custom-vjp's ``fun_jaxpr``, branches tuples, ...)."""
    out: List[Any] = []
    for val in eqn.params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            if isinstance(v, (_jcore.Jaxpr, _jcore.ClosedJaxpr)):
                out.append(v)
    return out


def _as_jaxpr(j):
    return j.jaxpr if isinstance(j, _jcore.ClosedJaxpr) else j


def _walk(jaxpr, in_loop: bool, visit) -> None:
    """DFS over eqns; ``visit(eqn, in_loop)``; loop flag set below
    scan/while."""
    for eqn in _as_jaxpr(jaxpr).eqns:
        visit(eqn, in_loop)
        child_in_loop = in_loop or eqn.primitive.name in LOOP_PRIMITIVES
        for sub in _sub_jaxprs(eqn):
            _walk(sub, child_in_loop, visit)


def _aval_key(aval) -> Tuple:
    return (str(getattr(aval, "shape", None)),
            str(getattr(aval, "dtype", None)),
            bool(getattr(aval, "weak_type", False)))


def signature_key(closed) -> Tuple:
    """The lowering key of a traced call: input avals incl. weak_type —
    the same equivalence jit's compile cache uses."""
    return tuple(_aval_key(v.aval) for v in _as_jaxpr(closed).invars)


def _is_f64(aval) -> bool:
    return str(getattr(aval, "dtype", "")) == "float64"


def _float_width(dtype) -> Optional[int]:
    s = str(dtype)
    if s in ("float16", "bfloat16"):
        return 16
    if s == "float32":
        return 32
    if s == "float64":
        return 64
    return None


def audit_spec(name: str, spec: AuditSpec) -> Tuple[List[Finding], Dict]:
    """Trace + walk one entry point. Returns (findings, report) where
    report carries the evidence CI artifacts and tests assert on:
    ``n_lowering_keys``, ``n_eqns``, ``sweep_len``."""
    findings: List[Finding] = []
    keys = []
    jaxprs = []
    for args in spec.sweep:
        closed = jax.make_jaxpr(spec.fn)(*args)
        jaxprs.append(closed)
        keys.append(signature_key(closed))
    distinct = sorted(set(keys), key=keys.index)
    if len(distinct) > spec.max_lowerings:
        findings.append(audit_finding(
            "FT104", name,
            f"{len(distinct)} distinct lowering keys across the declared "
            f"{len(spec.sweep)}-point sweep (contract: "
            f"<= {spec.max_lowerings}) — each extra key is a recompile "
            "landing at an uncontrolled moment",
            hint="align the callers' arg dtypes/weak-types (jnp-typed "
                 "scalars) or mark program-variant args static",
            detail="; ".join(repr(k) for k in distinct[:4])))

    f64_seen: List[str] = []
    callback_in_loop: List[str] = []
    upcasts: List[str] = []
    #: (op, axes) -> [eqn count, output bytes] — the collective
    #: signature, collected from the FIRST trace only so the numbers do
    #: not scale with sweep length (signature stability across the
    #: sweep is FT104's job)
    collectives: Dict[Tuple[str, Tuple[str, ...]], List[int]] = {}
    _first_walk = [True]

    def _collective_axes(eqn) -> Tuple[str, ...]:
        axes = eqn.params.get("axes", eqn.params.get("axis_name"))
        if axes is None:
            return ()
        if not isinstance(axes, (tuple, list)):
            axes = (axes,)
        return tuple(sorted(str(a) for a in axes))

    def visit(eqn, in_loop: bool) -> None:
        prim = eqn.primitive.name
        if prim in COLLECTIVE_PRIMITIVES and _first_walk[0]:
            key = (prim, _collective_axes(eqn))
            entry = collectives.setdefault(key, [0, 0])
            entry[0] += 1
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                shape = getattr(aval, "shape", None)
                dtype = getattr(aval, "dtype", None)
                if shape is None or dtype is None:
                    continue
                n = 1
                for d in shape:
                    n *= int(d)
                entry[1] += n * dtype.itemsize
        if prim in CALLBACK_PRIMITIVES and in_loop:
            callback_in_loop.append(prim)
        if not spec.allow_f64:
            for v in eqn.outvars:
                if _is_f64(getattr(v, "aval", None)):
                    f64_seen.append(prim)
                    break
        if prim == "convert_element_type":
            old = _float_width(getattr(eqn.invars[0].aval, "dtype", None))
            new = _float_width(eqn.params.get("new_dtype"))
            if old and new and new > old and (spec.grad_path or new == 64):
                upcasts.append(
                    f"{eqn.invars[0].aval.dtype}->{eqn.params['new_dtype']}")

    # hazard-walk ONE representative jaxpr per distinct lowering key —
    # with max_lowerings > 1 a hazard may live only in the program a
    # later sweep point traces (different branch/shape), and walking
    # only jaxprs[0] would report the entry clean
    walked_keys = set()
    for key, closed in zip(keys, jaxprs):
        if key in walked_keys:
            continue
        walked_keys.add(key)
        _walk(closed, False, visit)
        _first_walk[0] = False
        if not spec.allow_f64:
            for v in _as_jaxpr(closed).invars + _as_jaxpr(closed).outvars:
                if _is_f64(getattr(v, "aval", None)):
                    f64_seen.append("(entry boundary)")
                    break
    closed = jaxprs[0]  # report shape metadata from the first trace

    if f64_seen:
        findings.append(audit_finding(
            "FT101", name,
            f"float64 result(s) in the traced program (first at: "
            f"{f64_seen[0]}) under x64-off intent — silently truncated "
            "today, a 2x bandwidth tax the day x64 is enabled",
            hint="pin the literal/dtype to f32, or set allow_f64=True on "
                 "the AuditSpec if this entry means it",
            detail=",".join(f64_seen[:6])))
    if callback_in_loop:
        findings.append(audit_finding(
            "FT102", name,
            f"host callback ({callback_in_loop[0]}) inside a scan/while "
            "body — one host round-trip per iteration defeats the fused "
            "round scan",
            hint="hoist the callback out of the loop body, or debug with "
                 "jax.debug.print only in non-fused paths",
            detail=",".join(sorted(set(callback_in_loop)))))
    if upcasts:
        findings.append(audit_finding(
            "FT103", name,
            f"float upcast(s) on the traced path of a grad-declared "
            f"entry: {', '.join(sorted(set(upcasts))[:4])}",
            hint="make the accumulation dtype explicit at the cast site "
                 "(preferred) or declare the entry forward-only",
            detail=",".join(sorted(set(upcasts)))))

    report = {"entry": name, "sweep_len": len(spec.sweep),
              "n_lowering_keys": len(distinct),
              "max_lowerings": spec.max_lowerings,
              "n_eqns": len(_as_jaxpr(closed).eqns),
              "grad_path": spec.grad_path,
              "collectives": [
                  {"op": op, "axes": list(axes), "count": cnt,
                   "bytes": nbytes}
                  for (op, axes), (cnt, nbytes) in sorted(
                      collectives.items())]}
    return findings, report


# -- collective-signature baseline (FT105/FT106) -----------------------------

def collective_signature(report: Dict) -> List[Dict]:
    return report.get("collectives", [])


def _signature_fingerprint(collectives: List[Dict]) -> str:
    blob = json.dumps(collectives, sort_keys=True)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def write_collective_baseline(path: Path, reports: Sequence[Dict]) -> None:
    """Snapshot every audited entry's collective signature (op + axes +
    count + bytes, fingerprinted) — the deliberate, reviewable way to
    accept a collective change."""
    entries = {}
    for rep in reports:
        sig = collective_signature(rep)
        entries[rep["entry"]] = {
            "collectives": sig,
            "fingerprint": _signature_fingerprint(sig)}
    payload = {"version": COLLECTIVE_BASELINE_VERSION, "entries": entries}
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")


def check_collective_baseline(reports: Sequence[Dict], path: Path
                              ) -> Tuple[List[Finding], List[str]]:
    """-> (findings, stale_entry_names) vs ``ci/collective_baseline.json``.

    A missing or unreadable baseline is a LOUD FT105 — a deleted
    snapshot must fail CI, never silently skip the drift check. A
    baseline entry whose entry point no longer exists is stale (warn,
    like stale finding-baseline entries)."""
    path = Path(path)
    regen = ("accept deliberately: python -m fedml_tpu.analysis "
             "--write-collective-baseline")
    if not path.exists():
        return [audit_finding(
            "FT105", "<baseline>",
            f"collective baseline {path} is MISSING — collective-"
            "signature drift cannot be checked, and a silently skipped "
            "check is the failure mode this audit exists to prevent",
            hint=regen)], []
    try:
        data = json.loads(path.read_text())
        if data.get("version") != COLLECTIVE_BASELINE_VERSION:
            raise ValueError(
                f"unsupported version {data.get('version')!r}")
        baseline = data["entries"]
    except (OSError, ValueError, KeyError) as exc:
        return [audit_finding(
            "FT105", "<baseline>",
            f"collective baseline {path} is unreadable ({exc}) — "
            "regenerate it", hint=regen)], []
    findings: List[Finding] = []
    seen = set()
    for rep in reports:
        name = rep["entry"]
        seen.add(name)
        sig = collective_signature(rep)
        base = baseline.get(name)
        if base is None:
            findings.append(audit_finding(
                "FT105", name,
                "entry point has no collective-baseline entry — every "
                "registered hot entry point must be covered so a new "
                "collective cannot land unreviewed", hint=regen,
                detail=_signature_fingerprint(sig)))
            continue
        if base.get("fingerprint") == _signature_fingerprint(sig):
            continue
        by_key_new = {(c["op"], tuple(c["axes"])): c for c in sig}
        by_key_old = {(c["op"], tuple(c["axes"])): c
                      for c in base.get("collectives", [])}
        for key in sorted(set(by_key_new) - set(by_key_old)):
            c = by_key_new[key]
            findings.append(audit_finding(
                "FT105", name,
                f"NEW collective {c['op']} over axes {c['axes']} "
                f"({c['count']} eqn(s), ~{c['bytes']} bytes) not in the "
                "baseline — an unsolicited cross-device transfer on the "
                "hot path", hint=regen,
                detail=f"+{c['op']}{c['axes']}"))
        for key in sorted(set(by_key_old) - set(by_key_new)):
            c = by_key_old[key]
            findings.append(audit_finding(
                "FT105", name,
                f"collective {c['op']} over axes {c['axes']} DISAPPEARED "
                "from the traced program — an aggregation the protocol "
                "depends on may have been sharded away", hint=regen,
                detail=f"-{c['op']}{c['axes']}"))
        for key in sorted(set(by_key_old) & set(by_key_new)):
            new, old = by_key_new[key], by_key_old[key]
            if new["count"] != old["count"]:
                    findings.append(audit_finding(
                    "FT105", name,
                    f"collective {new['op']} over axes {new['axes']} "
                    f"changed eqn count {old['count']} -> "
                    f"{new['count']}", hint=regen,
                    detail=f"{new['op']}{new['axes']} "
                           f"count {old['count']}->{new['count']}"))
            elif old["bytes"] and not (
                    1.0 / BYTES_TOLERANCE
                    <= new["bytes"] / old["bytes"]
                    <= BYTES_TOLERANCE):
                    findings.append(audit_finding(
                    "FT106", name,
                    f"collective {new['op']} over axes {new['axes']} "
                    f"bytes estimate drifted {old['bytes']} -> "
                    f"{new['bytes']} (tolerance {BYTES_TOLERANCE}x) — "
                    "a sharding or batching change moved real "
                    "interconnect traffic", hint=regen,
                    detail=f"{new['op']}{new['axes']} "
                           f"{old['bytes']}->{new['bytes']}"))
        # fingerprint moved but no per-key drift: bytes changed WITHIN
        # tolerance — exactly what BYTES_TOLERANCE exists to absorb, so
        # not a finding (the per-key checks above are the real compare;
        # the fingerprint is only a fast-path short-circuit, and the
        # stored one re-pins on the next deliberate regen)
    stale = sorted(set(baseline) - seen)
    return findings, stale


def run_audit(only: Optional[Sequence[str]] = None
              ) -> Tuple[List[Finding], List[Dict]]:
    """Build + audit every registered entry point (or the ``only``
    subset). A builder/trace crash is a loud FT100 finding, never a
    silently shorter audit."""
    entries = load_entry_points()
    findings: List[Finding] = []
    reports: List[Dict] = []
    for name in sorted(entries):
        if only and name not in only:
            continue
        try:
            spec = entries[name]()
            got, report = audit_spec(name, spec)
        except Exception as exc:
            logging.exception("jaxpr audit: entry %s failed", name)
            findings.append(audit_finding(
                "FT100", name,
                f"entry point failed to build/trace: {type(exc).__name__}: "
                f"{exc}",
                hint="an auditable entry must stay traceable on the CPU CI "
                     "backend; fix the builder or the program"))
            continue
        findings.extend(got)
        reports.append(report)
    return findings, reports
