"""Hot-entry-point registry for the jaxpr audit (layer 2).

Each algorithm/parallel driver registers its hot compiled programs here
via the :func:`hot_entry_point` decorator. Registration is a dict
insert; the decorated BUILDER runs only when the auditor asks, so
drivers pay nothing at import time. A builder returns an
:class:`AuditSpec`: the callable to trace, a *sweep* of argument tuples
that must all lower to the same signature, and the audit intents
(grad-path, f64 tolerance, expected lowering-key count).

This module must stay import-light (stdlib only): driver modules import
it at module scope.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass
class AuditSpec:
    """What the auditor traces for one entry point.

    ``sweep``: argument tuples that are all legitimate calls of ``fn``
    and — per the driver's own signature-stability contract — must
    produce at most ``max_lowerings`` distinct lowering keys (FT104).
    ``grad_path``: the program contains a grad; float upcasts inside it
    are flagged (FT103). ``allow_f64``: set only by entries that mean
    to compute in f64 (none today) — otherwise any f64 aval is FT101.
    """

    fn: Callable
    sweep: Sequence[Tuple[Any, ...]]
    max_lowerings: int = 1
    grad_path: bool = False
    allow_f64: bool = False


#: name -> builder() -> AuditSpec
_REGISTRY: Dict[str, Callable[[], AuditSpec]] = {}

#: modules whose import registers the shipped entry points — the audit
#: imports these lazily; a module that cannot import on this backend
#: surfaces as a loud audit error, not a silently shorter registry
ENTRY_POINT_MODULES = (
    "fedml_tpu.algorithms.fedavg",
    "fedml_tpu.algorithms.fedopt",
    "fedml_tpu.parallel.spmd",
    "fedml_tpu.parallel.mesh",
    "fedml_tpu.ops.flash_attention",
    "fedml_tpu.ops.sparsify",
)


def hot_entry_point(name: str) -> Callable:
    """Decorator: register ``builder`` under ``name``. Re-registration
    under the same name replaces (idempotent under module reload)."""

    def deco(builder: Callable[[], AuditSpec]):
        _REGISTRY[name] = builder
        return builder

    return deco


def load_entry_points(extra_modules: Sequence[str] = ()) -> Dict[str, Callable]:
    """Import the registering modules and return the registry snapshot."""
    for mod in tuple(ENTRY_POINT_MODULES) + tuple(extra_modules):
        importlib.import_module(mod)
    return dict(_REGISTRY)


def get(name: str) -> Optional[Callable[[], AuditSpec]]:
    return _REGISTRY.get(name)


def names() -> List[str]:
    return sorted(_REGISTRY)
