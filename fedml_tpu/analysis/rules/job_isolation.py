"""FT018 — module-global mutable state reachable from a job's
server/silo classes (tenancy isolation guard).

Multi-job tenancy (``fedml_tpu/sched``) runs N federations in ONE
process: every server manager, silo actor, control-plane object, and
compression mirror must be *instanced or keyed per job*, or two tenants
silently share state and the bit-exact solo-parity contract (the chaos
harness's acceptance oracle) rots the first time someone caches
something at module scope "for convenience". That failure mode is
invisible to single-job tests — exactly the class a static guard
exists for.

The rule: in the cross-silo actor modules and the scheduler package, a
module-level binding of a MUTABLE container or synchronization object
(dict/list/set literals and comprehensions; ``dict()``/``list()``/
``set()``/``defaultdict``/``deque``/``OrderedDict``/``Counter`` calls;
``threading.Lock/RLock/Condition/Event/Semaphore``; ``queue.Queue``
family) is a finding when it is *reachable from a job's server/silo
classes* — referenced inside a class whose base names a
``*ServerManager``/``*ClientManager``, or inside a module-level
function such a class calls (one hop — the ``_shared_local_train``
pattern).

Sanctioned singletons carry ``# ft: allow[FT018] why`` at the binding:
the device mutex (one physical dispatch queue exists no matter how many
tenants) and the pure jitted-program cache (keyed by (module, task,
cfg), carries no job state) are the two in-tree examples — the pragma
rationale is the review surface for any future one.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import (FileContext, Rule, dotted_name,
                                     is_corpus_path)

#: the multi-tenant actor surface: cross-silo server/silo modules + the
#: scheduler package itself (path suffixes / path fragments)
_SCOPED_SUFFIXES = ("algorithms/fedavg_cross_silo.py",
                    "algorithms/fedavg_async.py")
_SCOPED_FRAGMENT = "fedml_tpu/sched/"

#: constructor names (last dotted component) that build mutable state
_MUTABLE_CTORS = frozenset({
    "dict", "list", "set", "defaultdict", "deque", "OrderedDict",
    "Counter", "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "LifoQueue", "PriorityQueue",
    "SimpleQueue",
})

_ACTOR_BASES = ("ServerManager", "ClientManager")


def _is_mutable_binding(value: ast.expr) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        name = dotted_name(value.func)
        if name and name.split(".")[-1] in _MUTABLE_CTORS:
            return True
    return False


def _is_actor_class(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        name = (dotted_name(base) or "").split(".")[-1]
        if any(tok in name for tok in _ACTOR_BASES):
            return True
    return False


def _names_loaded(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class JobIsolationRule(Rule):
    id = "FT018"
    title = ("module-global mutable state reachable from a job's "
             "server/silo classes (tenancy isolation hazard)")
    hint = ("move the state onto the manager instance (or key it per "
            "job id); a deliberate process-wide singleton carries "
            "# ft: allow[FT018] with the rationale reviewers will hold "
            "it to")

    def applies(self, relpath: str) -> bool:
        rel = relpath.replace("\\", "/")
        return (any(rel.endswith(s) for s in _SCOPED_SUFFIXES)
                or _SCOPED_FRAGMENT in rel
                or is_corpus_path(relpath))

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # 1) module-level mutable bindings: name -> binding node
        bindings: Dict[str, ast.AST] = {}
        for node in ctx.tree.body:
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
                value = node.value
            else:
                continue
            if not _is_mutable_binding(value):
                continue
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    bindings[tgt.id] = node
        if not bindings:
            return
        # 2) names referenced inside actor classes, and the module-level
        #    functions those classes reach (one hop)
        module_funcs = {n.name: n for n in ctx.tree.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
        reachable: Set[str] = set()
        for cls in ctx.tree.body:
            if not isinstance(cls, ast.ClassDef) or not _is_actor_class(cls):
                continue
            direct = _names_loaded(cls)
            reachable |= direct
            for fname in direct & set(module_funcs):
                reachable |= _names_loaded(module_funcs[fname])
        for name in sorted(set(bindings) & reachable):
            node = bindings[name]
            yield ctx.finding(
                self, node,
                f"module-global mutable {name!r} is reachable from a "
                f"server/silo class — under multi-job tenancy every "
                f"tenant in this process shares it, so one job's state "
                f"can leak into another's trajectory (the bit-exact "
                f"solo-parity contract breaks silently)")
