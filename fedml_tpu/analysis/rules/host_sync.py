"""FT003 — host-sync primitives in hot-path modules.

The whole point of the fused drivers (``FusedRounds``, the mesh block
scans) is that the host enqueues R rounds and syncs ONCE at the eval
boundary. A stray ``.item()`` / ``jax.device_get`` /
``jax.block_until_ready`` in those modules re-serializes host and
device every round — the r04 femnist flagship's "571 s/eval" was
exactly a sync landing inside the wrong phase. ``np.asarray`` is
flagged only inside nested defs (the closures handed to jit/vmap/scan,
where it would silently call back to the host on a tracer); top-level
host packing code uses numpy legitimately.

Intentional syncs — the ``device_wait`` timer phases at eval
boundaries — carry ``# ft: allow[FT003]`` pragmas with their rationale.

Scope: the hot modules only (``parallel/`` compiled drivers +
``algorithms/fedavg.py``, which hosts ``FusedRounds``). Host-side
coordination modules (``parallel/prefetch.py``, ``parallel/multihost.py``)
are excluded: they ARE the host side.
"""

from __future__ import annotations

import ast
from typing import Iterator

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import (FileContext, Rule, dotted_name,
                                     is_corpus_path)

HOT_PATH_FILES = (
    "fedml_tpu/parallel/spmd.py",
    "fedml_tpu/parallel/gspmd_round.py",
    "fedml_tpu/parallel/fsdp.py",
    "fedml_tpu/parallel/tensor.py",
    "fedml_tpu/parallel/sequence.py",
    "fedml_tpu/parallel/pipeline.py",
    "fedml_tpu/parallel/expert.py",
    "fedml_tpu/algorithms/fedavg.py",
)

SYNC_CALLS = {"jax.device_get": "device_get",
              "jax.block_until_ready": "block_until_ready"}


class HostSyncRule(Rule):
    id = "FT003"
    title = "host-device sync primitive in a hot-path module"
    hint = ("keep hot paths async (enqueue-only); sync once at the eval "
            "boundary inside a timer phase, and pragma that one site with "
            "its rationale")

    def applies(self, relpath: str) -> bool:
        return relpath.endswith(HOT_PATH_FILES) or is_corpus_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in SYNC_CALLS:
                yield ctx.finding(
                    self, node,
                    f"{name} blocks the host on device compute in a "
                    "hot-path module")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "item" and not node.args):
                yield ctx.finding(
                    self, node,
                    ".item() forces a device->host transfer (and a full "
                    "queue drain) in a hot-path module")
            elif (name in ("np.asarray", "numpy.asarray", "np.array",
                           "numpy.array")
                  and ctx.in_nested_def(node.lineno)):
                yield ctx.finding(
                    self, node,
                    f"{name} inside a traced closure pulls a tracer to the "
                    "host (ConcretizationError at best, silent sync at "
                    "worst)")
