"""Rule registry: one module per project-specific rule.

Each rule carries an id (FT001..FT018), a docstring explaining the
hazard in THIS codebase's terms, and a fix hint. ``all_rules()`` is the
canonical ordered instantiation the engine and the CLI share.

Beyond the per-file AST rules live the engine/whole-program families
(listed in ``rule_table()`` so ``--list-rules`` and the README show the
complete surface):

- FT012 — unused-pragma detection (engine pass in ``analysis/lint.py``)
- FT016 — flag/env conformance (``analysis/flagsconf.py``)
- FT10x — jaxpr audit of registered hot entry points
  (``analysis/jaxpr_audit.py``)
- FT2xx — whole-program protocol conformance (``analysis/protocol.py``)
- FT30x — round-shape conformance over the ``algorithms/`` driver zoo
  (``analysis/roundshape.py``)

``CORPUS_RULE_IDS`` names every rule that must ship a
``tests/analysis_corpus/<id>_pos.py`` / ``_neg.py`` pair — the
corpus-completeness meta-test enforces it, so a future rule cannot land
untested. Trace-level (FT10x) and snapshot-level (FT200/FT204) checks
are exercised by planted in-process specs instead of corpus files.
"""

from __future__ import annotations

from typing import List

from fedml_tpu.analysis.lint import Rule
from fedml_tpu.analysis.rules.broad_except import BroadExceptRule
from fedml_tpu.analysis.rules.comm_timeouts import CommTimeoutRule
from fedml_tpu.analysis.rules.concurrency import (LockOrderRule,
                                                  SharedStateLockRule)
from fedml_tpu.analysis.rules.determinism import (FsEnumOrderRule,
                                                  SetIterationOrderRule,
                                                  WallClockControlFlowRule)
from fedml_tpu.analysis.rules.donation import DonatedReuseRule
from fedml_tpu.analysis.rules.float64 import Float64Rule
from fedml_tpu.analysis.rules.host_sync import HostSyncRule
from fedml_tpu.analysis.rules.jit_static import JitScalarArgRule
from fedml_tpu.analysis.rules.job_isolation import JobIsolationRule
from fedml_tpu.analysis.rules.metrics_names import MetricNameRule
from fedml_tpu.analysis.rules.population_growth import PopulationGrowthRule
from fedml_tpu.analysis.rules.rng import GlobalRngRule
from fedml_tpu.analysis.rules.server_state import ServerStateRule
from fedml_tpu.analysis.lifecycle import (BlockingUnderLockRule,
                                          LeakOnRaiseRule,
                                          ShutdownReachabilityRule,
                                          SubmitAfterCloseRule,
                                          ThreadLifecycleRule)

_RULES = (GlobalRngRule, DonatedReuseRule, HostSyncRule,
          JitScalarArgRule, BroadExceptRule, Float64Rule,
          CommTimeoutRule, PopulationGrowthRule, ServerStateRule,
          SharedStateLockRule, LockOrderRule,
          FsEnumOrderRule, SetIterationOrderRule,
          WallClockControlFlowRule, MetricNameRule, JobIsolationRule,
          ThreadLifecycleRule, LeakOnRaiseRule, BlockingUnderLockRule,
          ShutdownReachabilityRule, SubmitAfterCloseRule)

#: engine / whole-program / audit checks that are not per-file Rule
#: instances but are part of the rule surface
_EXTRA_RULE_ROWS = (
    {"id": "FT012",
     "title": "pragma that suppresses no finding (stale suppression)",
     "hint": "delete the pragma; warned by default, a finding under "
             "--strict-pragmas"},
    {"id": "FT100",
     "title": "jaxpr audit: entry point failed to build/trace",
     "hint": "an auditable entry must stay traceable on the CPU CI "
             "backend"},
    {"id": "FT101",
     "title": "jaxpr audit: float64 aval under x64-off intent",
     "hint": "pin the dtype to f32 or set allow_f64 on the AuditSpec"},
    {"id": "FT102",
     "title": "jaxpr audit: host callback inside a scan/while body",
     "hint": "hoist the callback out of the fused loop"},
    {"id": "FT103",
     "title": "jaxpr audit: float upcast on a grad-declared path",
     "hint": "make the accumulation dtype explicit at the cast site"},
    {"id": "FT104",
     "title": "jaxpr audit: lowering-key count exceeds the declared "
              "contract (recompile class)",
     "hint": "align caller arg dtypes/weak-types or mark variant args "
             "static"},
    {"id": "FT105",
     "title": "collective audit: new/removed/changed collective vs "
              "ci/collective_baseline.json (or missing baseline)",
     "hint": "review, then --write-collective-baseline"},
    {"id": "FT106",
     "title": "collective audit: bytes estimate drifted beyond "
              "tolerance",
     "hint": "review the sharding change, then "
             "--write-collective-baseline"},
    {"id": "FT200",
     "title": "protocol audit: ci/protocol_graph.json snapshot missing "
              "or unreadable",
     "hint": "--write-protocol-graph"},
    {"id": "FT201",
     "title": "protocol audit: message type sent but no handler "
              "registered",
     "hint": "register the peer-side handler or delete the send path"},
    {"id": "FT202",
     "title": "protocol audit: handler registered for a type nothing "
              "sends",
     "hint": "add the sender or remove the dead registration"},
    {"id": "FT203",
     "title": "protocol audit: handler requires a payload key no "
              "sender writes",
     "hint": "add the key at every send site or read it optionally"},
    {"id": "FT204",
     "title": "protocol audit: sender->handler graph drifted from the "
              "snapshot",
     "hint": "review the protocol change, then --write-protocol-graph"},
    {"id": "FT016",
     "title": "flag/env conformance: dead flag (defined, read nowhere), "
              "shared-arg-set flag missing from the README table, or "
              "undocumented $FEDML_TPU_* env read",
     "hint": "wire or delete the flag; document the knob in README.md"},
    {"id": "FT300",
     "title": "round-shape audit: ci/round_engine_map.json snapshot "
              "missing or unreadable",
     "hint": "--write-round-map"},
    {"id": "FT301",
     "title": "round-shape audit: driver re-implements a shared skeleton "
              "helper locally",
     "hint": "import the shared helper (core.sampling / core.pytree / "
             "data.base / trainer.functional) instead of forking it"},
    {"id": "FT302",
     "title": "round-shape audit: per-round sample+pack with no prefetch "
              "binding (skeleton wiring absent in this driver)",
     "hint": "route through FedAvgAPI._host_round_inputs or pragma with "
             "the structural rationale"},
    {"id": "FT303",
     "title": "round-shape audit: aggregation hook ignores the reported "
              "client weights",
     "hint": "weight by sample counts, or pragma a deliberately "
             "unweighted robust rule"},
    {"id": "FT304",
     "title": "round-shape audit: driver-local env read bypassing the "
              "shared arg set",
     "hint": "read config through the shared arg set / Config dataclass"},
    {"id": "FT305",
     "title": "round-shape audit: extracted map drifted from the "
              "snapshot",
     "hint": "review the round-shape change, then --write-round-map"},
    {"id": "FT025",
     "title": "lifecycle audit: ci/shutdown_graph.json snapshot missing "
              "or drifted from the extracted worker/resource graph",
     "hint": "review the worker/resource change, then "
             "--write-shutdown-graph"},
)

#: every rule id that must have a pos/neg corpus pair (meta-tested);
#: snapshot-level checks (FT200/FT204, FT300/FT305) are exercised by
#: planted in-process specs instead of corpus files
CORPUS_RULE_IDS = tuple(sorted(
    [cls.id for cls in _RULES]
    + ["FT012", "FT201", "FT202", "FT203",
       "FT016", "FT301", "FT302", "FT303", "FT304"]))


def all_rules() -> List[Rule]:
    return [cls() for cls in _RULES]


def rule_table() -> List[dict]:
    """id/title/hint rows for --list-rules and the README table — the
    full surface: AST rules, engine passes, jaxpr audit, protocol."""
    rows = [{"id": cls.id, "title": cls.title, "hint": cls.hint}
            for cls in _RULES]
    rows.extend(dict(r) for r in _EXTRA_RULE_ROWS)
    return sorted(rows, key=lambda r: r["id"])
