"""Rule registry: one module per project-specific rule.

Each rule carries an id (FT001..FT009), a docstring explaining the
hazard in THIS codebase's terms, and a fix hint. ``all_rules()`` is the
canonical ordered instantiation the engine and the CLI share.
"""

from __future__ import annotations

from typing import List

from fedml_tpu.analysis.lint import Rule
from fedml_tpu.analysis.rules.broad_except import BroadExceptRule
from fedml_tpu.analysis.rules.comm_timeouts import CommTimeoutRule
from fedml_tpu.analysis.rules.donation import DonatedReuseRule
from fedml_tpu.analysis.rules.float64 import Float64Rule
from fedml_tpu.analysis.rules.host_sync import HostSyncRule
from fedml_tpu.analysis.rules.jit_static import JitScalarArgRule
from fedml_tpu.analysis.rules.population_growth import PopulationGrowthRule
from fedml_tpu.analysis.rules.rng import GlobalRngRule
from fedml_tpu.analysis.rules.server_state import ServerStateRule

_RULES = (GlobalRngRule, DonatedReuseRule, HostSyncRule,
          JitScalarArgRule, BroadExceptRule, Float64Rule,
          CommTimeoutRule, PopulationGrowthRule, ServerStateRule)


def all_rules() -> List[Rule]:
    return [cls() for cls in _RULES]


def rule_table() -> List[dict]:
    """id/title/hint rows for --list-rules and the README table."""
    return [{"id": cls.id, "title": cls.title, "hint": cls.hint}
            for cls in _RULES]
