"""FT010/FT011 — thread/lock discipline for the actor runtime.

The two worst bugs of the PR 2–7 era were concurrency defects caught
*dynamically*, late: the global-RNG seed/draw race (prefetch worker vs
round loop) and the stale ``_STOP`` sentinel (receive loop vs restart).
This module freezes the class out statically, per CLASS, by inferring
the distinct **thread roots** the runtime actually spawns:

- the *receive root*: every handler registered via
  ``register_message_receive_handler`` plus ``run``/``receive_message``
  — the comm layer dispatches them all on ONE receive thread, so two
  handlers sharing state is NOT a race;
- one root per ``threading.Thread(target=...)`` / ``threading.Timer(...,
  cb)`` target (named methods and nested ``def`` closures both);
- the prefetch root: callables handed to ``RoundPrefetcher(produce,
  ..., next_key=...)`` run on its worker thread.

**FT010** — a ``self.<attr>`` reached from MORE THAN ONE root, written
by at least one of them, where the accesses share no common lexical
lock (``with self._lock`` / ``with self._cond`` / ``with _DEVICE_LOCK``
/ ``with locked_global_numpy_rng()`` — any ``with`` whose context
expression names a lock/cond/mutex). ``__init__`` writes are exempt
(construction happens before any thread starts), and so are methods
unreachable from every root (main-thread setup like ``send_init_msg``).

**FT011** — inverted lock-acquisition order: method A takes lock L1
then (lexically nested, or via a same-class call made while holding
L1) lock L2, while method B takes L2 then L1 — the classic AB/BA
deadlock, which no single-threaded test ever hits.

Scope: library code only (``tests/`` is single-threaded by
construction; corpus paths are linted as library code). Per-class,
per-file: state shared through a base class in another module is out
of reach — keep thread-shared state and its roots in one file, which
the comm/prefetch/control modules already do.

The per-class substrate (thread-root inference, lock-stack visitor,
call closure) lives in :mod:`fedml_tpu.analysis.classmodel` — it is
shared with the lifecycle pass (FT020–FT025), which reuses the same
root inference for its shutdown-reachability checks.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import FileContext, Rule, is_test_path

from fedml_tpu.analysis.classmodel import (_Access, _ClassModel,
                                           _lock_name)

__all__ = ["SharedStateLockRule", "LockOrderRule"]


class SharedStateLockRule(Rule):
    id = "FT010"
    title = ("cross-thread shared state written without a common lock "
             "(receive loop vs heartbeat/timer/prefetch roots)")
    hint = ("guard every access with one shared lock (with self._lock), "
            "move the write onto the owning thread (post a self-addressed "
            "message), or pragma a deliberately unguarded GIL-atomic "
            "flag: # ft: allow[FT010] why it is safe")

    def applies(self, relpath: str) -> bool:
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            model = _ClassModel(cls)
            if len(model.roots) < 2:
                continue
            # attr -> root -> accesses (from root-reachable funcs only)
            per_root: Dict[str, Dict[str, List[_Access]]] = {}
            for root, entries in model.roots.items():
                for qual in model.closure(entries):
                    for acc in model.funcs[qual].accesses:
                        per_root.setdefault(acc.attr, {}).setdefault(
                            root, []).append(acc)
            for attr in sorted(per_root):
                roots = per_root[attr]
                if len(roots) < 2:
                    continue
                all_acc = [a for accs in roots.values() for a in accs]
                if not any(a.is_write for a in all_acc):
                    continue  # read-shared immutable state is fine
                common = frozenset.intersection(
                    *[a.locks for a in all_acc])
                if common:
                    continue  # every access shares a lock
                seen_lines: Set[int] = set()
                for acc in sorted(all_acc, key=lambda a: a.line):
                    if not acc.is_write or acc.line in seen_lines:
                        continue
                    seen_lines.add(acc.line)
                    yield ctx.finding(
                        self, acc.node,
                        f"{cls.name}.{attr} is written here and reached "
                        f"from {len(roots)} thread roots "
                        f"({', '.join(sorted(roots))}) with no common "
                        "lock — the interleaving is invisible to "
                        "single-threaded tests and surfaces as a flaky "
                        "race (the PR-2 seed/draw class)")


class LockOrderRule(Rule):
    id = "FT011"
    title = "inverted lock-acquisition order across methods (AB/BA deadlock)"
    hint = ("pick one global order for the two locks and take them in "
            "that order everywhere (release-and-reacquire if needed); "
            "or collapse them into one lock")

    def applies(self, relpath: str) -> bool:
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # per CLASS: collect (held, taken) pairs per function — both
        # lexical nestings and one level of held-lock -> same-class
        # call. Per-class scoping keeps the comparison on locks that
        # can actually be the same object: two classes whose methods
        # both spell `self._io_lock` hold DIFFERENT locks, and a
        # module-wide pair dict would report a bogus AB/BA inversion
        # across them.
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            pairs: Dict[Tuple[str, str], Tuple[str, int]] = {}
            model = _ClassModel(cls)
            acquires: Dict[str, Set[str]] = {}
            for qual, fn in model.funcs.items():
                got: Set[str] = set()
                for node in ast.walk(fn.node):
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        for item in node.items:
                            ln = _lock_name(item.context_expr)
                            if ln:
                                got.add(ln)
                acquires[qual] = got
            for qual, fn in model.funcs.items():
                for held, taken, line in fn.acquire_pairs:
                    pairs.setdefault((held, taken),
                                     (f"{cls.name}.{qual}", line))
                for held, callee in fn.calls_under_lock:
                    res = model._resolve(qual, callee)
                    if res is None:
                        continue
                    for taken in acquires.get(res, ()):
                        if taken != held:
                            pairs.setdefault(
                                (held, taken),
                                (f"{cls.name}.{qual} -> {res}",
                                 fn.node.lineno))
            reported: Set[frozenset] = set()
            for (a, b), (where_ab, line_ab) in sorted(pairs.items()):
                if (b, a) not in pairs:
                    continue
                key = frozenset((a, b))
                if key in reported:
                    continue
                reported.add(key)
                where_ba, line_ba = pairs[(b, a)]
                snippet = (ctx.lines[line_ab - 1].strip()
                           if 0 < line_ab <= len(ctx.lines) else "")
                yield Finding(
                    rule=self.id, path=ctx.relpath, line=line_ab,
                    message=f"lock order inversion: {where_ab} (line "
                            f"{line_ab}) acquires {a} then {b}, but "
                            f"{where_ba} (line {line_ba}) acquires {b} "
                            f"then {a} — two threads interleaving these "
                            "paths deadlock and the federation hangs",
                    hint=self.hint, snippet=snippet)
