"""FT010/FT011 — thread/lock discipline for the actor runtime.

The two worst bugs of the PR 2–7 era were concurrency defects caught
*dynamically*, late: the global-RNG seed/draw race (prefetch worker vs
round loop) and the stale ``_STOP`` sentinel (receive loop vs restart).
This module freezes the class out statically, per CLASS, by inferring
the distinct **thread roots** the runtime actually spawns:

- the *receive root*: every handler registered via
  ``register_message_receive_handler`` plus ``run``/``receive_message``
  — the comm layer dispatches them all on ONE receive thread, so two
  handlers sharing state is NOT a race;
- one root per ``threading.Thread(target=...)`` / ``threading.Timer(...,
  cb)`` target (named methods and nested ``def`` closures both);
- the prefetch root: callables handed to ``RoundPrefetcher(produce,
  ..., next_key=...)`` run on its worker thread.

**FT010** — a ``self.<attr>`` reached from MORE THAN ONE root, written
by at least one of them, where the accesses share no common lexical
lock (``with self._lock`` / ``with self._cond`` / ``with _DEVICE_LOCK``
/ ``with locked_global_numpy_rng()`` — any ``with`` whose context
expression names a lock/cond/mutex). ``__init__`` writes are exempt
(construction happens before any thread starts), and so are methods
unreachable from every root (main-thread setup like ``send_init_msg``).

**FT011** — inverted lock-acquisition order: method A takes lock L1
then (lexically nested, or via a same-class call made while holding
L1) lock L2, while method B takes L2 then L1 — the classic AB/BA
deadlock, which no single-threaded test ever hits.

Scope: library code only (``tests/`` is single-threaded by
construction; corpus paths are linted as library code). Per-class,
per-file: state shared through a base class in another module is out
of reach — keep thread-shared state and its roots in one file, which
the comm/prefetch/control modules already do.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import (FileContext, Rule, dotted_name,
                                     is_test_path)

#: substrings that mark a ``with`` context expression as a mutual
#: exclusion (matched on the LAST dotted component, lowercased)
_LOCKISH = ("lock", "cond", "mutex", "rlock", "semaphore")
_LOCK_CTORS = frozenset({"locked_global_numpy_rng"})

#: method calls that mutate their receiver in place (kept narrow — a
#: false "write" flags thread-safe primitives like Event.set)
_MUTATORS = frozenset({"append", "appendleft", "extend", "insert",
                       "setdefault", "pop", "popitem", "clear",
                       "update", "remove", "discard"})

#: methods that belong to the receive root besides registered handlers
_RECEIVE_ROOT_EXTRAS = ("run", "receive_message")


def _lock_name(expr: ast.expr) -> Optional[str]:
    """Normalized lock identity of a with-item context expr, or None.
    ``self._lock`` and ``_lock`` normalize apart (different objects);
    a call ``locked_global_numpy_rng()`` normalizes to its callee."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    name = dotted_name(expr)
    if not name:
        return None
    last = name.split(".")[-1].lower()
    if any(tok in last for tok in _LOCKISH) or \
            name.split(".")[-1] in _LOCK_CTORS:
        return name
    return None


class _Access:
    __slots__ = ("attr", "line", "node", "is_write", "locks")

    def __init__(self, attr: str, line: int, node: ast.AST,
                 is_write: bool, locks: Tuple[str, ...]):
        self.attr = attr
        self.line = line
        self.node = node
        self.is_write = is_write
        self.locks = frozenset(locks)


class _Func:
    """One analyzable function body: a method or a nested def inside a
    method (``qual`` = "method" or "method.<nested>")."""

    def __init__(self, qual: str, node: ast.AST):
        self.qual = qual
        self.node = node
        self.accesses: List[_Access] = []
        self.calls: Set[str] = set()          # self.X() / local nested defs
        self.acquire_pairs: List[Tuple[str, str, int]] = []  # (held, taken)
        self.calls_under_lock: List[Tuple[str, str]] = []  # (lock, callee)


def _self_attr(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _FuncVisitor(ast.NodeVisitor):
    """Collect accesses / calls / lock orderings for ONE function body,
    tracking the lexical with-lock stack. Nested defs are NOT entered —
    they are separate _Func units."""

    def __init__(self, func: _Func):
        self.func = func
        self.lock_stack: List[str] = []
        self._root = func.node

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is self._root:
            self.generic_visit(node)
        # else: nested def — its own unit

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.generic_visit(node)  # lambdas stay part of the enclosing body

    def visit_With(self, node: ast.With) -> None:
        taken = [ln for item in node.items
                 if (ln := _lock_name(item.context_expr))]
        for ln in taken:
            for held in self.lock_stack:
                if held != ln:
                    self.func.acquire_pairs.append((held, ln, node.lineno))
        self.lock_stack.extend(taken)
        self.generic_visit(node)
        for _ in taken:
            self.lock_stack.pop()

    visit_AsyncWith = visit_With

    def _record(self, attr: Optional[str], node: ast.AST,
                is_write: bool) -> None:
        if attr:
            self.func.accesses.append(_Access(
                attr, getattr(node, "lineno", 0), node, is_write,
                tuple(self.lock_stack)))

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._record(_self_attr(tgt), node, True)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record(_self_attr(node.target), node, True)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATORS:
                self._record(_self_attr(node.func.value), node, True)
            callee = dotted_name(node.func)
            if callee and callee.startswith("self."):
                name = callee[len("self."):]
                if "." not in name:
                    self.func.calls.add(name)
                    for held in self.lock_stack:
                        self.func.calls_under_lock.append((held, name))
        elif isinstance(node.func, ast.Name):
            self.func.calls.add(node.func.id)  # maybe a nested def
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load):
            self._record(_self_attr(node), node, False)
        self.generic_visit(node)


def _callable_target(expr: ast.expr) -> Optional[str]:
    """``self.M`` -> "M"; bare ``fire`` -> "fire"; else None."""
    name = dotted_name(expr)
    if not name:
        return None
    if name.startswith("self.") and name.count(".") == 1:
        return name[len("self."):]
    if "." not in name:
        return name
    return None


class _ClassModel:
    def __init__(self, cls: ast.ClassDef):
        self.cls = cls
        self.funcs: Dict[str, _Func] = {}
        #: root label -> entry function quals
        self.roots: Dict[str, Set[str]] = {}
        self._collect()

    def _collect(self) -> None:
        for method in self.cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            self.funcs[method.name] = _Func(method.name, method)
            for child in ast.walk(method):
                if child is method:
                    continue
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    # nested defs (timer `fire`, thread `runner`) are
                    # their own units, qualified under the method
                    qual = f"{method.name}.{child.name}"
                    if qual not in self.funcs:
                        self.funcs[qual] = _Func(qual, child)
        for fn in list(self.funcs.values()):
            _FuncVisitor(fn).visit(fn.node)
        self._infer_roots()

    def _resolve(self, caller_qual: str, name: str) -> Optional[str]:
        """A name referenced inside ``caller_qual``: nested def first,
        then a plain method."""
        nested = f"{caller_qual.split('.')[0]}.{name}"
        if nested in self.funcs:
            return nested
        if name in self.funcs:
            return name
        return None

    def _infer_roots(self) -> None:
        receive: Set[str] = set()
        for qual, fn in self.funcs.items():
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted_name(node.func) or ""
                last = callee.split(".")[-1]
                if last == "register_message_receive_handler" \
                        and len(node.args) >= 2:
                    target = _callable_target(node.args[1])
                    if target:
                        res = self._resolve(qual, target)
                        if res:
                            receive.add(res)
                elif last == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = _callable_target(kw.value)
                            if target:
                                res = self._resolve(qual, target)
                                if res:
                                    self.roots.setdefault(
                                        f"thread:{target}", set()).add(res)
                elif last == "Timer" and len(node.args) >= 2:
                    target = _callable_target(node.args[1])
                    if target:
                        res = self._resolve(qual, target)
                        if res:
                            self.roots.setdefault(
                                f"timer:{target}", set()).add(res)
                elif last == "RoundPrefetcher":
                    producers = []
                    if node.args:
                        producers.append(_callable_target(node.args[0]))
                    for kw in node.keywords:
                        if kw.arg == "next_key":
                            producers.append(_callable_target(kw.value))
                    for target in producers:
                        if target:
                            res = self._resolve(qual, target)
                            if res:
                                self.roots.setdefault(
                                    "prefetch", set()).add(res)
        for extra in _RECEIVE_ROOT_EXTRAS:
            if extra in self.funcs:
                receive.add(extra)
        if receive:
            self.roots["receive"] = receive

    def closure(self, entries: Set[str]) -> Set[str]:
        """Entry quals expanded through same-class calls. ``__init__``
        itself is excluded (construction precedes every thread) — but a
        nested def INSIDE ``__init__`` handed to a Thread/Timer runs
        after start() and stays in."""
        seen: Set[str] = set()
        work = [q for q in entries if q in self.funcs]
        while work:
            qual = work.pop()
            if qual in seen or qual == "__init__":
                continue
            seen.add(qual)
            for name in self.funcs[qual].calls:
                res = self._resolve(qual, name)
                if res and res not in seen:
                    work.append(res)
        return seen


class SharedStateLockRule(Rule):
    id = "FT010"
    title = ("cross-thread shared state written without a common lock "
             "(receive loop vs heartbeat/timer/prefetch roots)")
    hint = ("guard every access with one shared lock (with self._lock), "
            "move the write onto the owning thread (post a self-addressed "
            "message), or pragma a deliberately unguarded GIL-atomic "
            "flag: # ft: allow[FT010] why it is safe")

    def applies(self, relpath: str) -> bool:
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            model = _ClassModel(cls)
            if len(model.roots) < 2:
                continue
            # attr -> root -> accesses (from root-reachable funcs only)
            per_root: Dict[str, Dict[str, List[_Access]]] = {}
            for root, entries in model.roots.items():
                for qual in model.closure(entries):
                    for acc in model.funcs[qual].accesses:
                        per_root.setdefault(acc.attr, {}).setdefault(
                            root, []).append(acc)
            for attr in sorted(per_root):
                roots = per_root[attr]
                if len(roots) < 2:
                    continue
                all_acc = [a for accs in roots.values() for a in accs]
                if not any(a.is_write for a in all_acc):
                    continue  # read-shared immutable state is fine
                common = frozenset.intersection(
                    *[a.locks for a in all_acc])
                if common:
                    continue  # every access shares a lock
                seen_lines: Set[int] = set()
                for acc in sorted(all_acc, key=lambda a: a.line):
                    if not acc.is_write or acc.line in seen_lines:
                        continue
                    seen_lines.add(acc.line)
                    yield ctx.finding(
                        self, acc.node,
                        f"{cls.name}.{attr} is written here and reached "
                        f"from {len(roots)} thread roots "
                        f"({', '.join(sorted(roots))}) with no common "
                        "lock — the interleaving is invisible to "
                        "single-threaded tests and surfaces as a flaky "
                        "race (the PR-2 seed/draw class)")


class LockOrderRule(Rule):
    id = "FT011"
    title = "inverted lock-acquisition order across methods (AB/BA deadlock)"
    hint = ("pick one global order for the two locks and take them in "
            "that order everywhere (release-and-reacquire if needed); "
            "or collapse them into one lock")

    def applies(self, relpath: str) -> bool:
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # per CLASS: collect (held, taken) pairs per function — both
        # lexical nestings and one level of held-lock -> same-class
        # call. Per-class scoping keeps the comparison on locks that
        # can actually be the same object: two classes whose methods
        # both spell `self._io_lock` hold DIFFERENT locks, and a
        # module-wide pair dict would report a bogus AB/BA inversion
        # across them.
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            pairs: Dict[Tuple[str, str], Tuple[str, int]] = {}
            model = _ClassModel(cls)
            acquires: Dict[str, Set[str]] = {}
            for qual, fn in model.funcs.items():
                got: Set[str] = set()
                for node in ast.walk(fn.node):
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        for item in node.items:
                            ln = _lock_name(item.context_expr)
                            if ln:
                                got.add(ln)
                acquires[qual] = got
            for qual, fn in model.funcs.items():
                for held, taken, line in fn.acquire_pairs:
                    pairs.setdefault((held, taken),
                                     (f"{cls.name}.{qual}", line))
                for held, callee in fn.calls_under_lock:
                    res = model._resolve(qual, callee)
                    if res is None:
                        continue
                    for taken in acquires.get(res, ()):
                        if taken != held:
                            pairs.setdefault(
                                (held, taken),
                                (f"{cls.name}.{qual} -> {res}",
                                 fn.node.lineno))
            reported: Set[frozenset] = set()
            for (a, b), (where_ab, line_ab) in sorted(pairs.items()):
                if (b, a) not in pairs:
                    continue
                key = frozenset((a, b))
                if key in reported:
                    continue
                reported.add(key)
                where_ba, line_ba = pairs[(b, a)]
                snippet = (ctx.lines[line_ab - 1].strip()
                           if 0 < line_ab <= len(ctx.lines) else "")
                yield Finding(
                    rule=self.id, path=ctx.relpath, line=line_ab,
                    message=f"lock order inversion: {where_ab} (line "
                            f"{line_ab}) acquires {a} then {b}, but "
                            f"{where_ba} (line {line_ba}) acquires {b} "
                            f"then {a} — two threads interleaving these "
                            "paths deadlock and the federation hangs",
                    hint=self.hint, snippet=snippet)
