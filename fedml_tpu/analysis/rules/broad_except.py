"""FT005 — broad ``except`` that swallows errors.

A ``except Exception: pass`` in a thread target is how the PR 2 RNG
race stayed invisible: the prefetch worker's failure surfaced rounds
later as a corrupt cohort instead of a stack trace. The federation's
actor threads (silo clients, the prefetch worker, the watchdog) must
either re-raise or leave a traceback in the log.

A broad handler (``except Exception`` / ``except BaseException`` /
bare ``except``) is compliant when it demonstrably propagates the
error, i.e. its body contains any of:

- a ``raise`` (re-raise or raise-from);
- ``logging.exception`` / ``logger.exception(...)`` or
  ``traceback.print_exc()`` / ``print_exception(...)``;
- any call carrying ``exc_info=...``;
- a *use* of the bound exception name (``except ... as exc`` where
  ``exc`` is read — stored for a later re-raise, recorded, or included
  in a log message).

Anything else needs an explicit ``# ft: allow[FT005]`` pragma with its
rationale (e.g. best-effort ``__del__`` shutdown paths).
"""

from __future__ import annotations

import ast
from typing import Iterator

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import FileContext, Rule, dotted_name

BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    if isinstance(t, (ast.Name, ast.Attribute)):
        name = dotted_name(t) or ""
        return name.split(".")[-1] in BROAD
    if isinstance(t, ast.Tuple):
        return any(
            (dotted_name(e) or "").split(".")[-1] in BROAD for e in t.elts)
    return False


def _propagates(handler: ast.ExceptHandler) -> bool:
    bound = handler.name
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if name.endswith((".exception", ".print_exc",
                              ".print_exception")):
                return True
            if any(kw.arg == "exc_info" for kw in node.keywords):
                return True
        if bound and isinstance(node, ast.Name) and node.id == bound \
                and isinstance(node.ctx, ast.Load):
            return True
    return False


class BroadExceptRule(Rule):
    id = "FT005"
    title = "broad except that swallows the error"
    hint = ("narrow the exception type, re-raise, log with exc_info=True, "
            "or pragma the intentional best-effort site: "
            "# ft: allow[FT005] <why>")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or _propagates(node):
                continue
            what = ("bare except" if node.type is None else
                    f"except {ast.unparse(node.type)}")
            yield ctx.finding(
                self, node,
                f"{what} neither re-raises, logs exc_info, nor uses the "
                "bound exception — in a thread/worker target the failure "
                "vanishes")
