"""FT009 — server round-state mutated in the message loop but missing
from the checkpoint field manifest.

The elastic control plane (``fedml_tpu/control/``) checkpoints the
cross-silo server's FULL round-schedule state so a killed-and-restarted
server resumes mid-schedule. The failure mode this rule freezes out is
the quiet one: a later PR adds ``self.some_new_counter`` to a server
handler, forgets to add it to ``_capture_control_state``, and every
failover silently resets that field — the resumed schedule diverges from
the unkilled run in a way no unit test of the new feature notices.

The contract lives in ``fedml_tpu/control/manifest.py``: every
``self.<attr>`` a server manager *mutates outside __init__* must be in
``SERVER_CHECKPOINT_FIELDS`` (captured + restored),
``SERVER_EPHEMERAL_FIELDS`` (documented restart-fresh), or carry a
``# ft: allow[FT009] why`` pragma. Detected mutations:

- ``self.X = ...`` / ``self.X += ...`` (plain + augmented assigns),
- ``self.X[...] = ...`` / ``self.X[...] += ...`` (subscript stores),
- ``self.X.append/add/update/extend/pop/...(...)`` (container mutators).

Scope: the cross-silo round-based server modules only
(``algorithms/fedavg_cross_silo.py`` + ``algorithms/fedavg_async.py``,
plus the analysis corpus), and within them only classes whose base list
names a ``*ServerManager``. Classes in
``UNCHECKPOINTED_SERVER_CLASSES`` (FedAsync — no round schedule exists
to resume) are exempt wholesale.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import (FileContext, Rule, dotted_name,
                                     is_corpus_path)
from fedml_tpu.control.manifest import (SERVER_CHECKPOINT_FIELDS,
                                        SERVER_EPHEMERAL_FIELDS,
                                        UNCHECKPOINTED_SERVER_CLASSES)

#: the cross-silo round-based server modules (path suffixes)
_SERVER_MODULES = ("algorithms/fedavg_cross_silo.py",
                   "algorithms/fedavg_async.py")

#: method calls that mutate their receiver in place
_MUTATORS = frozenset({"append", "appendleft", "add", "update", "extend",
                       "insert", "setdefault", "pop", "popitem", "clear",
                       "discard", "remove"})

_ALLOWED = SERVER_CHECKPOINT_FIELDS | SERVER_EPHEMERAL_FIELDS


def _self_attr(node: ast.expr) -> Optional[str]:
    """``self.X`` -> ``X`` (through one subscript level for
    ``self.X[...]`` targets); None otherwise."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _is_server_class(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        name = dotted_name(base) or ""
        if "ServerManager" in name.split(".")[-1]:
            return True
    return False


class ServerStateRule(Rule):
    id = "FT009"
    title = ("server round-state mutated in the message loop but absent "
             "from the checkpoint field manifest")
    hint = ("add the field to SERVER_CHECKPOINT_FIELDS (and capture + "
            "restore it in _capture_control_state/_restore_control_state) "
            "or to SERVER_EPHEMERAL_FIELDS with a restart-fresh "
            "rationale (fedml_tpu/control/manifest.py); pragma "
            "deliberate exceptions: # ft: allow[FT009] why")

    def applies(self, relpath: str) -> bool:
        rel = relpath.replace("\\", "/")
        return (any(rel.endswith(m) for m in _SERVER_MODULES)
                or is_corpus_path(relpath))

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef) \
                    or not _is_server_class(cls):
                continue
            if cls.name in UNCHECKPOINTED_SERVER_CLASSES:
                continue
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name == "__init__":
                    # construction-time defaults are not "forgotten":
                    # a field only matters once the round loop mutates it
                    continue
                yield from self._check_method(ctx, cls, method)

    def _check_method(self, ctx: FileContext, cls: ast.ClassDef,
                      method: ast.AST) -> Iterator[Finding]:
        for node in ast.walk(method):
            attr = None
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr:
                        break
            elif isinstance(node, ast.AugAssign):
                attr = _self_attr(node.target)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                attr = _self_attr(node.func.value)
            if attr and attr not in _ALLOWED:
                yield ctx.finding(
                    self, node,
                    f"{cls.name}.{attr} is mutated in the server's "
                    f"message/round loop but is in neither "
                    f"SERVER_CHECKPOINT_FIELDS nor "
                    f"SERVER_EPHEMERAL_FIELDS — a restarted server "
                    f"silently resets it and the resumed schedule "
                    f"diverges from the unkilled run")
