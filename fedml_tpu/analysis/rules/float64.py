"""FT006 — float64 literals/dtypes outside the modules that mean it.

The device stack runs x64-off: a ``float64`` dtype reaching jnp is
silently truncated to f32 (masking an intent bug), and with x64
enabled it doubles HBM traffic and halves MXU throughput. A handful of
host-side modules use f64 deliberately and are allowlisted:

- ``contribution/shap.py`` — KernelSHAP's least-squares solve;
- ``core/mpc.py`` — the fixed-point secret-share codec;
- ``algorithms/turboaggregate.py`` — secure-sum fixed-point staging;
- ``comm/grpc_proto.py`` — the reference wire format's f64->f32 rule;
- ``data/`` — host-side dataset generation/statistics (never shipped
  to the device; loaders cast to f32 at pack time);
- ``analysis/`` — this subsystem's own detector pattern strings.

New intentional sites: extend the allowlist (with a rationale in this
docstring) for a whole module, or pragma a single line, or baseline it
with a note — in that order of preference.
"""

from __future__ import annotations

import ast
from typing import Iterator

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import (FileContext, Rule, dotted_name,
                                     is_test_path)

ALLOWED_FILES = (
    "fedml_tpu/contribution/shap.py",
    "fedml_tpu/core/mpc.py",
    "fedml_tpu/algorithms/turboaggregate.py",
    "fedml_tpu/comm/grpc_proto.py",
)
ALLOWED_DIRS = ("fedml_tpu/data/", "fedml_tpu/analysis/")


class Float64Rule(Rule):
    id = "FT006"
    title = "float64 dtype outside the intentional-f64 modules"
    hint = ("use f32 (the device dtype) or jnp.asarray(..., jnp.float32); "
            "if the f64 is intentional host math, allowlist the module in "
            "analysis/rules/float64.py or pragma the line")

    def applies(self, relpath: str) -> bool:
        if is_test_path(relpath):
            return False  # tests compute f64 references on purpose
        if relpath.endswith(ALLOWED_FILES):
            return False
        return not any(d in relpath for d in ALLOWED_DIRS)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                base = dotted_name(node.value)
                if base in ("np", "numpy", "jnp", "jax.numpy"):
                    yield ctx.finding(
                        self, node,
                        f"{base}.float64 in a module outside the "
                        "intentional-f64 allowlist (x64-off truncates it "
                        "silently; x64-on doubles bandwidth)")
            elif isinstance(node, ast.Constant) and node.value == "float64":
                yield ctx.finding(
                    self, node,
                    "'float64' dtype string outside the intentional-f64 "
                    "allowlist")
