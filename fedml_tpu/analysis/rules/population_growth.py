"""FT008 — unbounded per-client accumulation in algorithm round loops.

The bug class the population-virtualization work (fedml_tpu/state/)
exists to retire: a driver that does

    for round ...:
        self.residuals[client_id] = ...        # grows with population
    for c in cohort:
        self.per_client_log.append(...)        # grows with rounds*cohort

holds O(population) (or O(rounds)) host memory in a resident Python
dict/list — exactly what made 10^6-client federations unreachable before
the tiered client-state store. At million-client scale every per-client
artifact must either live behind the store's LRU/disk tiers or carry an
eviction path.

Findings:

1. **client-keyed subscript growth in a loop** — ``X[<client-ish>] =``
   inside any ``for``/``while`` body, where no eviction for ``X``
   (``del X[...]`` / ``X.pop`` / ``X.popitem`` / ``X.clear``) appears in
   the file and ``X`` is not store-backed (its dotted name mentions
   ``store``/``cache``/``lru`` — those implement the bounded tier).
2. **append inside a client loop** — ``X.append(...)`` lexically inside
   a ``for`` whose target is client-ish (``client_idx``, ``cid``,
   ``silo``, ``rank``, ``c``, ...), same eviction/store suppressions.

Scope: ``fedml_tpu/algorithms/`` only (plus the analysis corpus) — that
is where round loops live; data/ builders construct bounded federations
by design and core/ is shared substrate. Intentional resident
structures (e.g. cross-silo state that scales with SILO count, which is
tens, not millions) carry ``# ft: allow[FT008] why`` pragmas.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Set, Tuple

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import (FileContext, Rule, dotted_name,
                                     is_corpus_path)

#: identifiers that mean "a client/participant id" in this codebase
_CLIENTISH_RE = re.compile(
    r"(?:^|_)(?:client|clients|cid|cids|silo|silos|sender|rank|worker)"
    r"(?:_|$|\d)|^c$")

#: container names that ARE the bounded tier (or delegate to it)
_BOUNDED_RE = re.compile(r"store|cache|lru", re.IGNORECASE)

_EVICT_METHODS = frozenset({"pop", "popitem", "clear"})


def _is_clientish(name: str) -> bool:
    return bool(_CLIENTISH_RE.search(name))


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class PopulationGrowthRule(Rule):
    id = "FT008"
    title = "unbounded per-client dict/list growth in an algorithm loop"
    hint = ("route per-client state through fedml_tpu.state's "
            "ClientStateStore (LRU + disk shards), or evict "
            "(del/pop/clear) what the round no longer needs; pragma "
            "structures bounded by silo count: # ft: allow[FT008] <why>")

    def applies(self, relpath: str) -> bool:
        return "/algorithms/" in f"/{relpath}" or is_corpus_path(relpath)

    # -- suppression substrate --------------------------------------------
    def _evicted_containers(self, ctx: FileContext) -> Set[str]:
        """Dotted container names the file evicts from ANYWHERE — a
        container with any eviction path is bounded by its author's
        policy, not this rule's business (coarse on purpose: the rule
        flags structures with NO shrink path at all)."""
        out: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Delete):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript):
                        name = dotted_name(tgt.value)
                        if name:
                            out.add(name)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in _EVICT_METHODS):
                name = dotted_name(node.func.value)
                if name:
                    out.add(name)
        return out

    def _loop_spans(self, ctx: FileContext,
                    clientish_only: bool) -> List[Tuple[int, int]]:
        spans = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.While) and not clientish_only:
                spans.append((node.lineno, node.end_lineno or node.lineno))
            elif isinstance(node, ast.For):
                if clientish_only and not any(
                        _is_clientish(n) for n in _names_in(node.target)):
                    continue
                spans.append((node.lineno, node.end_lineno or node.lineno))
        return spans

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        evicted = self._evicted_containers(ctx)
        loops = self._loop_spans(ctx, clientish_only=False)
        client_loops = self._loop_spans(ctx, clientish_only=True)

        def bounded(container: str) -> bool:
            return (container in evicted
                    or bool(_BOUNDED_RE.search(container)))

        def in_spans(line: int, spans) -> bool:
            return any(a < line <= b for a, b in spans)

        for node in ast.walk(ctx.tree):
            # 1) X[<client-ish>] = ... inside any loop body
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Subscript):
                        continue
                    container = dotted_name(tgt.value)
                    if not container or bounded(container):
                        continue
                    if not in_spans(node.lineno, loops):
                        continue
                    if any(_is_clientish(n)
                           for n in _names_in(tgt.slice)):
                        yield ctx.finding(
                            self, node,
                            f"{container}[<client id>] grows inside a "
                            "loop with no eviction path in this file — "
                            "O(population) resident host memory; use "
                            "the client-state store or del/pop what "
                            "the round no longer needs")
            # 2) X.append(...) inside a loop over clients
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "append"):
                container = dotted_name(node.func.value)
                if not container or bounded(container):
                    continue
                if not in_spans(node.lineno, client_loops):
                    continue
                yield ctx.finding(
                    self, node,
                    f"{container}.append inside a per-client loop with "
                    "no eviction path in this file — accumulates one "
                    "entry per sampled client forever; bound it, evict "
                    "it, or back it with the client-state store")
