"""FT004 — Python scalars at jit call sites without static_argnums.

The r5 bench artifact (577.8 tok/s) was a recompile landing inside a
timed region because two call sites fed the same jitted program
different *signatures* for the same logical argument. Python scalars
are the usual culprit: ``f(x, r)`` traces ``r`` as a weak-typed scalar,
while the other caller's ``f(x, jnp.uint32(r))`` traces a strong-typed
one — two cache entries, and the second compile lands wherever the
second caller runs (a bench window, a receive thread). Booleans are
worse: they are almost always branch selectors that belong in
``static_argnums``.

The rule flags, at call sites of module-local jitted callables
(``x = jax.jit(...)`` / ``self.y = jax.jit(...)`` / ``@jax.jit`` defs):

- Python int/float/bool literals at non-static positions or keywords;
- a ``for``-loop variable over ``range(...)`` passed positionally (the
  host round loop's ``f(vars, r)`` spelling — the tree's sanctioned
  form is ``f(vars, jnp.uint32(r))``).

Positions past a ``*args`` splat are unresolvable and skipped.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Set

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import FileContext, JitBinding, Rule, dotted_name


class JitScalarArgRule(Rule):
    id = "FT004"
    title = "Python scalar / shape-varying arg at a jit call site"
    hint = ("pass a typed device scalar (jnp.uint32(r) / jnp.asarray(v, "
            "dtype)) so every caller shares one signature, or add the "
            "position to static_argnums if it selects a program variant")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.jit_bindings:
            return
        yield from self._walk(ctx, ctx.tree, {})

    def _walk(self, ctx: FileContext, node: ast.AST,
              range_vars: Dict[str, int]) -> Iterator[Finding]:
        """DFS carrying the set of in-scope ``for x in range(...)`` vars."""
        for child in ast.iter_child_nodes(node):
            local = range_vars
            if isinstance(child, ast.For) and isinstance(child.iter, ast.Call):
                if dotted_name(child.iter.func) == "range" and isinstance(
                        child.target, ast.Name):
                    local = dict(range_vars)
                    local[child.target.id] = child.lineno
            if isinstance(child, ast.Call):
                yield from self._check_call(ctx, child, range_vars)
            yield from self._walk(ctx, child, local)

    def _check_call(self, ctx: FileContext, call: ast.Call,
                    range_vars: Dict[str, int]) -> Iterator[Finding]:
        callee = dotted_name(call.func)
        binding = ctx.jit_bindings.get(callee or "")
        if binding is None:
            return
        for pos, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break  # positions past a splat are unknown
            if pos in binding.static_nums:
                continue
            yield from self._check_arg(ctx, callee, arg, f"position {pos}",
                                       range_vars)
        for kw in call.keywords:
            if kw.arg is None or kw.arg in binding.static_names:
                continue
            yield from self._check_arg(ctx, callee, kw.value,
                                       f"keyword {kw.arg!r}", range_vars)

    def _check_arg(self, ctx: FileContext, callee: str, arg: ast.expr,
                   where: str, range_vars: Dict[str, int]
                   ) -> Iterator[Finding]:
        if isinstance(arg, ast.Constant) and isinstance(
                arg.value, (bool, int, float)):
            kind = type(arg.value).__name__
            yield ctx.finding(
                self, arg,
                f"Python {kind} literal {arg.value!r} at {where} of jitted "
                f"`{callee}` traces a weak-typed signature any other caller "
                "can miss (recompile)")
        elif isinstance(arg, ast.Name) and arg.id in range_vars:
            yield ctx.finding(
                self, arg,
                f"range() loop variable `{arg.id}` passed to jitted "
                f"`{callee}` at {where} as a Python int — a second caller "
                "passing a device scalar forks the jit cache")
