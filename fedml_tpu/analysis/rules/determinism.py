"""FT013/FT014/FT015 — run-order determinism lints.

Every acceptance gate in this repo is a bit-exact parity test (fused vs
host-loop trajectories, failover resume vs unkilled reference,
compression `none` vs raw), yet nothing statically forbade the three
classic parity-killers Bonawitz et al. (*Towards Federated Learning at
Scale*) name as the dominant source of irreproducible federated
schedules:

- **FT013** — unsorted filesystem enumeration (``os.listdir`` /
  ``os.scandir`` / ``glob.glob`` / ``Path.iterdir`` / ``.glob`` /
  ``.rglob``) whose order leaks into whatever consumes it. The
  client-state store, checkpoint GC, and failover restore all enumerate
  directories; an unsorted listing makes shard selection, GC deletion
  order, and restore choice depend on the filesystem — ext4 and tmpfs
  disagree, and so do two runs on one machine. Wrapping the call in
  ``sorted(...)`` fixes it; wrapping in ``set(...)``/``frozenset(...)``
  (explicit order erasure: membership semantics) is also accepted —
  iterating that set for order-sensitive work is then FT014's domain.
- **FT014** — iteration over a ``set`` feeding order-sensitive work
  (numeric accumulation, list building, message emission). Python set
  order depends on hash seeding and insertion history: a float sum, a
  send sequence, or a cohort list built from raw set iteration differs
  run to run (floating-point addition does not commute bitwise).
  ``sorted(the_set)`` restores a stable order.
- **FT015** — ``time.time()``/``time.monotonic()`` reaching a
  CONTROL-FLOW decision (a comparison, directly or through a local
  variable). Wall clock in telemetry is fine (``wall_s`` records);
  wall clock deciding *what the schedule does next* makes the run
  unreproducible. The sanctioned real-time sites — liveness/deadline
  eviction, watchdog stalls, chaos-harness windows, retry backoff —
  carry a pragma with the rationale; everything else is a bug.

Scope: library code only (tests are single-run by construction; corpus
paths are linted as library code, like every rule).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import (FileContext, Rule, dotted_name,
                                     is_test_path)

#: os/glob module functions whose result order is filesystem-dependent
_FS_ENUM_FUNCS = frozenset({
    "os.listdir", "listdir", "os.scandir", "scandir",
    "glob.glob", "glob.iglob",
})
#: method names whose receiver is (duck-typed) a Path — same hazard
_FS_ENUM_METHODS = frozenset({"iterdir", "glob", "rglob"})
#: wrappers that neutralize enumeration order: sorted() imposes one,
#: set()/frozenset() erase it explicitly (membership semantics)
_ORDER_SAFE_WRAPPERS = frozenset({"sorted", "set", "frozenset"})

#: receiver modules of wall-clock reads (``import time as _time`` idiom)
_CLOCK_MODULES = frozenset({"time", "_time"})
_CLOCK_ATTRS = frozenset({"time", "monotonic", "perf_counter"})

#: in-place growth calls that make a loop body order-sensitive
_ORDER_SENSITIVE_CALLS = frozenset({
    "append", "appendleft", "extend", "send", "send_message", "put",
    "write", "writelines", "add_local_trained_result",
})


def _parent_map(tree: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _scope_walk(root: ast.AST):
    """ast.walk that does NOT descend into nested function defs — each
    def is its own scope (a nested def's clock locals / set names must
    not taint the enclosing function's analysis, and vice versa).
    Lambdas stay in the enclosing scope (they hold no statements)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)


def _is_fs_enum_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    if name and (name in _FS_ENUM_FUNCS):
        return True
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in _FS_ENUM_METHODS:
        # x.glob("*") / p.iterdir() — but NOT glob.glob (handled above;
        # a bare module attr would double-report)
        recv = dotted_name(node.func.value)
        return recv != "glob"
    return False


def _safely_wrapped(node: ast.AST, parents: Dict[int, ast.AST]) -> bool:
    """The enumeration is an argument (any depth within the expression)
    of a ``sorted``/``set``/``frozenset`` call."""
    cur = node
    while True:
        parent = parents.get(id(cur))
        if parent is None or isinstance(parent, ast.stmt):
            return False
        if isinstance(parent, ast.Call):
            name = dotted_name(parent.func)
            if name in _ORDER_SAFE_WRAPPERS:
                return True
        cur = parent


class FsEnumOrderRule(Rule):
    id = "FT013"
    title = ("unsorted filesystem enumeration (os.listdir/glob/iterdir) — "
             "shard/checkpoint selection order becomes "
             "filesystem-dependent")
    hint = ("wrap the enumeration in sorted(...) (or set(...) when only "
            "membership matters), or pragma a genuinely order-insensitive "
            "site: # ft: allow[FT013] why order cannot matter")

    def applies(self, relpath: str) -> bool:
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # textual pre-gate: parent-map construction is the expensive
        # part and almost no file enumerates the filesystem
        if not any(tok in ctx.source for tok in
                   ("listdir", "scandir", "glob", "iterdir")):
            return
        parents = _parent_map(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and _is_fs_enum_call(node)):
                continue
            if _safely_wrapped(node, parents):
                continue
            what = dotted_name(node.func) or (
                f".{node.func.attr}" if isinstance(node.func, ast.Attribute)
                else "<enum>")
            yield ctx.finding(
                self, node,
                f"{what}(...) result is consumed in filesystem order — "
                "two hosts (or two runs) enumerate differently, so "
                "checkpoint GC, shard selection, and restore choice "
                "diverge where every gate expects bit-exact parity")


def _is_set_expr(node: ast.expr) -> bool:
    """An expression that is literally a set at this site."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name in ("set", "frozenset"):
            return True
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
                "intersection", "union", "difference",
                "symmetric_difference"):
            # set-algebra results are sets when the receiver is — only
            # treat known set receivers as evidence (handled by caller
            # through the assignment tables); a bare method call alone
            # is too ambiguous to flag
            return False
    return False


def _collect_set_names(fn: ast.AST) -> Set[str]:
    """Local names assigned a set-typed value in ``fn``'s own scope
    (nested defs excluded — their locals are separate scopes)."""
    names: Set[str] = set()
    for node in _scope_walk(fn):
        if isinstance(node, ast.Assign) and _is_set_expr(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
    return names


def _collect_set_self_attrs(cls: ast.ClassDef) -> Set[str]:
    """``self.<attr>`` names assigned a set-typed value in any method."""
    attrs: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_set_expr(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    attrs.add(tgt.attr)
    return attrs


def _body_is_order_sensitive(loop: ast.For) -> bool:
    """Numeric accumulation, ordered-container growth, or message
    emission inside the loop body."""
    for node in ast.walk(loop):
        if node is loop:
            continue
        if isinstance(node, ast.AugAssign):
            return True
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and name.split(".")[-1] in _ORDER_SENSITIVE_CALLS:
                return True
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            return True
    return False


class SetIterationOrderRule(Rule):
    id = "FT014"
    title = ("iteration over a set feeding numeric accumulation / "
             "message emission / cohort construction (run-order "
             "nondeterminism)")
    hint = ("iterate sorted(the_set) — float accumulation and send order "
            "must not depend on hash-seed iteration order; or pragma an "
            "order-insensitive body: # ft: allow[FT014] why")

    def applies(self, relpath: str) -> bool:
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # textual pre-gate: set() / frozenset() calls, set
        # comprehensions, or multi-element set literals. A missed
        # single-element literal {x} cannot misorder anything.
        import re as _re
        if "set(" not in ctx.source and not _re.search(
                r"\{[^\n{}:]+\bfor\b|\{[^\n{}:]+,", ctx.source):
            return
        # class-level set-typed self attrs, per class
        self_attrs_by_cls: List[Tuple[ast.ClassDef, Set[str]]] = [
            (cls, _collect_set_self_attrs(cls))
            for cls in ast.walk(ctx.tree) if isinstance(cls, ast.ClassDef)]

        def in_class_with_attr(loop: ast.For, attr: str) -> bool:
            for cls, attrs in self_attrs_by_cls:
                if attr in attrs:
                    for node in ast.walk(cls):
                        if node is loop:
                            return True
            return False

        def check_loop(node: ast.For, local_sets: Set[str]) -> bool:
            it = node.iter
            set_like = _is_set_expr(it)
            if not set_like and isinstance(it, ast.Name):
                set_like = it.id in local_sets
            if not set_like and isinstance(it, ast.Attribute) \
                    and isinstance(it.value, ast.Name) \
                    and it.value.id == "self":
                set_like = in_class_with_attr(node, it.attr)
            return set_like and _body_is_order_sensitive(node)

        message = ("loop iterates a set and its body accumulates / "
                   "emits in iteration order — set order depends on "
                   "hash seeding and insertion history, so sums, send "
                   "sequences, and cohort lists differ run to run "
                   "(float addition does not commute bitwise)")
        funcs = [n for n in ast.walk(ctx.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        seen_lines: Set[int] = set()
        # every def (incl. nested ones) and the module body is its own
        # scope: a nested def's loops are checked only against ITS set
        # names, never the enclosing function's
        for scope in funcs + [ctx.tree]:
            local_sets = (_collect_set_names(scope)
                          if scope is not ctx.tree else
                          {t.id for n in _scope_walk(ctx.tree)
                           if isinstance(n, ast.Assign)
                           and _is_set_expr(n.value)
                           for t in n.targets if isinstance(t, ast.Name)})
            for node in _scope_walk(scope):
                if isinstance(node, ast.For) \
                        and node.lineno not in seen_lines \
                        and check_loop(node, local_sets):
                    seen_lines.add(node.lineno)
                    yield ctx.finding(self, node, message)


def _is_clock_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    if not name:
        return False
    parts = name.split(".")
    if len(parts) == 2:
        return parts[0] in _CLOCK_MODULES and parts[1] in _CLOCK_ATTRS
    # ``from time import monotonic`` — the bare spellings that are
    # unambiguous (a bare ``time()`` call could be anything and stays
    # out of scope, like FT001's aliasing limitation)
    return len(parts) == 1 and parts[0] in ("monotonic", "perf_counter")


def _contains_clock_call(node: ast.AST) -> bool:
    return any(_is_clock_call(n) for n in ast.walk(node))


class WallClockControlFlowRule(Rule):
    id = "FT015"
    title = ("wall-clock read (time.time/monotonic) deciding control "
             "flow — the schedule becomes unreproducible")
    hint = ("derive the decision from round indices / seeded state, or "
            "pragma a sanctioned real-time site (liveness deadline, "
            "watchdog, chaos window, retry backoff): "
            "# ft: allow[FT015] why real time is the contract here")

    def applies(self, relpath: str) -> bool:
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # textual pre-gate: only files that read a wall clock at all
        if not any(tok in ctx.source for tok in
                   ("time(", ".monotonic(", "monotonic()",
                    "perf_counter")):
            return
        funcs = [n for n in ast.walk(ctx.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        reported: Set[int] = set()
        for fn in funcs + [ctx.tree]:
            # names assigned from expressions containing a clock read
            # (``deadline = time.monotonic() + t``) — STRICTLY
            # scope-local: _scope_walk stops at nested defs, which get
            # their own pass (funcs lists every def, nested included),
            # so one function's clock local cannot taint another's
            # comparisons
            clockish: Set[str] = set()
            for node in _scope_walk(fn):
                if isinstance(node, ast.Assign) \
                        and _contains_clock_call(node.value):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            clockish.add(tgt.id)
            for node in _scope_walk(fn):
                if not isinstance(node, ast.Compare):
                    continue
                if node.lineno in reported:
                    continue
                hit = _contains_clock_call(node)
                if not hit and clockish:
                    hit = any(isinstance(n, ast.Name)
                              and isinstance(n.ctx, ast.Load)
                              and n.id in clockish
                              for n in ast.walk(node))
                if not hit:
                    continue
                reported.add(node.lineno)
                yield ctx.finding(
                    self, node,
                    "comparison on a wall-clock read controls what "
                    "happens next — two runs of the same seed take "
                    "different branches, so the schedule (and every "
                    "bit-exact parity gate downstream) is "
                    "unreproducible unless this site is a sanctioned "
                    "real-time contract (pragma it with the rationale)")
