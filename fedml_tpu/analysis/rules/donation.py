"""FT002 — reuse of a buffer after passing it to a donating jit.

Every fused driver here donates its dead global-model buffer
(``jax.jit(round_fn, donate_argnums=(0,))``) so XLA reuses the HBM for
the new model instead of holding both live. Donation makes the argument
buffer INVALID after the call: reading it again raises on TPU
(``Invalid buffer passed``) or, worse on some backends, silently reads
reused memory. The sanctioned pattern is the same-statement overwrite::

    self.variables, stats = self._round_fn(self.variables, ...)

The rule tracks, per module, names bound to ``jax.jit(...,
donate_argnums=...)`` (including ``self.attr`` bindings), then walks
each function linearly: an argument passed at a donated position that is
*read again* before being *reassigned* is flagged. Assignment targets of
the calling statement count as reassigned (the pattern above is safe).

Known limits (by design, to stay quiet rather than guess): donation
metadata is not propagated across function returns (``make_spmd_round``
callers), ``*args`` splats hide positions, and control flow is
approximated by statement order.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import FileContext, Rule, dotted_name


def _assigned_names(stmt: ast.stmt) -> Set[str]:
    """Dotted names stored by this statement (assign/augassign/for/with)."""
    out: Set[str] = set()

    def add_target(tgt: ast.expr) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                add_target(e)
        else:
            name = dotted_name(tgt)
            if name:
                out.add(name)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            add_target(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        add_target(stmt.target)
    elif isinstance(stmt, ast.For):
        add_target(stmt.target)
    elif isinstance(stmt, ast.With):
        for item in stmt.items:
            if item.optional_vars is not None:
                add_target(item.optional_vars)
    return out


def _loads_in(node: ast.AST, name: str) -> Optional[ast.AST]:
    """First Load of dotted ``name`` inside ``node``, or None."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Name, ast.Attribute)) and isinstance(
                getattr(sub, "ctx", None), ast.Load):
            if dotted_name(sub) == name:
                return sub
    return None


def _flat_statements(body: List[ast.stmt]) -> List[ast.stmt]:
    """Statements in source order, flattened through compound statements
    (linear over-approximation of control flow)."""
    out: List[ast.stmt] = []
    for stmt in body:
        out.append(stmt)
        for field in ("body", "orelse", "finalbody"):
            out.extend(_flat_statements(getattr(stmt, field, []) or []))
        for handler in getattr(stmt, "handlers", []) or []:
            out.extend(_flat_statements(handler.body))
    return out


class DonatedReuseRule(Rule):
    id = "FT002"
    title = "variable reused after donation to a jit(donate_argnums=...) call"
    hint = ("rebind the result over the donated input in the same statement "
            "(x = f(x, ...)), or drop donate_argnums for buffers that must "
            "stay live")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        donors = {name: b for name, b in ctx.jit_bindings.items() if b.donate}
        if not donors:
            return
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._check_function(ctx, func, donors)

    def _donated_args(self, call: ast.Call, donate: Set[int]) -> List[str]:
        names: List[str] = []
        for pos, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break  # positions past a splat are unresolvable
            if pos in donate:
                name = dotted_name(arg)
                if name:
                    names.append(name)
        return names

    def _check_function(self, ctx: FileContext, func, donors
                        ) -> Iterator[Finding]:
        stmts = [s for s in _flat_statements(func.body)
                 if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                       ast.ClassDef))]
        # (donated name, call lineno, statement index) worklist
        pending: List[Tuple[str, int, int]] = []
        for i, stmt in enumerate(stmts):
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    callee = dotted_name(node.func)
                    if callee in donors:
                        for name in self._donated_args(node,
                                                       donors[callee].donate):
                            pending.append((name, node.lineno, i))
        for name, call_line, start in pending:
            # the calling statement's own targets re-bind the name
            if name in _assigned_names(stmts[start]):
                continue
            for stmt in stmts[start + 1:]:
                load = _loads_in(stmt, name)
                stores = _assigned_names(stmt)
                if load is not None and name not in stores:
                    yield ctx.finding(
                        self, load,
                        f"`{name}` was donated to a jit call at line "
                        f"{call_line} (donate_argnums) and is read again — "
                        "the buffer is invalid after donation")
                    break
                if name in stores:
                    break
