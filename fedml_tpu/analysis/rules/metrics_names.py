"""FT017 — metric-name conformance against the documented registry.

``RoundTimer``'s phase/counter/gauge maps are ``defaultdict``s, so a
typo'd name at a ``timer.count("ft_retrys")`` call site silently creates
a NEW key: the intended series stops moving, every evidence row still
looks healthy, and nothing fails. The documented metric registry
(``fedml_tpu/obs/registry.py``) is the single source of truth; this rule
closes the loop in both directions — the same conformance pattern FT016
applies to launcher flags:

- a ``timer.count/add/gauge/phase`` call whose FIRST argument is a
  string literal (conditional ``a if c else b`` literals included) not
  registered in ``METRICS`` is a finding at the call site;
- inside the registry module itself, a registered metric name that does
  not appear (backticked) in the repo ``README.md`` is a finding — the
  registry doubles as the machine-checked README metrics table.

Receiver scoping: only calls whose receiver *names a RoundTimer by this
codebase's conventions* (``timer`` / ``self.timer`` / ``round_timer`` /
``tmr`` / ``tm`` / ``self._timer``-style tails) are checked — a
``set.add("x")`` or ``threading.Timer`` call never matches the
method+literal+receiver triple. Non-literal names (f-strings, loop
variables) are out of scope, like every AST rule's aliasing limit.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator, List, Optional

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import FileContext, Rule, dotted_name

#: receiver tails that name a RoundTimer in this tree's idiom
_TIMER_NAMES = frozenset({"timer", "_timer", "round_timer",
                          "_round_timer", "tmr", "tm"})
_METHODS = frozenset({"count", "add", "gauge", "phase"})

#: the registry module's repo-relative path (the README-table check
#: anchors here)
_REGISTRY_RELPATH = "fedml_tpu/obs/registry.py"

#: registry-path -> (mtime, names) — one parse per registry per run
_REGISTRY_CACHE: dict = {}


def _metric_keys_from_tree(tree: ast.AST) -> Optional[frozenset]:
    """The METRICS dict's literal string keys out of a registry module's
    AST — the oracle stays inside the tree under analysis (an external
    checkout's registry is ITS registry, not this process's import)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign):
            tgt, val = node.target, node.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, val = node.targets[0], node.value
        else:
            continue
        if isinstance(tgt, ast.Name) and tgt.id == "METRICS" \
                and isinstance(val, ast.Dict):
            return frozenset(k.value for k in val.keys
                             if isinstance(k, ast.Constant)
                             and isinstance(k.value, str))
    return None


def _known_metrics(ctx: FileContext) -> frozenset:
    """The allow set for ``ctx``'s tree: the ANALYZED tree's registry
    (located via ctx's root = path minus relpath) when present, the
    imported package registry as the fallback (throwaway test dirs and
    corpus files have no registry of their own)."""
    registry = _registry_path_for(ctx)
    if registry is not None:
        try:
            mtime = registry.stat().st_mtime_ns
        except OSError:
            mtime = None
        key = str(registry)
        cached = _REGISTRY_CACHE.get(key)
        if cached is not None and cached[0] == mtime:
            return cached[1]
        names = None
        try:
            names = _metric_keys_from_tree(
                ast.parse(registry.read_text()))
        except (OSError, SyntaxError):
            names = None
        if names is not None:
            _REGISTRY_CACHE[key] = (mtime, names)
            return names
    from fedml_tpu.obs.registry import metric_names
    return metric_names()


def _registry_path_for(ctx: FileContext) -> Optional[Path]:
    """<analyzed tree root>/fedml_tpu/obs/registry.py, derived by
    stripping ``relpath`` off the context's absolute path; None when the
    analyzed set isn't rooted in a tree that ships a registry."""
    try:
        path = Path(ctx.path).resolve()
        rel = Path(ctx.relpath)
        if path.parts[-len(rel.parts):] != rel.parts:
            return None
        root = Path(*path.parts[:-len(rel.parts)])
    except (ValueError, OSError):
        return None
    registry = root / _REGISTRY_RELPATH
    return registry if registry.is_file() else None


def _literal_names(node: ast.expr) -> List[str]:
    """String literals an argument can evaluate to: a plain constant, or
    both arms of a conditional (``"hit" if ok else "miss"``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.IfExp):
        return _literal_names(node.body) + _literal_names(node.orelse)
    return []


def _is_timer_receiver(func: ast.expr) -> bool:
    if not isinstance(func, ast.Attribute):
        return False
    recv = dotted_name(func.value)
    if not recv:
        return False
    return recv.split(".")[-1] in _TIMER_NAMES


class MetricNameRule(Rule):
    id = "FT017"
    title = ("timer.count/add/gauge/phase with a literal metric name "
             "absent from the documented registry (defaultdict: a typo "
             "silently creates a dead series)")
    hint = ("register the metric in fedml_tpu/obs/registry.py (and add "
            "its README table row), fix the typo, or pragma a "
            "deliberately unregistered name: # ft: allow[FT017] why")

    def applies(self, relpath: str) -> bool:
        from fedml_tpu.analysis.lint import is_test_path
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.relpath == _REGISTRY_RELPATH:
            yield from self._check_registry_vs_readme(ctx)
        # textual pre-gate: almost no file talks to a timer
        if not any(tok in ctx.source for tok in
                   (".count(", ".gauge(", ".phase(", ".add(")):
            return
        known = None
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METHODS and node.args):
                continue
            if not _is_timer_receiver(node.func):
                continue
            names = _literal_names(node.args[0])
            if not names:
                continue  # non-literal: out of scope (aliasing limit)
            if known is None:
                known = _known_metrics(ctx)
            for name in names:
                if name not in known:
                    yield ctx.finding(
                        self, node,
                        f"timer.{node.func.attr}({name!r}, ...) uses a "
                        "metric name absent from the documented registry "
                        "(fedml_tpu/obs/registry.py) — the defaultdict "
                        "silently creates a new key, so a typo here "
                        "kills the intended series without any failure")

    def _check_registry_vs_readme(self,
                                  ctx: FileContext) -> Iterator[Finding]:
        """The registry IS the README metrics table's oracle: every
        registered name must appear backticked in the repo README —
        both read from the ANALYZED tree (pragma suppression is the
        engine's central pass, like every rule)."""
        readme = self._find_readme(ctx.path)
        if readme is None:
            return
        text = readme.read_text()
        names = _metric_keys_from_tree(ctx.tree) or frozenset()
        # anchor findings at the METRICS dict assignment
        line = 1
        for node in ast.iter_child_nodes(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                tgt = (node.targets[0] if isinstance(node, ast.Assign)
                       else node.target)
                if isinstance(tgt, ast.Name) and tgt.id == "METRICS":
                    line = node.lineno
                    break
        for name in sorted(names):
            if f"`{name}`" not in text:
                yield Finding(
                    rule=self.id, path=ctx.relpath, line=line,
                    message=f"registered metric {name!r} is missing from "
                            "the README \"Observability\" metric table — "
                            "the registry and the table are one "
                            "machine-checked surface",
                    hint=self.hint,
                    snippet=(ctx.lines[line - 1].strip()
                             if 0 < line <= len(ctx.lines) else ""))

    @staticmethod
    def _find_readme(registry_path: Path) -> Optional[Path]:
        """README.md at the analyzed tree's root: registry.py lives at
        <root>/fedml_tpu/obs/registry.py."""
        try:
            root = Path(registry_path).resolve().parents[2]
        except IndexError:
            return None
        readme = root / "README.md"
        return readme if readme.is_file() else None
