"""FT007 — unbounded blocking or swallowed I/O errors in comm modules.

The exact bug class PR 5's fault-tolerance work fixed, frozen as a rule
so it cannot regress:

1. **Swallowed socket errors** — an ``except OSError:`` (or
   ``ConnectionError`` / ``socket.error`` / a tuple of them) whose body
   is ONLY ``pass``/``...`` silently loses a frame with no error, no
   counter, no log (the old ``tcp._Peer.send`` drop — the server then
   waits forever on a reply that no longer exists). Handlers that
   count, log, re-raise, or use the bound exception are compliant;
   intentional best-effort shutdown paths carry a
   ``# ft: allow[FT007]`` pragma with their rationale.

2. **Blocking calls without a deadline** — in a federation, an
   unbounded block IS a hang:

   - ``socket.create_connection(...)`` without a ``timeout=`` kwarg;
   - ``sock.settimeout(None)`` (explicitly removing a deadline);
   - invoking a gRPC callable — direct
     ``channel.stream_unary(...)(...)`` chains or a name bound from
     ``unary_unary``/``stream_unary``/``unary_stream``/``stream_stream``
     — without a ``timeout=`` kwarg.

Scope: ``fedml_tpu/comm/`` only (plus the analysis corpus). Protocol
modules above the transport have their own deadline machinery
(``round_deadline_s``) and different idioms.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import (FileContext, Rule, dotted_name,
                                     is_corpus_path)

#: exception names whose silent swallow loses I/O errors
_NET_EXCS = frozenset({"OSError", "IOError", "ConnectionError",
                       "ConnectionResetError", "ConnectionRefusedError",
                       "BrokenPipeError", "TimeoutError", "error"})

#: grpc channel methods returning a blocking RPC callable
_RPC_FACTORIES = frozenset({"unary_unary", "stream_unary", "unary_stream",
                            "stream_stream"})


def _names_net_exc(node: ast.expr) -> bool:
    if isinstance(node, (ast.Name, ast.Attribute)):
        return (dotted_name(node) or "").split(".")[-1] in _NET_EXCS
    if isinstance(node, ast.Tuple):
        return any(_names_net_exc(e) for e in node.elts)
    return False


def _body_is_only_pass(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(stmt, ast.Pass)
               or (isinstance(stmt, ast.Expr)
                   and isinstance(stmt.value, ast.Constant)
                   and stmt.value.value is Ellipsis)
               for stmt in handler.body)


def _has_timeout_kwarg(call: ast.Call) -> bool:
    # kw.arg None is a **kwargs splat — unresolvable, benefit of the doubt
    return any(kw.arg == "timeout" or kw.arg is None
               for kw in call.keywords)


class CommTimeoutRule(Rule):
    id = "FT007"
    title = "unbounded blocking / swallowed socket error in a comm module"
    hint = ("pass timeout= to blocking socket/gRPC calls; make OSError "
            "handlers count + log (or re-raise) instead of pass; pragma "
            "intentional best-effort shutdown sites: "
            "# ft: allow[FT007] <why>")

    def applies(self, relpath: str) -> bool:
        return "/comm/" in f"/{relpath}" or is_corpus_path(relpath)

    def _rpc_bindings(self, ctx: FileContext) -> Set[str]:
        """Names (incl. self-attrs) bound from a gRPC rpc-factory call:
        ``stub = ch.stream_unary(...)`` — later bare ``stub(req)`` calls
        must carry a deadline."""
        out: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            fname = dotted_name(node.value.func) or ""
            if fname.split(".")[-1] in _RPC_FACTORIES:
                for tgt in node.targets:
                    name = dotted_name(tgt)
                    if name:
                        out.add(name)
        return out

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        rpc_names = self._rpc_bindings(ctx)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler):
                # bare `except:` is FT005's business; FT007 wants the
                # narrowed-but-swallowed socket error specifically
                if node.type is not None and _names_net_exc(node.type) \
                        and _body_is_only_pass(node):
                    yield ctx.finding(
                        self, node,
                        "socket/conn error swallowed with a bare pass — "
                        "the frame (and the failure) vanish: count + "
                        "warn, re-raise, or pragma the intentional "
                        "shutdown path")
                continue
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func) or ""
            leaf = fname.split(".")[-1]
            if leaf == "create_connection" and not _has_timeout_kwarg(node):
                # positional form: socket.create_connection(addr, timeout)
                if len(node.args) < 2:
                    yield ctx.finding(
                        self, node,
                        "create_connection without a timeout blocks a "
                        "send slot for the kernel's connect timeout "
                        "(minutes) when the peer is dark")
            elif leaf == "settimeout" and node.args and isinstance(
                    node.args[0], ast.Constant) \
                    and node.args[0].value is None:
                yield ctx.finding(
                    self, node,
                    "settimeout(None) removes the socket's deadline — an "
                    "unbounded block is a federation hang; bound it or "
                    "pragma the dedicated-reader-thread idiom")
            elif isinstance(node.func, ast.Call):
                # direct chain: ch.stream_unary(METHOD)(request_iter, ...)
                inner = dotted_name(node.func.func) or ""
                if inner.split(".")[-1] in _RPC_FACTORIES \
                        and not _has_timeout_kwarg(node):
                    yield ctx.finding(
                        self, node,
                        "gRPC call without a timeout= deadline — a hung "
                        "stream blocks the sender forever")
            elif fname in rpc_names and not _has_timeout_kwarg(node):
                yield ctx.finding(
                    self, node,
                    f"{fname} is a gRPC rpc callable invoked without a "
                    "timeout= deadline — a hung stream blocks the sender "
                    "forever")
