"""FT001 — global numpy RNG mutation outside the core.sampling lock.

The PR 2 race, as a rule: ``core.sampling.sample_clients`` implements
the reference's sampling contract by seeding the PROCESS-GLOBAL numpy
RNG per round. The async round pipeline's prefetch worker (and the
cross-silo silo threads) share that process, so any other
``np.random.*`` draw on the global stream can interleave with a
seed/draw pair and corrupt a cohort — observed only as a *flaky* parity
test until the seed+draw was made atomic under
``core.sampling._GLOBAL_RNG_LOCK``.

Safe spellings the rule recognizes:

- a local stream: ``np.random.RandomState(seed)`` /
  ``np.random.default_rng(seed)`` / ``np.random.Generator`` (these
  CONSTRUCT a stream; draws on the instance never touch global state);
- a draw lexically inside ``with locked_global_numpy_rng(...)`` (or a
  direct ``with _GLOBAL_RNG_LOCK``) — the sanctioned way to keep the
  reference's global-stream bit-parity where a contract requires it.

Scope: library code only. ``tests/`` is exempt (pytest runs the
process single-threaded before any prefetcher exists).
"""

from __future__ import annotations

import ast
from typing import Iterator

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import FileContext, Rule, dotted_name, is_test_path

#: np.random functions that mutate the process-global stream
GLOBAL_MUTATORS = frozenset({
    "seed", "choice", "shuffle", "permutation", "randint", "rand",
    "randn", "random", "random_sample", "ranf", "sample", "dirichlet",
    "normal", "uniform", "binomial", "beta", "poisson", "multinomial",
    "standard_normal", "exponential", "gamma", "lognormal", "bytes",
    "set_state", "get_state",
})

#: constructors of LOCAL streams — never a finding
LOCAL_STREAM_CTORS = frozenset({
    "RandomState", "Generator", "default_rng", "SeedSequence", "PCG64",
    "Philox", "MT19937",
})


class GlobalRngRule(Rule):
    id = "FT001"
    title = "global numpy RNG use outside the core.sampling lock"
    hint = ("draw from a local np.random.Generator/RandomState, or hold "
            "core.sampling.locked_global_numpy_rng() across the seed+draws "
            "when the reference contract pins the global stream")

    def applies(self, relpath: str) -> bool:
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if not name:
                continue
            parts = name.split(".")
            if len(parts) < 3 or parts[-2] != "random" or parts[0] not in (
                    "np", "numpy"):
                continue
            fn = parts[-1]
            if fn in LOCAL_STREAM_CTORS:
                continue
            if fn not in GLOBAL_MUTATORS:
                continue
            if ctx.under_rng_lock(node.lineno):
                continue
            yield ctx.finding(
                self, node,
                f"np.random.{fn} mutates the process-global RNG stream that "
                "core.sampling's per-round seed/draw contract shares with "
                "the prefetch worker and silo threads")
