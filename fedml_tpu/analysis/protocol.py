"""FT2xx — whole-program protocol conformance for the cross-silo wire.

The actor protocol is a distributed contract with no single definition:
``MSG_TYPE_*`` constants name the message types, ``Message(TYPE, ...)``
constructions + ``msg.add(KEY, ...)`` calls define what each sender
ships, and ``register_message_receive_handler(TYPE, self.handler)``
registrations + the handler's ``msg.get(KEY)`` reads define what each
receiver demands. PRs 4–7 grew this contract to 12+ message types
across two files and three server flavors — and nothing checked the two
sides against each other until a SIGKILL acceptance test hung.

This pass extracts the full sender→handler graph statically:

- **constants**: module-level ``MSG_TYPE_<NAME> = <int>`` definitions
  (identity = *defining module + name*, so base_framework's type 10
  NEIGHBOR_RESULT and cross-silo's type 10 HEARTBEAT never collide) and
  ``MSG_ARG_KEY_<NAME>`` payload-key strings, both resolved through
  ``from X import Y [as Z]`` chains and ``Class.ATTR`` class constants;
- **send sites**: every ``Message(TYPE, ...)`` construction, with the
  payload keys the surrounding function ``add``s to that message
  variable (a non-literal key marks the site ``dynamic``: its key set
  is open and payload checks stay quiet);
- **handler sites**: every registration, resolved to the method in the
  same class, with the keys it reads — ``msg.get(K)`` /
  ``params[K]`` are *required*, ``get_params().get(K, default)`` is
  *optional* — followed one call level deep through same-file helpers
  the message is forwarded to.

Findings (pragma-able at the send/registration line like every rule):

- **FT200** — the checked-in snapshot ``ci/protocol_graph.json`` is
  missing: CI must fail loudly, not silently skip the drift check.
- **FT201** — a message type is sent but no handler is registered for
  it anywhere (the S2C_JOIN_BACKPRESSURE-without-a-silo-handler class).
- **FT202** — a handler is registered for a type nothing ever sends
  (dead protocol surface, usually a renamed constant).
- **FT203** — a handler *requires* a payload key no sender of that
  type writes (KeyError on the receive thread => a hung federation).
- **FT204** — the extracted graph drifted from the snapshot: new or
  removed types/senders/handlers/keys fail lint until the snapshot is
  regenerated with ``--write-protocol-graph`` (a deliberate,
  reviewable protocol change).

The pass is whole-program by construction — it runs over the full
default tree and is skipped under ``--changed-only``.
"""

from __future__ import annotations

import ast
import hashlib
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import FileContext, dotted_name, is_test_path

GRAPH_VERSION = 1

#: envelope/header keys every message carries — never payload contract
#: (__wire_job__ is the scheduler's tenancy tag, stamped at the
#: transport layer like the reliable seq stamp — comm/base.py)
_HEADER_KEYS = frozenset({"msg_type", "sender", "receiver", "__wire_seq__",
                          "__wire_job__"})

_HINTS = {
    "FT200": ("regenerate the snapshot: python -m fedml_tpu.analysis "
              "--write-protocol-graph"),
    "FT201": ("register a handler for this type on the receiving role "
              "(register_message_receive_handler) or delete the dead "
              "send path"),
    "FT202": ("add the send site this handler is waiting for, or remove "
              "the registration (dead protocol surface)"),
    "FT203": ("add the key at every send site of this type, or read it "
              "optionally: msg.get_params().get(key, default)"),
    "FT204": ("review the protocol change, then refresh the snapshot: "
              "python -m fedml_tpu.analysis --write-protocol-graph"),
}


def _module_of(relpath: str) -> str:
    """``fedml_tpu/comm/message.py`` -> ``fedml_tpu.comm.message``;
    package ``__init__`` files map to the package itself."""
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class _ModuleTable:
    """Per-module symbol information the resolver needs."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.module = _module_of(ctx.relpath)
        #: NAME -> int value (module-level MSG_TYPE-shaped constants)
        self.int_consts: Dict[str, int] = {}
        #: NAME -> str value (module-level key constants)
        self.str_consts: Dict[str, str] = {}
        #: NAME -> (module, name) import aliases
        self.imports: Dict[str, Tuple[str, str]] = {}
        #: NAME -> unresolved RHS expr (e.g. Message.MSG_ARG_KEY_X)
        self.alias_exprs: Dict[str, ast.expr] = {}
        #: ClassName -> {ATTR: str value}
        self.class_str_attrs: Dict[str, Dict[str, str]] = {}
        self._collect()

    def _collect(self) -> None:
        for node in self.ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                val = node.value
                if isinstance(val, ast.Constant):
                    if isinstance(val.value, bool):
                        pass
                    elif isinstance(val.value, int):
                        self.int_consts[name] = val.value
                    elif isinstance(val.value, str):
                        self.str_consts[name] = val.value
                else:
                    self.alias_exprs[name] = val
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        node.module, alias.name)
            elif isinstance(node, ast.ClassDef):
                attrs: Dict[str, str] = {}
                for stmt in node.body:
                    if isinstance(stmt, ast.Assign) \
                            and len(stmt.targets) == 1 \
                            and isinstance(stmt.targets[0], ast.Name) \
                            and isinstance(stmt.value, ast.Constant) \
                            and isinstance(stmt.value.value, str):
                        attrs[stmt.targets[0].id] = stmt.value.value
                if attrs:
                    self.class_str_attrs[node.name] = attrs


class _Program:
    """The resolver over every module table (whole-program view)."""

    def __init__(self, ctxs: Sequence[FileContext]):
        self.tables: Dict[str, _ModuleTable] = {}
        for ctx in ctxs:
            t = _ModuleTable(ctx)
            self.tables[t.module] = t

    # -- constant resolution ------------------------------------------------
    def resolve_int(self, module: str, name: str, _depth: int = 0
                    ) -> Optional[Tuple[str, str, int]]:
        """-> (defining module, name, value) for an int constant."""
        if _depth > 8:
            return None
        t = self.tables.get(module)
        if t is None:
            return None
        if name in t.int_consts:
            return (module, name, t.int_consts[name])
        if name in t.imports:
            mod, orig = t.imports[name]
            return self.resolve_int(mod, orig, _depth + 1)
        return None

    def resolve_str(self, module: str, expr: ast.expr, _depth: int = 0
                    ) -> Optional[str]:
        """String value of a key expression: literal, module constant,
        imported constant, or ``Class.ATTR`` class constant."""
        if _depth > 8:
            return None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        name = dotted_name(expr)
        if not name:
            return None
        return self._resolve_str_name(module, name, _depth)

    def _resolve_str_name(self, module: str, name: str, _depth: int
                          ) -> Optional[str]:
        if _depth > 8:
            return None
        t = self.tables.get(module)
        if t is None:
            return None
        head, _, rest = name.partition(".")
        if rest:  # Class.ATTR or imported-module attribute
            cls_mod, cls_name = self._resolve_name_target(module, head)
            if cls_name is not None:
                ct = self.tables.get(cls_mod)
                if ct and cls_name in ct.class_str_attrs:
                    return ct.class_str_attrs[cls_name].get(rest)
            return None
        if name in t.str_consts:
            return t.str_consts[name]
        if name in t.alias_exprs:
            return self.resolve_str(module, t.alias_exprs[name], _depth + 1)
        if name in t.imports:
            mod, orig = t.imports[name]
            return self._resolve_str_name(mod, orig, _depth + 1)
        return None

    def _resolve_name_target(self, module: str, name: str, _depth: int = 0
                             ) -> Tuple[str, Optional[str]]:
        """Follow import chains for a bare name until the module that
        really defines it (class or constant)."""
        if _depth > 8:
            return module, None
        t = self.tables.get(module)
        if t is None:
            return module, None
        if name in t.class_str_attrs:
            return module, name
        if name in t.imports:
            mod, orig = t.imports[name]
            return self._resolve_name_target(mod, orig, _depth + 1)
        return module, name  # defined (or at least terminal) here


# -- per-function extraction -------------------------------------------------

class _SendSite:
    def __init__(self, type_id: Tuple[str, str, int], path: str, line: int,
                 where: str):
        self.type_id = type_id
        self.path = path
        self.line = line
        self.where = where
        self.keys: Set[str] = set()
        self.dynamic = False


class _ParametricSend:
    """A ``Message(param, ...)`` construction whose type flows in as a
    function parameter (the ``_broadcast_model(msg_type, idxs)`` shape).
    Callers passing a resolvable constant materialize one send site per
    distinct type."""

    def __init__(self, fn_name: str, param: str, params: List[str],
                 path: str, line: int, where: str):
        self.fn_name = fn_name
        self.param = param
        self.params = params  # full positional parameter list (incl self)
        self.path = path
        self.line = line
        self.where = where
        self.keys: Set[str] = set()
        self.dynamic = False


def _shallow_walk(root: ast.AST):
    """ast.walk that does NOT descend into nested function defs — each
    nested def (timer ``fire``, thread ``runner``) is its own extraction
    unit, so its sends are never double-counted."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)


class _HandlerSite:
    def __init__(self, type_id: Tuple[str, str, int], path: str, line: int,
                 cls: str, handler: str):
        self.type_id = type_id
        self.path = path
        self.line = line
        self.cls = cls
        self.handler = handler
        self.required: Set[str] = set()
        self.optional: Set[str] = set()
        self.resolved = False  # handler method found + analyzed


def _functions(tree: ast.AST):
    """Every function/method def with its enclosing class name ('' for
    module level)."""
    out = []

    def visit(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((cls, child))
                visit(child, cls)
            else:
                visit(child, cls)

    visit(tree, "")
    return out


def _extract_sends(prog: _Program, table: _ModuleTable, fn: ast.AST,
                   cls: str
                   ) -> Tuple[List[_SendSite], List[_ParametricSend]]:
    """``Message(TYPE, ...)`` constructions in one function, with the
    keys added to the bound variable in the same function body.

    Statement order matters: the codebase rebinds the same variable to
    different messages in one handler (``out = Message(BACKPRESSURE,
    ...)`` then ``out = Message(SYNC_MODEL, ...)``), so bind/add events
    replay in line order. A type expression that is a *parameter* of
    ``fn`` yields a :class:`_ParametricSend` for caller resolution; a
    conditional ``A if c else B`` yields a site per branch."""
    module, ctx = table.module, table.ctx
    where = f"{cls or '<module>'}.{fn.name}"
    param_names = [a.arg for a in fn.args.args]
    sites: List[_SendSite] = []
    parametrics: List[_ParametricSend] = []
    # (line, kind, payload): kind "bind" -> (var, targets) | "add" ->
    # (var, key expr)
    events: List[Tuple[int, int, str, object]] = []

    def type_exprs(call: ast.Call) -> List[ast.expr]:
        expr = None
        if call.args:
            expr = call.args[0]
        else:
            for kw in call.keywords:
                if kw.arg == "type":
                    expr = kw.value
        if expr is None:
            return []
        if isinstance(expr, ast.IfExp):
            return [expr.body, expr.orelse]
        return [expr]

    def targets_of(call: ast.Call) -> List[object]:
        """Send sites / parametric sends this construction creates."""
        callee = dotted_name(call.func)
        if not callee or callee.split(".")[-1] != "Message":
            return []
        out: List[object] = []
        for expr in type_exprs(call):
            name = dotted_name(expr)
            if not name or "." in name:
                continue  # literal ints / computed types: undeclared
            if name in param_names:
                out.append(_ParametricSend(fn.name, name, param_names,
                                           ctx.relpath, call.lineno, where))
                continue
            tid = prog.resolve_int(module, name)
            if tid is not None:
                out.append(_SendSite(tid, ctx.relpath, call.lineno, where))
        return out

    def register(made: List[object]) -> None:
        for m in made:
            if isinstance(m, _SendSite):
                sites.append(m)
            else:
                parametrics.append(m)

    bound_calls: Set[int] = set()  # Call node ids consumed by an Assign
    for node in _shallow_walk(fn):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call):
            made = targets_of(node.value)
            if made:
                bound_calls.add(id(node.value))
                register(made)
                for tgt in node.targets:
                    nm = dotted_name(tgt)
                    if nm:
                        events.append((node.lineno, 0, "bind", (nm, made)))
    for node in _shallow_walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if id(node) not in bound_calls:
            made = targets_of(node)
            register(made)  # inline Message(...) passed straight to send
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("add", "add_params") and node.args:
            recv = dotted_name(node.func.value)
            if recv:
                events.append((node.lineno, 1, "add",
                               (recv, node.args[0])))
    # replay in source order: a bind replaces the variable's message
    by_var: Dict[str, List[object]] = {}
    for _, _, kind, payload in sorted(events, key=lambda e: (e[0], e[1])):
        if kind == "bind":
            by_var[payload[0]] = payload[1]
        else:
            recv, key_expr = payload
            for site in by_var.get(recv, ()):
                key = prog.resolve_str(module, key_expr)
                if key is None:
                    site.dynamic = True
                elif key not in _HEADER_KEYS:
                    site.keys.add(key)
    return sites, parametrics


class _KeyReads:
    def __init__(self):
        self.required: Set[str] = set()
        self.optional: Set[str] = set()
        self.forwards: List[Tuple[str, str]] = []  # (callee, via) — msg fwd


def _method_key_reads(prog: _Program, table: _ModuleTable,
                      fn: ast.AST, msg_param: str) -> _KeyReads:
    """Keys one function reads off its message parameter.

    ``msg.get(K)`` / ``msg.get_params()[K]`` / ``params[K]`` (where
    ``params = msg.get_params()``) are required; ``.get(K, default)``
    dict-gets are optional. Calls that forward the message variable are
    recorded for one-level expansion."""
    out = _KeyReads()
    module = table.module
    #: local aliases of msg.get_params() results
    param_aliases: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = dotted_name(node.value.func)
            if callee == f"{msg_param}.get_params":
                for tgt in node.targets:
                    nm = dotted_name(tgt)
                    if nm:
                        param_aliases.add(nm)
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            if callee == f"{msg_param}.get" and node.args:
                key = prog.resolve_str(module, node.args[0])
                if key is not None and key not in _HEADER_KEYS:
                    out.required.add(key)
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get":
                recv = dotted_name(node.func.value)
                recv_is_params = (recv in param_aliases
                                  or (isinstance(node.func.value, ast.Call)
                                      and dotted_name(node.func.value.func)
                                      == f"{msg_param}.get_params"))
                if recv_is_params and node.args:
                    key = prog.resolve_str(module, node.args[0])
                    if key is not None and key not in _HEADER_KEYS:
                        out.optional.add(key)  # dict-get tolerates absence
            else:
                # forwarded message: self.helper(msg) / helper(msg, ...)
                args = list(node.args) + [kw.value for kw in node.keywords]
                if any(isinstance(a, ast.Name) and a.id == msg_param
                       for a in args) and callee:
                    last = callee.split(".")[-1]
                    out.forwards.append((last, callee))
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            recv = dotted_name(node.value)
            if recv in param_aliases or (
                    isinstance(node.value, ast.Call)
                    and dotted_name(node.value.func)
                    == f"{msg_param}.get_params"):
                key = prog.resolve_str(module, node.slice)
                if key is not None and key not in _HEADER_KEYS:
                    out.required.add(key)
    return out


def _msg_param_name(fn: ast.AST) -> Optional[str]:
    """The message parameter of a handler/helper: the first non-self
    positional arg."""
    args = [a.arg for a in fn.args.args if a.arg != "self"]
    return args[0] if args else None


def extract_protocol(ctxs: Sequence[FileContext]) -> Dict:
    """-> the full protocol graph (see module docstring) as a dict:
    ``{"version", "types": [{module, name, value, senders, handlers}]}``
    with line numbers included (the ``runs/`` artifact shape)."""
    prog = _Program(ctxs)
    sends: List[_SendSite] = []
    handlers: List[_HandlerSite] = []

    for ctx in ctxs:
        table = prog.tables[_module_of(ctx.relpath)]
        funcs = _functions(ctx.tree)
        #: (cls, name) -> fn node for handler resolution
        methods = {(c, f.name): f for c, f in funcs}
        #: fn name -> parametric sends declared in this file
        file_parametrics: Dict[str, List[_ParametricSend]] = {}
        for cls, fn in funcs:
            got, pars = _extract_sends(prog, table, fn, cls)
            sends.extend(got)
            for p in pars:
                file_parametrics.setdefault(p.fn_name, []).append(p)
            for node in _shallow_walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted_name(node.func) or ""
                if callee.split(".")[-1] != \
                        "register_message_receive_handler" \
                        or len(node.args) < 2:
                    continue
                tname = dotted_name(node.args[0])
                if not tname or "." in tname:
                    continue
                tid = prog.resolve_int(table.module, tname)
                if tid is None:
                    continue
                hname = dotted_name(node.args[1]) or "<lambda>"
                short = hname.split(".")[-1]
                site = _HandlerSite(tid, ctx.relpath, node.lineno, cls,
                                    short)
                handlers.append(site)
                target = methods.get((cls, short)) or methods.get(("", short))
                if target is None:
                    continue
                site.resolved = True
                msg_param = _msg_param_name(target)
                if msg_param is None:
                    continue
                reads = _method_key_reads(prog, table, target, msg_param)
                site.required |= reads.required
                site.optional |= reads.optional
                # one-level expansion through same-file helpers the
                # message is forwarded to
                seen = {short}
                work = list(reads.forwards)
                while work:
                    helper, _ = work.pop()
                    if helper in seen:
                        continue
                    seen.add(helper)
                    hfn = methods.get((cls, helper)) or \
                        methods.get(("", helper))
                    if hfn is None:
                        continue
                    hparam = _msg_param_name(hfn)
                    if hparam is None:
                        continue
                    hreads = _method_key_reads(prog, table, hfn, hparam)
                    site.required |= hreads.required
                    site.optional |= hreads.optional
                    work.extend(hreads.forwards)
        # materialize parametric sends at their call sites: the caller
        # chooses the type, the callee's body defines the payload keys
        # (the `_broadcast_model(MSG_TYPE_..., idxs)` shape)
        if file_parametrics:
            for cls, fn in funcs:
                for node in _shallow_walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = dotted_name(node.func) or ""
                    last = callee.split(".")[-1]
                    for p in file_parametrics.get(last, ()):
                        idx = p.params.index(p.param)
                        if p.params and p.params[0] == "self" \
                                and "." in callee:
                            idx -= 1  # bound-method call drops self
                        if not 0 <= idx < len(node.args):
                            continue
                        tname = dotted_name(node.args[idx])
                        if not tname or "." in tname:
                            continue
                        tid = prog.resolve_int(table.module, tname)
                        if tid is None:
                            continue
                        site = _SendSite(
                            tid, ctx.relpath, node.lineno,
                            f"{cls or '<module>'}.{fn.name}")
                        site.keys = set(p.keys)
                        site.dynamic = p.dynamic
                        sends.append(site)

    types: Dict[Tuple[str, str, int], Dict] = {}

    def entry(tid):
        if tid not in types:
            types[tid] = {"module": tid[0], "name": tid[1],
                          "value": tid[2], "senders": [], "handlers": []}
        return types[tid]

    for s in sends:
        entry(s.type_id)["senders"].append({
            "path": s.path, "line": s.line, "where": s.where,
            "keys": sorted(s.keys), "dynamic": s.dynamic})
    for h in handlers:
        entry(h.type_id)["handlers"].append({
            "path": h.path, "line": h.line, "class": h.cls,
            "handler": h.handler, "required": sorted(h.required),
            "optional": sorted(h.optional), "resolved": h.resolved})
    # declared-but-unused constants still appear (value-only nodes):
    # the graph must cover EVERY msg type the tree defines
    for module, table in prog.tables.items():
        if is_test_path(table.ctx.relpath):
            continue
        for name, value in table.int_consts.items():
            if name.startswith("MSG_TYPE_"):
                entry((module, name, value))
    rows = [types[k] for k in sorted(types)]
    for row in rows:
        row["senders"].sort(key=lambda s: (s["path"], s["line"]))
        row["handlers"].sort(key=lambda h: (h["path"], h["line"]))
    return {"version": GRAPH_VERSION, "types": rows}


def normalize_graph(graph: Dict) -> Dict:
    """Line-free shape for the checked-in snapshot: unrelated edits must
    not drift the fingerprint."""
    out = []
    for row in graph["types"]:
        out.append({
            "module": row["module"], "name": row["name"],
            "value": row["value"],
            "senders": sorted({json.dumps(
                {"path": s["path"], "keys": s["keys"],
                 "dynamic": s["dynamic"]}, sort_keys=True)
                for s in row["senders"]}),
            "handlers": sorted({json.dumps(
                {"path": h["path"], "class": h["class"],
                 "handler": h["handler"], "required": h["required"],
                 "optional": h["optional"]}, sort_keys=True)
                for h in row["handlers"]}),
        })
    payload = {"version": GRAPH_VERSION, "types": out}
    blob = json.dumps(payload, sort_keys=True)
    payload["fingerprint"] = hashlib.sha1(blob.encode()).hexdigest()[:16]
    return payload


def _finding(rule: str, path: str, line: int, message: str,
             snippet: str = "") -> Finding:
    return Finding(rule=rule, path=path, line=line, message=message,
                   hint=_HINTS[rule], snippet=snippet)


def _type_label(row: Dict) -> str:
    return f"{row['module']}.{row['name']} (= {row['value']})"


def conformance_findings(graph: Dict,
                         ctxs: Sequence[FileContext]) -> List[Finding]:
    """FT201/FT202/FT203 over the extracted graph. Pragma suppression is
    applied via the originating file's context (``# ft: allow[FT20x]``
    at the send/registration line)."""
    by_path = {ctx.relpath: ctx for ctx in ctxs}

    def allowed(rule: str, path: str, line: int) -> bool:
        ctx = by_path.get(path)
        return ctx.allowed(rule, line) if ctx else False

    def snippet(path: str, line: int) -> str:
        ctx = by_path.get(path)
        if ctx and 0 < line <= len(ctx.lines):
            return ctx.lines[line - 1].strip()
        return ""

    findings: List[Finding] = []
    for row in graph["types"]:
        senders, hands = row["senders"], row["handlers"]
        if senders and not hands:
            s = senders[0]
            if not allowed("FT201", s["path"], s["line"]):
                findings.append(_finding(
                    "FT201", s["path"], s["line"],
                    f"message type {_type_label(row)} is sent "
                    f"({len(senders)} site(s)) but NO handler is "
                    "registered for it anywhere — the receiver's "
                    "dispatch raises KeyError (or the frame is dropped) "
                    "and the protocol hangs at the next barrier",
                    snippet(s["path"], s["line"])))
        if hands and not senders:
            h = hands[0]
            if not allowed("FT202", h["path"], h["line"]):
                findings.append(_finding(
                    "FT202", h["path"], h["line"],
                    f"handler {h['class']}.{h['handler']} is registered "
                    f"for {_type_label(row)} but nothing in the tree "
                    "ever sends that type — dead protocol surface "
                    "(renamed constant? deleted sender?)",
                    snippet(h["path"], h["line"])))
        if not (senders and hands):
            continue
        any_dynamic = any(s["dynamic"] for s in senders)
        sent_everywhere = set(senders[0]["keys"])
        for s in senders[1:]:
            sent_everywhere &= set(s["keys"])
        for h in hands:
            if not h["resolved"]:
                continue
            for key in h["required"]:
                if key in sent_everywhere or any_dynamic:
                    continue
                sent_somewhere = any(key in s["keys"] for s in senders)
                if allowed("FT203", h["path"], h["line"]):
                    continue
                where = ("only SOME senders write it"
                         if sent_somewhere else "no sender writes it")
                findings.append(_finding(
                    "FT203", h["path"], h["line"],
                    f"handler {h['class']}.{h['handler']} REQUIRES "
                    f"payload key {key!r} of {_type_label(row)} but "
                    f"{where} — msg.get raises KeyError on the receive "
                    "thread and the round never closes",
                    snippet(h["path"], h["line"])))
    return findings


def snapshot_findings(graph: Dict, snapshot_path: Path) -> List[Finding]:
    """FT200 (missing snapshot) / FT204 (drift) against ``ci/``."""
    norm = normalize_graph(graph)
    path = Path(snapshot_path)
    if not path.exists():
        return [_finding(
            "FT200", str(snapshot_path), 0,
            "protocol-graph snapshot is MISSING — the drift check "
            "cannot run, and a silently skipped check is exactly the "
            "failure mode this pass exists to prevent")]
    try:
        old = json.loads(path.read_text())
    except (OSError, ValueError) as exc:
        return [_finding(
            "FT200", str(snapshot_path), 0,
            f"protocol-graph snapshot is unreadable ({exc}) — "
            "regenerate it")]
    if old.get("fingerprint") == norm["fingerprint"]:
        return []
    # diff at type granularity for an actionable message
    old_types = {(t["module"], t["name"]): t for t in old.get("types", [])}
    new_types = {(t["module"], t["name"]): t for t in norm["types"]}
    changes: List[str] = []
    for key in sorted(set(new_types) - set(old_types)):
        changes.append(f"new type {key[0]}.{key[1]}")
    for key in sorted(set(old_types) - set(new_types)):
        changes.append(f"removed type {key[0]}.{key[1]}")
    for key in sorted(set(old_types) & set(new_types)):
        if old_types[key] != new_types[key]:
            changes.append(f"changed senders/handlers/keys of "
                           f"{key[0]}.{key[1]}")
    detail = "; ".join(changes) or "graph fingerprint changed"
    return [_finding(
        "FT204", str(snapshot_path), 0,
        f"protocol graph drifted from the checked-in snapshot: {detail}")]


def write_graph(graph: Dict, artifact_path: Path,
                snapshot_path: Optional[Path] = None) -> None:
    """Write the line-bearing artifact (``runs/``) and optionally the
    normalized snapshot (``ci/``)."""
    artifact_path = Path(artifact_path)
    artifact_path.parent.mkdir(parents=True, exist_ok=True)
    artifact_path.write_text(json.dumps(graph, indent=2, sort_keys=True)
                             + "\n")
    if snapshot_path is not None:
        snapshot_path = Path(snapshot_path)
        snapshot_path.parent.mkdir(parents=True, exist_ok=True)
        snapshot_path.write_text(
            json.dumps(normalize_graph(graph), indent=2, sort_keys=True)
            + "\n")


def check_protocol(ctxs: Sequence[FileContext], snapshot_path: Path,
                   artifact_path: Optional[Path] = None,
                   write_snapshot: bool = False
                   ) -> Tuple[List[Finding], Dict]:
    """The CLI entry: extract, emit the artifact, check conformance +
    snapshot. With ``write_snapshot`` the snapshot is refreshed instead
    of compared (conformance findings still apply — a snapshot must
    never launder an FT201)."""
    lib_ctxs = [c for c in ctxs if not is_test_path(c.relpath)]
    graph = extract_protocol(lib_ctxs)
    if artifact_path is not None:
        write_graph(graph, artifact_path)
    findings = conformance_findings(graph, lib_ctxs)
    if write_snapshot:
        snapshot_path = Path(snapshot_path)
        snapshot_path.parent.mkdir(parents=True, exist_ok=True)
        snapshot_path.write_text(
            json.dumps(normalize_graph(graph), indent=2, sort_keys=True)
            + "\n")
    else:
        findings.extend(snapshot_findings(graph, snapshot_path))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, graph
