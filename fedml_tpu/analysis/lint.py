"""Layer 1 — the AST lint engine.

One parse per file, shared by every rule through a :class:`FileContext`
that pre-computes what the project rules keep asking for: suppression
pragmas, ``with``-block spans whose context expression names the global
RNG lock, nested-``def`` spans (jit-traced closures in hot modules), and
the module's jitted-callable bindings (FT002/FT004).

Scoping: rules declare where they apply via :meth:`Rule.applies`.
Driver code under ``tests/`` is exempt from the concurrency rules
(single-threaded by construction) — EXCEPT ``analysis_corpus``
directories, which hold seeded violations and are always linted as
library code. The directory walker skips corpus dirs, so they are only
linted when named explicitly (the analyzer's own tests do exactly that).

Pragma syntax (suppresses on its own line or the line above)::

    np.random.seed(s)  # ft: allow[FT001] — build-time, pre-thread
    # ft: allow[FT005,FT003] rationale text
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from fedml_tpu.analysis.finding import Finding

PRAGMA_RE = re.compile(r"#\s*ft:\s*allow\[([A-Z0-9,\s]+)\]")

#: recognized spellings of the core.sampling global-RNG mutual exclusion
#: (FT001 treats draws lexically inside these ``with`` blocks as safe)
RNG_LOCK_NAMES = ("locked_global_numpy_rng", "_GLOBAL_RNG_LOCK",
                  "global_rng_lock")

#: directories never entered by the recursive walker
SKIP_DIRS = {"__pycache__", ".git", "analysis_corpus", "node_modules",
             ".pytest_cache", "build", "dist"}


def _parts(relpath: str) -> Tuple[str, ...]:
    return tuple(Path(relpath).parts)


def is_corpus_path(relpath: str) -> bool:
    return "analysis_corpus" in _parts(relpath)


def is_test_path(relpath: str) -> bool:
    if is_corpus_path(relpath):
        return False  # seeded-violation corpora are linted as library code
    parts = _parts(relpath)
    return "tests" in parts or (parts and parts[-1].startswith("test_"))


class JitBinding:
    """A name (or self-attribute) bound to a ``jax.jit(...)`` result in
    this module, with its donation/static metadata — the shared substrate
    of FT002 (donated-buffer reuse) and FT004 (scalar-arg signatures)."""

    def __init__(self, name: str, lineno: int,
                 donate: Set[int], static_nums: Set[int],
                 static_names: Set[str]):
        self.name = name
        self.lineno = lineno
        self.donate = donate
        self.static_nums = static_nums
        self.static_names = static_names


def _int_set(node: Optional[ast.expr]) -> Set[int]:
    """Literal ints out of ``(0, 1)`` / ``0`` argnums values; non-literal
    expressions yield an empty set (we cannot resolve them — rules then
    stay quiet rather than guess)."""
    if node is None:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out: Set[int] = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.add(elt.value)
        return out
    return set()


def _str_set(node: Optional[ast.expr]) -> Set[str]:
    if node is None:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return {e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)}
    return set()


def dotted_name(node: ast.expr) -> Optional[str]:
    """``self._round_fn`` / ``np.random.seed`` as a dotted string, or None
    for anything not a pure attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _jit_call_meta(call: ast.Call) -> Optional[Tuple[Set[int], Set[int], Set[str]]]:
    """(donate, static_nums, static_names) if ``call`` constructs a jitted
    callable: ``jax.jit(...)`` or ``functools.partial(jax.jit, ...)``."""
    name = dotted_name(call.func)
    kw_src: Optional[ast.Call] = None
    if name in ("jax.jit", "jit"):
        kw_src = call
    elif name in ("functools.partial", "partial") and call.args:
        first = dotted_name(call.args[0])
        if first in ("jax.jit", "jit"):
            kw_src = call
    if kw_src is None:
        return None
    donate: Set[int] = set()
    static_nums: Set[int] = set()
    static_names: Set[str] = set()
    for kw in kw_src.keywords:
        if kw.arg == "donate_argnums":
            val = kw.value
            # the tree's `(0,) if donate else ()` idiom: take the
            # donating branch — flagging a maybe-donated reuse is the
            # conservative direction for FT002
            if isinstance(val, ast.IfExp):
                val = val.body
            donate |= _int_set(val)
        elif kw.arg == "static_argnums":
            static_nums |= _int_set(kw.value)
        elif kw.arg == "static_argnames":
            static_names |= _str_set(kw.value)
    return donate, static_nums, static_names


class FileContext:
    """Everything the rules need about one file, computed once."""

    def __init__(self, path: Path, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        self.pragmas = self._collect_pragmas()
        #: pragma line -> rule ids that actually suppressed a finding
        self.pragmas_used: Dict[int, Set[str]] = {}
        self.lock_spans = self._collect_lock_spans()
        self.nested_def_spans = self._collect_nested_def_spans()
        self.jit_bindings = self._collect_jit_bindings()

    # -- pragmas ----------------------------------------------------------
    def _collect_pragmas(self) -> Dict[int, Set[str]]:
        """Pragmas from real COMMENT tokens only: a pragma example
        quoted in a docstring, a rule's hint string, or a test's
        source-literal must neither suppress nor count as unused."""
        import io
        import tokenize
        out: Dict[int, Set[str]] = {}
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = PRAGMA_RE.search(tok.string)
                if m:
                    out[tok.start[0]] = {r.strip()
                                         for r in m.group(1).split(",")
                                         if r.strip()}
        except tokenize.TokenError:  # pragma: no cover — ast parsed, so
            for i, line in enumerate(self.lines, start=1):  # regex fallback
                m = PRAGMA_RE.search(line)
                if m:
                    out[i] = {r.strip() for r in m.group(1).split(",")
                              if r.strip()}
        return out

    def allowed(self, rule: str, line: int) -> bool:
        """Pragma on the finding's line or the line directly above it.

        A match is also RECORDED (``pragmas_used``): a pragma that never
        suppresses anything across a whole run is stale and surfaces as
        an unused-pragma warning / FT012 finding (``--strict-pragmas``).
        """
        for ln in (line, line - 1):
            if rule in self.pragmas.get(ln, ()):
                self.pragmas_used.setdefault(ln, set()).add(rule)
                return True
        return False

    # -- structure helpers ------------------------------------------------
    def _collect_lock_spans(self) -> List[Tuple[int, int]]:
        spans = []
        for node in ast.walk(self.tree):
            if isinstance(node, ast.With):
                for item in node.items:
                    src = ast.dump(item.context_expr)
                    if any(name in src for name in RNG_LOCK_NAMES):
                        spans.append((node.lineno, node.end_lineno or node.lineno))
                        break
        return spans

    def under_rng_lock(self, line: int) -> bool:
        return any(a <= line <= b for a, b in self.lock_spans)

    def _collect_nested_def_spans(self) -> List[Tuple[int, int]]:
        """Spans of defs nested inside another def (closures handed to
        jit/vmap/scan in this codebase's idiom) — class methods are NOT
        nested defs."""
        spans: List[Tuple[int, int]] = []

        def visit(node: ast.AST, in_func: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    if in_func:
                        spans.append((child.lineno,
                                      child.end_lineno or child.lineno))
                    visit(child, True)
                elif isinstance(child, ast.ClassDef):
                    visit(child, in_func)
                else:
                    visit(child, in_func)

        visit(self.tree, False)
        return spans

    def in_nested_def(self, line: int) -> bool:
        return any(a <= line <= b for a, b in self.nested_def_spans)

    def _collect_jit_bindings(self) -> Dict[str, JitBinding]:
        """name/attr -> JitBinding for every ``x = jax.jit(...)`` /
        ``self.y = jax.jit(...)`` assignment and every def decorated with
        ``@jax.jit`` / ``@functools.partial(jax.jit, ...)``."""
        out: Dict[str, JitBinding] = {}

        def record(name: Optional[str], lineno: int, meta) -> None:
            if name and meta is not None:
                out[name] = JitBinding(name, lineno, *meta)

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                meta = _jit_call_meta(node.value)
                for tgt in node.targets:
                    record(dotted_name(tgt), node.lineno, meta)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        meta = _jit_call_meta(dec)
                        record(node.name, node.lineno, meta)
                    elif dotted_name(dec) in ("jax.jit", "jit"):
                        record(node.name, node.lineno, (set(), set(), set()))
        return out

    # -- finding constructor ---------------------------------------------
    def finding(self, rule: "Rule", node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        snippet = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        return Finding(rule=rule.id, path=self.relpath, line=line,
                       message=message, hint=rule.hint, snippet=snippet)


class Rule:
    """Base class: subclasses set ``id``/``title``/``hint``, implement
    ``check``, and may narrow ``applies`` (path scoping)."""

    id: str = "FT000"
    title: str = ""
    hint: str = ""

    def applies(self, relpath: str) -> bool:
        return True

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


def iter_python_files(paths: Sequence[Path]) -> Iterator[Path]:
    for p in paths:
        p = Path(p)
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for sub in sorted(p.rglob("*.py")):
                if not any(part in SKIP_DIRS for part in sub.parts):
                    yield sub


def build_contexts(paths: Sequence[Path], root: Optional[Path] = None
                   ) -> Tuple[List[FileContext], List[Finding]]:
    """Parse every python file under ``paths`` once. Unparseable files
    produce an FT000 finding instead of crashing the run. ``root``
    anchors the repo-relative paths findings carry (defaults to the
    common parent, so fingerprints are stable no matter where the CLI
    is invoked from)."""
    root = Path(root).resolve() if root else None
    ctxs: List[FileContext] = []
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        resolved = path.resolve()
        if root is not None:
            try:
                rel = resolved.relative_to(root).as_posix()
            except ValueError:
                rel = resolved.as_posix()
        else:
            rel = path.as_posix()
        try:
            ctxs.append(FileContext(path, rel, path.read_text()))
        except (SyntaxError, UnicodeDecodeError) as exc:
            findings.append(Finding(
                rule="FT000", path=rel,
                line=getattr(exc, "lineno", 0) or 0,
                message=f"unparseable: {type(exc).__name__}: {exc}",
                hint="fix the syntax error; the linter cannot see this file"))
    return ctxs, findings


def lint_contexts(ctxs: Sequence[FileContext],
                  rules: Optional[Iterable[Rule]] = None) -> List[Finding]:
    """Run every rule over the pre-built contexts (pragma suppression
    applied and recorded on each context's ``pragmas_used``)."""
    from fedml_tpu.analysis.rules import all_rules
    rules = list(rules) if rules is not None else all_rules()
    findings: List[Finding] = []
    for ctx in ctxs:
        for rule in rules:
            if not rule.applies(ctx.relpath):
                continue
            for f in rule.check(ctx):
                if not ctx.allowed(f.rule, f.line):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lint_paths(paths: Sequence[Path], root: Optional[Path] = None,
               rules: Optional[Iterable[Rule]] = None) -> List[Finding]:
    """Parse + lint (the one-call convenience the tests and callers that
    don't need the shared contexts use)."""
    ctxs, findings = build_contexts(paths, root=root)
    findings.extend(lint_contexts(ctxs, rules=rules))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


UNUSED_PRAGMA_RULE = "FT012"


def unused_pragmas(ctxs: Sequence[FileContext],
                   active_rule_ids: Set[str],
                   strict: bool = False) -> Tuple[List[Dict], List[Finding]]:
    """-> (warnings, findings): pragma entries that suppressed nothing.

    Run AFTER every pass that consumes pragmas (lint, protocol). Only
    rule ids in ``active_rule_ids`` are judged — a pragma for a pass
    that did not run this invocation (e.g. FT2xx under
    ``--changed-only``) is not "unused", it is unexercised. ``strict``
    additionally returns each stale pragma as an FT012 finding (itself
    pragma-able: ``# ft: allow[FT012] why``)."""
    warnings: List[Dict] = []
    findings: List[Finding] = []
    for ctx in ctxs:
        for line, rules in sorted(ctx.pragmas.items()):
            for rule in sorted(rules):
                if rule == UNUSED_PRAGMA_RULE or rule not in active_rule_ids:
                    continue
                if rule in ctx.pragmas_used.get(line, ()):
                    continue
                warnings.append({"path": ctx.relpath, "line": line,
                                 "rule": rule})
                if strict and not ctx.allowed(UNUSED_PRAGMA_RULE, line):
                    snippet = (ctx.lines[line - 1].strip()
                               if 0 < line <= len(ctx.lines) else "")
                    findings.append(Finding(
                        rule=UNUSED_PRAGMA_RULE, path=ctx.relpath, line=line,
                        message=f"pragma allow[{rule}] suppresses no "
                                f"finding in this run — the flagged code "
                                "was fixed or moved; stale suppressions "
                                "accumulate and mask future regressions",
                        hint="delete the pragma (or the stale rule id "
                             "from its list)",
                        snippet=snippet))
    return warnings, findings
