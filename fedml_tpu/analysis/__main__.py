"""CLI: ``python -m fedml_tpu.analysis [paths...]``.

Default paths are ``fedml_tpu/`` and ``tests/`` under the repo root
(auto-detected: the cwd if it contains ``fedml_tpu/``, else the
package's parent). Exit codes: 0 clean (all findings fixed, pragma'd
or baselined), 1 active findings, 2 internal error. Human output goes
to stdout in ``--format text`` (the default), one JSON report object
in ``--format json``; ``--output`` additionally writes the JSON report
as a CI artifact in either mode.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List

from fedml_tpu.analysis.baseline import (apply_baseline, load_baseline,
                                         save_baseline)
from fedml_tpu.analysis.lint import lint_paths


def _repo_root() -> Path:
    cwd = Path.cwd()
    if (cwd / "fedml_tpu").is_dir():
        return cwd
    import fedml_tpu
    return Path(fedml_tpu.__file__).resolve().parent.parent


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fedml_tpu.analysis",
        description="JAX-aware static analysis: AST lint + jaxpr audit")
    parser.add_argument("paths", nargs="*", type=Path,
                        help="files/dirs to lint (default: fedml_tpu/ and "
                             "tests/ under the repo root)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", type=Path, default=None,
                        help="baseline JSON; matching findings are "
                             "suppressed, unmatched entries warn stale "
                             "(default: ci/analysis_baseline.json under "
                             "the repo root, when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the default repo baseline")
    parser.add_argument("--write-baseline", type=Path, default=None,
                        help="write the active findings to this baseline "
                             "file and exit 0 (tool-adoption escape hatch)")
    parser.add_argument("--no-audit", action="store_true",
                        help="skip the jaxpr audit layer (AST lint only)")
    parser.add_argument("--audit-only", action="store_true",
                        help="skip the AST lint (jaxpr audit only)")
    parser.add_argument("--output", type=Path, default=None,
                        help="also write the JSON report here (CI artifact)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        from fedml_tpu.analysis.rules import rule_table
        for row in rule_table():
            print(f"{row['id']}  {row['title']}\n       fix: {row['hint']}")
        return 0

    root = _repo_root()
    paths = args.paths or [p for p in (root / "fedml_tpu", root / "tests")
                           if p.exists()]
    if args.baseline is None and not args.no_baseline:
        default_bl = root / "ci" / "analysis_baseline.json"
        if default_bl.exists():
            args.baseline = default_bl
    elif args.no_baseline:
        args.baseline = None

    findings = []
    if not args.audit_only:
        findings.extend(lint_paths(paths, root=root))

    audit_reports: List[dict] = []
    if not args.no_audit:
        # honor $JAX_PLATFORMS against environments whose sitecustomize
        # sets the platform programmatically (same belt-and-braces as
        # tests/conftest.py) — audit builders execute model init, and an
        # accidental tunnel-TPU dispatch turns 14 s of CI into minutes
        import os
        if os.environ.get("JAX_PLATFORMS"):
            import jax
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        from fedml_tpu.analysis.jaxpr_audit import run_audit
        audit_findings, audit_reports = run_audit()
        findings.extend(audit_findings)

    stale: List[dict] = []
    suppressed = []
    entries: List[dict] = []
    if args.baseline is not None:
        entries = load_baseline(args.baseline)
        findings, suppressed, stale = apply_baseline(findings, entries)

    if args.write_baseline is not None:
        # active AND currently-suppressed findings: refreshing an
        # existing baseline must not drop the entries that are still
        # live in the tree (they were filtered out of `findings` above)
        adopted = sorted(findings + suppressed,
                         key=lambda f: (f.path, f.line, f.rule))
        save_baseline(args.write_baseline, adopted, note="adopted",
                      notes_by_fingerprint={e["fingerprint"]: e.get("note", "")
                                            for e in entries})
        print(f"wrote {len(adopted)} entries to {args.write_baseline}")
        return 0

    report = {
        "findings": [f.to_json() for f in findings],
        "suppressed": [f.to_json() for f in suppressed],
        "stale_baseline": stale,
        "audit": audit_reports,
        "counts": {"active": len(findings), "suppressed": len(suppressed),
                   "stale_baseline": len(stale)},
    }
    if args.output is not None:
        args.output.parent.mkdir(parents=True, exist_ok=True)
        args.output.write_text(json.dumps(report, indent=2) + "\n")

    if args.format == "json":
        print(json.dumps(report, indent=2))
    else:
        for f in findings:
            print(f.format_text())
        for e in stale:
            print(f"WARNING: stale baseline entry {e['rule']} "
                  f"{e.get('path', '?')} ({e['fingerprint']}) matches "
                  "nothing — the code was fixed; remove the entry")
        for rep in audit_reports:
            print(f"audit: {rep['entry']}: {rep['n_lowering_keys']} "
                  f"lowering key(s) over {rep['sweep_len']}-point sweep, "
                  f"{rep['n_eqns']} top-level eqns")
        n = len(findings)
        print(f"{n} active finding(s), {len(suppressed)} baselined, "
              f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'}")
    return 1 if findings else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except KeyboardInterrupt:  # pragma: no cover
        sys.exit(130)
    except Exception:  # the documented "internal error" exit: a crash
        import traceback  # (malformed baseline, unreadable output dir)
        traceback.print_exc()  # must be distinguishable from "findings"
        sys.exit(2)
