"""CLI: ``python -m fedml_tpu.analysis [paths...]``.

Default paths are ``fedml_tpu/`` and ``tests/`` under the repo root
(auto-detected: the cwd if it contains ``fedml_tpu/``, else the
package's parent). Five passes share one parse of the tree:

1. AST lint (FT001–FT015, FT020–FT024) + unused-pragma detection
   (FT012 under ``--strict-pragmas``; a warning otherwise);
2. whole-program protocol conformance (FT2xx) with the sender→handler
   graph emitted to ``runs/protocol_graph.json`` and drift-checked
   against the ``ci/protocol_graph.json`` snapshot;
3. round-shape conformance over the ``algorithms/`` driver zoo (FT30x)
   plus flag/env conformance (FT016): the round-engine map lands in
   ``runs/round_engine_map.json`` and is drift-checked against the
   ``ci/round_engine_map.json`` snapshot (accept with
   ``--write-round-map``);
4. resource-lifecycle extraction (FT025): the worker/resource shutdown
   graph lands in ``runs/shutdown_graph.json`` and is drift-checked
   against the ``ci/shutdown_graph.json`` snapshot (accept with
   ``--write-shutdown-graph``);
5. jaxpr audit of registered hot entry points (FT10x) incl. the
   collective-signature check against ``ci/collective_baseline.json``.

``--changed-only [REF]`` lints only files touched vs a git ref
(default HEAD) — the sub-second pre-commit lane; the whole-program
protocol/round-shape/flag passes and the jaxpr audit are skipped there
by construction.

Exit codes: 0 clean (all findings fixed, pragma'd or baselined), 1
active findings, 2 internal error. Human output goes to stdout in
``--format text`` (the default), one JSON report object in ``--format
json``, GitHub Actions ``::error`` annotations in ``--format github``;
``--output`` additionally writes the JSON report as a CI artifact in
any mode.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path
from typing import List, Optional, Set

from fedml_tpu.analysis.baseline import (apply_baseline, load_baseline,
                                         save_baseline)
from fedml_tpu.analysis.lint import (SKIP_DIRS, build_contexts,
                                     lint_contexts, unused_pragmas)


def _repo_root() -> Path:
    cwd = Path.cwd()
    if (cwd / "fedml_tpu").is_dir():
        return cwd
    import fedml_tpu
    return Path(fedml_tpu.__file__).resolve().parent.parent


def _changed_files(root: Path, ref: str,
                   scope: List[Path]) -> Optional[List[Path]]:
    """Python files touched vs ``ref`` (committed diffs, working-tree
    edits, and untracked files), restricted to the requested scope and
    the walker's skip rules. None = git unavailable (caller falls back
    to a full lint, loudly)."""
    def run(*args: str) -> Optional[List[str]]:
        try:
            r = subprocess.run(["git", *args], cwd=root,
                               capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        return r.stdout.split("\n") if r.returncode == 0 else None

    diffed = run("diff", "--name-only", ref, "--")
    if diffed is None:
        return None
    # git reports names relative to the TOPLEVEL, which is not
    # necessarily the analysis root (a repo vendoring the project one
    # level down would otherwise silently lint nothing and pass)
    top = run("rev-parse", "--show-toplevel")
    base = Path(top[0].strip()) if top and top[0].strip() else root
    untracked = run("ls-files", "--others", "--exclude-standard") or []
    scope_resolved = [p.resolve() for p in scope]
    out: List[Path] = []
    for name in sorted({*diffed, *untracked}):
        if not name.endswith(".py"):
            continue
        path = (base / name).resolve()
        if not path.is_file():
            continue  # deleted
        if any(part in SKIP_DIRS for part in path.parts):
            continue
        if scope_resolved and not any(
                p == path or p in path.parents for p in scope_resolved):
            continue
        out.append(path)
    return out


def _print_github(findings, stale, pragma_warnings) -> None:
    for f in findings:
        loc = (f"file={f.path},line={f.line}" if f.line
               else f"file={f.path}")
        msg = f.message.replace("\n", " ")
        print(f"::error {loc},title={f.rule}::{msg}")
    for e in stale:
        print(f"::warning file={e.get('path', '?')},title=stale-baseline::"
              f"baseline entry {e['rule']} ({e['fingerprint']}) matches "
              "nothing — remove it")
    for w in pragma_warnings:
        print(f"::warning file={w['path']},line={w['line']},"
              f"title=unused-pragma::allow[{w['rule']}] suppresses "
              "nothing — delete it")


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fedml_tpu.analysis",
        description="JAX-aware static analysis: AST lint + protocol "
                    "conformance + jaxpr/collective audit")
    parser.add_argument("paths", nargs="*", type=Path,
                        help="files/dirs to lint (default: fedml_tpu/ and "
                             "tests/ under the repo root)")
    parser.add_argument("--format", choices=("text", "json", "github"),
                        default="text")
    parser.add_argument("--baseline", type=Path, default=None,
                        help="baseline JSON; matching findings are "
                             "suppressed, unmatched entries warn stale "
                             "(default: ci/analysis_baseline.json under "
                             "the repo root, when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the default repo baseline")
    parser.add_argument("--write-baseline", type=Path, default=None,
                        help="write the active findings to this baseline "
                             "file and exit 0 (tool-adoption escape hatch)")
    parser.add_argument("--prune-stale", action="store_true",
                        help="rewrite the baseline minus entries that no "
                             "longer match anything (notes on live "
                             "entries preserved) and exit 0")
    parser.add_argument("--no-audit", action="store_true",
                        help="skip the jaxpr audit layer")
    parser.add_argument("--audit-only", action="store_true",
                        help="jaxpr audit only (no lint, no protocol)")
    parser.add_argument("--no-protocol", action="store_true",
                        help="skip the whole-program protocol pass")
    parser.add_argument("--no-roundshape", action="store_true",
                        help="skip the round-shape conformance pass "
                             "(FT30x)")
    parser.add_argument("--no-flags", action="store_true",
                        help="skip the flag/env conformance pass (FT016)")
    parser.add_argument("--no-lifecycle", action="store_true",
                        help="skip the shutdown-graph extraction / drift "
                             "pass (FT025; the FT020-FT024 rules run in "
                             "the lint pass regardless)")
    parser.add_argument("--changed-only", nargs="?", const="HEAD",
                        default=None, metavar="GITREF",
                        help="lint only python files changed vs GITREF "
                             "(default HEAD) — the fast pre-commit lane; "
                             "implies --no-audit --no-protocol (both are "
                             "whole-program passes)")
    parser.add_argument("--strict-pragmas", action="store_true",
                        help="unused pragmas become FT012 findings "
                             "instead of warnings")
    parser.add_argument("--write-protocol-graph", action="store_true",
                        help="refresh ci/protocol_graph.json from the "
                             "current tree (the deliberate way to accept "
                             "a protocol change)")
    parser.add_argument("--write-collective-baseline", action="store_true",
                        help="refresh ci/collective_baseline.json from "
                             "the current audit (accept a collective "
                             "change)")
    parser.add_argument("--write-round-map", action="store_true",
                        help="refresh ci/round_engine_map.json from the "
                             "current tree (the deliberate way to accept "
                             "a round-shape change)")
    parser.add_argument("--write-shutdown-graph", action="store_true",
                        help="refresh ci/shutdown_graph.json from the "
                             "current tree (the deliberate way to accept "
                             "a worker/resource lifecycle change)")
    parser.add_argument("--shutdown-graph-snapshot", type=Path,
                        default=None,
                        help="shutdown-graph snapshot path (default: "
                             "ci/shutdown_graph.json under the root)")
    parser.add_argument("--round-map-snapshot", type=Path, default=None,
                        help="round-shape snapshot path (default: "
                             "ci/round_engine_map.json under the root)")
    parser.add_argument("--protocol-snapshot", type=Path, default=None,
                        help="protocol snapshot path (default: "
                             "ci/protocol_graph.json under the root)")
    parser.add_argument("--collective-baseline", type=Path, default=None,
                        help="collective baseline path (default: "
                             "ci/collective_baseline.json under the root)")
    parser.add_argument("--output", type=Path, default=None,
                        help="also write the JSON report here (CI artifact)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        from fedml_tpu.analysis.rules import rule_table
        for row in rule_table():
            print(f"{row['id']}  {row['title']}\n       fix: {row['hint']}")
        return 0

    root = _repo_root()
    paths = args.paths or [p for p in (root / "fedml_tpu", root / "tests")
                           if p.exists()]
    if args.baseline is None and not args.no_baseline:
        default_bl = root / "ci" / "analysis_baseline.json"
        if default_bl.exists():
            args.baseline = default_bl
    elif args.no_baseline:
        args.baseline = None
    protocol_snapshot = (args.protocol_snapshot
                         or root / "ci" / "protocol_graph.json")
    collective_baseline = (args.collective_baseline
                           or root / "ci" / "collective_baseline.json")
    round_map_snapshot = (args.round_map_snapshot
                          or root / "ci" / "round_engine_map.json")
    shutdown_graph_snapshot = (args.shutdown_graph_snapshot
                               or root / "ci" / "shutdown_graph.json")

    changed_only = args.changed_only is not None
    if changed_only:
        changed = _changed_files(root, args.changed_only, paths)
        if changed is None:
            print(f"WARNING: git diff vs {args.changed_only!r} failed — "
                  "falling back to a full lint", file=sys.stderr)
            changed_only = False
        else:
            paths = changed

    run_lint = not args.audit_only
    run_protocol = (not args.audit_only and not args.no_protocol
                    and not changed_only)
    run_roundshape = (not args.audit_only and not args.no_roundshape
                      and not changed_only)
    run_flags = (not args.audit_only and not args.no_flags
                 and not changed_only)
    run_lifecycle = (not args.audit_only and not args.no_lifecycle
                     and not changed_only)
    run_audit_pass = not args.no_audit and not changed_only

    # the snapshot-refresh flags must apply or fail loudly — a silently
    # ignored --write-* leaves the developer believing a protocol or
    # collective change was accepted when the snapshot never moved
    if args.write_protocol_graph and (not run_protocol or args.paths):
        print("--write-protocol-graph needs the default whole-tree "
              "protocol pass (no explicit paths, no --changed-only / "
              "--no-protocol / --audit-only)", file=sys.stderr)
        return 2
    if args.write_collective_baseline and not run_audit_pass:
        print("--write-collective-baseline needs the audit pass (drop "
              "--no-audit / --changed-only)", file=sys.stderr)
        return 2
    if args.write_round_map and (not run_roundshape or args.paths):
        print("--write-round-map needs the default whole-tree "
              "round-shape pass (no explicit paths, no --changed-only / "
              "--no-roundshape / --audit-only)", file=sys.stderr)
        return 2
    if args.write_shutdown_graph and (not run_lifecycle or args.paths):
        print("--write-shutdown-graph needs the default whole-tree "
              "lifecycle pass (no explicit paths, no --changed-only / "
              "--no-lifecycle / --audit-only)", file=sys.stderr)
        return 2

    findings = []
    ctxs = []
    if run_lint:
        ctxs, findings = build_contexts(paths, root=root)
        from fedml_tpu.analysis.rules import all_rules
        rules = all_rules()
        findings.extend(lint_contexts(ctxs, rules=rules))
        active_rule_ids: Set[str] = {r.id for r in rules}
    else:
        active_rule_ids = set()

    graph = None
    full_walk = not args.paths
    if run_protocol:
        # snapshot comparison + the runs/ artifact only make sense for
        # the DEFAULT whole-tree walk: a partial graph from explicit
        # paths would always "drift" (and must not clobber the artifact)
        if full_walk:
            from fedml_tpu.analysis.protocol import check_protocol
            proto_findings, graph = check_protocol(
                ctxs, protocol_snapshot,
                artifact_path=root / "runs" / "protocol_graph.json",
                write_snapshot=args.write_protocol_graph)
        else:
            from fedml_tpu.analysis.lint import is_test_path
            from fedml_tpu.analysis.protocol import (conformance_findings,
                                                     extract_protocol)
            lib_ctxs = [c for c in ctxs if not is_test_path(c.relpath)]
            graph = extract_protocol(lib_ctxs)
            proto_findings = conformance_findings(graph, lib_ctxs)
        findings.extend(proto_findings)
        active_rule_ids |= {"FT201", "FT202", "FT203"}

    round_map = None
    if run_roundshape:
        from fedml_tpu.analysis import roundshape as rs
        if full_walk:
            # artifact + snapshot only make sense for the default walk
            # (a partial map would always "drift")
            rs_findings, round_map = rs.check_round_shapes(
                ctxs, round_map_snapshot,
                artifact_path=root / "runs" / "round_engine_map.json",
                write_snapshot=args.write_round_map)
            if args.write_round_map:
                print(f"wrote round-engine map snapshot "
                      f"({len(round_map['drivers'])} drivers) to "
                      f"{round_map_snapshot}")
        else:
            analysis = rs.analyze(ctxs)
            rs_findings = rs.conformance_findings(ctxs, analysis=analysis)
            round_map = rs.extract_round_shapes(ctxs, analysis=analysis)
        findings.extend(rs_findings)
        active_rule_ids |= {"FT301", "FT302", "FT303", "FT304"}

    shutdown_graph = None
    if run_lifecycle:
        from fedml_tpu.analysis import lifecycle as lc
        if full_walk:
            # artifact + snapshot only make sense for the default walk
            # (a partial graph would always "drift")
            lc_findings, shutdown_graph = lc.check_lifecycle(
                ctxs, shutdown_graph_snapshot,
                artifact_path=root / "runs" / "shutdown_graph.json",
                write_snapshot=args.write_shutdown_graph)
            if args.write_shutdown_graph:
                print(f"wrote shutdown-graph snapshot "
                      f"({len(shutdown_graph['classes'])} owner classes) "
                      f"to {shutdown_graph_snapshot}")
        else:
            lc_findings = []
            shutdown_graph = lc.extract_shutdown_graph(ctxs)
        findings.extend(lc_findings)
        active_rule_ids |= {"FT025"}

    flags_summary = None
    if run_flags:
        from fedml_tpu.analysis import flagsconf
        from fedml_tpu.analysis.lint import is_test_path
        lib_ctxs = [c for c in ctxs if not is_test_path(c.relpath)]
        extraction = flagsconf.extract_flags(lib_ctxs)
        findings.extend(flagsconf.conformance_findings(
            lib_ctxs, root=root, extraction=extraction))
        flags_summary = flagsconf.flags_report(lib_ctxs,
                                               extraction=extraction)
        active_rule_ids |= {"FT016"}

    audit_reports: List[dict] = []
    collective_stale: List[str] = []
    if run_audit_pass:
        # honor $JAX_PLATFORMS against environments whose sitecustomize
        # sets the platform programmatically (same belt-and-braces as
        # tests/conftest.py) — audit builders execute model init, and an
        # accidental tunnel-TPU dispatch turns 14 s of CI into minutes
        import os
        if os.environ.get("JAX_PLATFORMS"):
            import jax
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        from fedml_tpu.analysis.jaxpr_audit import (
            check_collective_baseline, run_audit,
            write_collective_baseline)
        audit_findings, audit_reports = run_audit()
        findings.extend(audit_findings)
        if args.write_collective_baseline:
            write_collective_baseline(collective_baseline, audit_reports)
            print(f"wrote collective baseline for {len(audit_reports)} "
                  f"entries to {collective_baseline}")
        else:
            coll_findings, collective_stale = check_collective_baseline(
                audit_reports, collective_baseline)
            findings.extend(coll_findings)

    pragma_warnings, pragma_findings = unused_pragmas(
        ctxs, active_rule_ids, strict=args.strict_pragmas)
    findings.extend(pragma_findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    stale: List[dict] = []
    suppressed = []
    entries: List[dict] = []
    if args.baseline is not None:
        entries = load_baseline(args.baseline)
        findings, suppressed, stale = apply_baseline(findings, entries)
        if changed_only:
            # entries for unscanned files cannot match anything — stale
            # reporting is only meaningful on a full walk
            stale = []

    if args.prune_stale:
        if args.baseline is None:
            print("--prune-stale needs a baseline (none found)",
                  file=sys.stderr)
            return 2
        stale_fps = {e["fingerprint"] for e in stale}
        kept = [e for e in entries if e["fingerprint"] not in stale_fps]
        args.baseline.write_text(json.dumps(
            {"version": 1, "entries": kept}, indent=2) + "\n")
        print(f"pruned {len(stale)} stale entr"
              f"{'y' if len(stale) == 1 else 'ies'} from {args.baseline} "
              f"({len(kept)} kept, notes preserved)")
        return 0

    if args.write_baseline is not None:
        # active AND currently-suppressed findings: refreshing an
        # existing baseline must not drop the entries that are still
        # live in the tree (they were filtered out of `findings` above)
        adopted = sorted(findings + suppressed,
                         key=lambda f: (f.path, f.line, f.rule))
        save_baseline(args.write_baseline, adopted, note="adopted",
                      notes_by_fingerprint={e["fingerprint"]: e.get("note", "")
                                            for e in entries})
        print(f"wrote {len(adopted)} entries to {args.write_baseline}")
        return 0

    report = {
        "findings": [f.to_json() for f in findings],
        "suppressed": [f.to_json() for f in suppressed],
        "stale_baseline": stale,
        "unused_pragmas": pragma_warnings,
        "audit": audit_reports,
        "collective_stale": collective_stale,
        "protocol": ({"types": len(graph["types"]),
                      "senders": sum(len(t["senders"])
                                     for t in graph["types"]),
                      "handlers": sum(len(t["handlers"])
                                      for t in graph["types"])}
                     if graph is not None else None),
        "roundshape": ({"drivers": len(round_map["drivers"]),
                        "kinds": {k: sum(1 for d in round_map["drivers"]
                                         if d["kind"] == k)
                                  for k in sorted({d["kind"] for d in
                                                   round_map["drivers"]})}}
                       if round_map is not None else None),
        "lifecycle": ({"classes": len(shutdown_graph["classes"]),
                       "workers": sum(len(c["workers"]) for c in
                                      shutdown_graph["classes"]),
                       "resources": sum(len(c["resources"]) for c in
                                        shutdown_graph["classes"])}
                      if shutdown_graph is not None else None),
        "flags": flags_summary,
        "counts": {"active": len(findings), "suppressed": len(suppressed),
                   "stale_baseline": len(stale),
                   "unused_pragmas": len(pragma_warnings)},
    }
    if args.output is not None:
        args.output.parent.mkdir(parents=True, exist_ok=True)
        args.output.write_text(json.dumps(report, indent=2) + "\n")

    if args.format == "json":
        print(json.dumps(report, indent=2))
    elif args.format == "github":
        _print_github(findings, stale, pragma_warnings)
        print(f"{len(findings)} active finding(s), "
              f"{len(suppressed)} baselined")
    else:
        for f in findings:
            print(f.format_text())
        for e in stale:
            print(f"WARNING: stale baseline entry {e['rule']} "
                  f"{e.get('path', '?')} ({e['fingerprint']}) matches "
                  "nothing — the code was fixed; remove the entry "
                  "(or run --prune-stale)")
        for w in pragma_warnings:
            print(f"WARNING: unused pragma {w['path']}:{w['line']} "
                  f"allow[{w['rule']}] suppresses nothing — delete it "
                  "(--strict-pragmas makes this a finding)")
        for name in collective_stale:
            print(f"WARNING: collective baseline entry {name} matches "
                  "no registered entry point — refresh with "
                  "--write-collective-baseline")
        if graph is not None:
            dest = (" -> runs/protocol_graph.json" if full_walk
                    else " (partial walk: no artifact/snapshot check)")
            print(f"protocol: {report['protocol']['types']} msg types, "
                  f"{report['protocol']['senders']} send site(s), "
                  f"{report['protocol']['handlers']} handler(s){dest}")
        if round_map is not None:
            dest = (" -> runs/round_engine_map.json" if full_walk
                    else " (partial walk: no artifact/snapshot check)")
            kinds = report["roundshape"]["kinds"]
            print(f"round-shape: {report['roundshape']['drivers']} "
                  f"driver(s) ("
                  + ", ".join(f"{v} {k}" for k, v in kinds.items())
                  + f"){dest}")
        if shutdown_graph is not None:
            dest = (" -> runs/shutdown_graph.json" if full_walk
                    else " (partial walk: no artifact/snapshot check)")
            print(f"lifecycle: {report['lifecycle']['classes']} owner "
                  f"class(es), {report['lifecycle']['workers']} "
                  f"worker(s), {report['lifecycle']['resources']} "
                  f"resource(s){dest}")
        if flags_summary is not None:
            print(f"flags: {flags_summary['flags_defined']} defined "
                  f"({flags_summary['flags_shared']} shared), "
                  f"{len(flags_summary['env_reads'])} documented-env "
                  "knob(s)")
        for rep in audit_reports:
            coll = ", ".join(
                f"{c['op']}{tuple(c['axes'])}x{c['count']}"
                for c in rep.get("collectives", [])) or "none"
            print(f"audit: {rep['entry']}: {rep['n_lowering_keys']} "
                  f"lowering key(s) over {rep['sweep_len']}-point sweep, "
                  f"{rep['n_eqns']} top-level eqns, collectives: {coll}")
        n = len(findings)
        print(f"{n} active finding(s), {len(suppressed)} baselined, "
              f"{len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}, "
              f"{len(pragma_warnings)} unused pragma(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except KeyboardInterrupt:  # pragma: no cover
        sys.exit(130)
    except Exception:  # the documented "internal error" exit: a crash
        import traceback  # (malformed baseline, unreadable output dir)
        traceback.print_exc()  # must be distinguishable from "findings"
        sys.exit(2)
