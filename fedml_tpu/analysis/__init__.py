"""fedml_tpu.analysis — JAX-aware static analysis for this codebase.

Two layers (ISSUE 3; in the spirit of XLA's HLO verifier, but aimed at
the hazards a TPU federated-learning stack actually ships):

- **AST lint** (:mod:`.lint`, :mod:`.rules`): project-specific rules
  FT001–FT006 over the source tree — thread-unsafe global RNG,
  donated-buffer reuse, hot-path host syncs, scalar jit signatures,
  swallowed exceptions, stray float64.
- **jaxpr audit** (:mod:`.jaxpr_audit`, :mod:`.registry`): traces the
  registered hot entry points and inspects the program itself — f64
  results, callbacks inside scan bodies, grad-path upcasts, lowering-
  key stability across a declared shape sweep.

CLI: ``python -m fedml_tpu.analysis --format text|json
[--baseline ci/analysis_baseline.json]`` — exit 0 iff every finding is
fixed, pragma'd (``# ft: allow[FTxxx]``), or baselined.
"""

from fedml_tpu.analysis.baseline import (apply_baseline, load_baseline,
                                         save_baseline)
from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import FileContext, Rule, lint_paths
from fedml_tpu.analysis.registry import AuditSpec, hot_entry_point

__all__ = ["Finding", "FileContext", "Rule", "lint_paths", "AuditSpec",
           "hot_entry_point", "apply_baseline", "load_baseline",
           "save_baseline"]
