"""FT020–FT025 — resource lifecycle, shutdown reachability, blocking hazards.

Five thread-bearing subsystems (comm writer threads, serve
rollout/coalescer workers, sched receive loops, the failover harness,
obs followers) kept growing the same bug class, found by hand each
time: ``launch_federation`` leaked its listening socket + worker
threads on a raise (EADDRINUSE on relaunch), the serve coalescer
deadlocked on a blocking put into its own full queue, ``submit()``
after ``close()`` blocked 30 s on a dead worker, ``rollout.drain()``
raced an in-flight swap. This module freezes the class out statically,
the way FT010/FT011 froze shared-state races:

- **FT020** thread-lifecycle — every ``Thread``/``Timer`` start site
  must be daemon'd or reachable from a close/stop/shutdown join path
  (interprocedural, one call level, reusing concurrency.py's per-class
  call graph). Local threads may instead join in-function or escape
  to a caller.
- **FT021** leak-on-raise — sockets/listeners/files/subprocesses (and
  same-module closable classes) acquired into a local with raising
  statements before the release and no ``finally``/context-manager
  protection. Init-assignment to a self-attr on a class with a
  close-ish method counts as escaped-to-owner (the owner's release
  edge is FT023's job); a self-attr on a class with NO close path is
  flagged here.
- **FT022** blocking-call-under-lock — lexical lock-hold dataflow
  (extending FT011's nested-``with`` walker, plus ``lk = self._lock``
  aliases and one same-class call level): ``queue.put/get`` without
  timeout, socket send/recv/accept, thread ``join()``, bare ``wait()``
  and device dispatch inside a held lock. Device gates
  (``*_device_lock``) and dedicated write-serialization locks
  (``_send_lock``/``_wlock``/``*_io_lock``) are exempt — serializing
  socket writers is what those locks are FOR.
- **FT023** shutdown-reachability — a class that starts a self-stored
  worker and defines a close path must set the worker's stop signal
  (closed flag, stop-Event ``set()``, queue sentinel, timer cancel, or
  tearing the socket the worker blocks on) on some path from close;
  every self-stored resource must be referenced from the close
  closure (the missing release edge is how the TCP listener leaked);
  and close must be idempotent (an unguarded ``X.shutdown()`` raises
  on the second call).
- **FT024** submit-after-close — public enqueue methods on classes
  whose close path sets a closed flag must read that flag before a
  blocking ``put`` (the 30 s-timeout-on-a-dead-worker shape).

Beyond the per-file rules, :func:`extract_shutdown_graph` emits the
whole-program **resource/shutdown graph** — every background worker
and owned resource with its teardown edges — to
``runs/shutdown_graph.json``, drift-checked against the line-free
fingerprinted snapshot ``ci/shutdown_graph.json`` (**FT025**: loud if
missing, drift finding otherwise; accept deliberate changes with
``--write-shutdown-graph``), exactly the FT200/FT204 pattern.

Scope: library code only (tests are single-threaded and short-lived
by construction; corpus paths are linted as library code). Sanctioned
sites carry ``# ft: allow[FT02x]`` pragmas with rationale — the
strict-pragma lane keeps them fresh.
"""

from __future__ import annotations

import ast
import hashlib
import json
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import (FileContext, Rule, dotted_name,
                                     is_test_path)
from fedml_tpu.analysis.classmodel import (_ClassModel, _lock_name,
                                           _self_attr)

GRAPH_VERSION = 1

#: method-name prefixes that mark a teardown path
_CLOSE_PREFIXES = ("close", "stop", "shutdown", "release", "terminate",
                   "finish", "quit", "dispose")

#: callee last-component -> resource kind (the acquirer set)
_ACQUIRE_KINDS = {
    "create_connection": "socket", "create_server": "socket",
    "socketpair": "socket", "Popen": "process",
}
#: ``open``/``socket`` only count when bare or from a stdlib fs/net
#: module — ``webbrowser.open`` or ``shelf.open`` must not register
_OPEN_PREFIXES = {"", "io", "os", "gzip", "bz2", "lzma", "codecs"}
_SOCKET_PREFIXES = {"", "socket"}

#: attr-name tokens that mark a closed/stop flag or event
_STOPPISH = ("stop", "clos", "shutdown", "done", "quit", "exit",
             "kill", "running", "alive", "active", "finished")
#: receiver-name tokens marking a queue-like hand-off object
def _queueish(name: str) -> bool:
    n = name.split(".")[-1].lower()
    return ("queue" in n or "box" in n or n.strip("_") == "q"
            or n.endswith("_q"))


def _threadish(name: str) -> bool:
    n = name.split(".")[-1].lower()
    return any(tok in n for tok in ("thread", "worker", "writer", "reader",
                                    "timer", "proc", "poller", "watcher",
                                    "pump"))


def _sockish(name: str) -> bool:
    n = name.split(".")[-1].lower()
    return any(tok in n for tok in ("sock", "conn", "server", "client",
                                    "peer", "fh", "file", "pipe"))


def _is_close_name(name: str) -> bool:
    return name in ("__exit__", "__del__") or \
        name.startswith(_CLOSE_PREFIXES)


def _stoppish(name: str) -> bool:
    n = name.split(".")[-1].lower()
    return any(tok in n for tok in _STOPPISH)


def _daemon_of(call: ast.Call) -> bool:
    """True when the ctor passes ``daemon=True`` (or a non-literal
    expression — we stay quiet rather than guess)."""
    for kw in call.keywords:
        if kw.arg == "daemon":
            if isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
            return True  # dynamic daemon-ness: not resolvable, stay quiet
    return False


def _worker_ctor(call: ast.Call) -> Optional[str]:
    """'thread' / 'timer' when ``call`` constructs one, else None."""
    name = dotted_name(call.func) or ""
    last = name.split(".")[-1]
    if last == "Thread":
        return "thread"
    if last == "Timer":
        return "timer"
    return None


def _acquire_kind(call: ast.Call,
                  closable_classes: Set[str]) -> Optional[str]:
    """Resource kind acquired by ``call``, or None."""
    name = dotted_name(call.func)
    if not name:
        return None
    parts = name.split(".")
    last = parts[-1]
    prefix = ".".join(parts[:-1])
    if last == "open" and prefix in _OPEN_PREFIXES:
        return "file"
    if last == "socket" and prefix in _SOCKET_PREFIXES:
        return "socket"
    if last in _ACQUIRE_KINDS:
        return _ACQUIRE_KINDS[last]
    if last in closable_classes:
        return "closable"
    if last.endswith("CommManager") or last.endswith("Endpoint"):
        # the framework's connection-owning classes: constructing one
        # binds a listening/outbound socket the creator must release
        return "endpoint"
    return None


def _closable_classes(tree: ast.Module) -> Set[str]:
    """Names of same-module classes that define a close-ish method —
    constructing one is acquiring a resource the creator must own."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for m in node.body:
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and _is_close_name(m.name):
                    out.add(node.name)
                    break
    return out


def _target_name(call: ast.Call, kind: str) -> Optional[str]:
    """The worker's entry callable as a short name (``self.M`` -> M)."""
    expr: Optional[ast.expr] = None
    if kind == "thread":
        for kw in call.keywords:
            if kw.arg == "target":
                expr = kw.value
    elif kind == "timer" and len(call.args) >= 2:
        expr = call.args[1]
    if expr is None:
        return None
    name = dotted_name(expr)
    if name and name.startswith("self.") and name.count(".") == 1:
        return name[len("self."):]
    if name and "." not in name:
        return name
    return None


class _Worker:
    __slots__ = ("kind", "attr", "local", "target", "line", "daemon",
                 "created_in", "node")

    def __init__(self, kind: str, attr: Optional[str], local: Optional[str],
                 target: Optional[str], line: int, daemon: bool,
                 created_in: str, node: ast.AST):
        self.kind = kind
        self.attr = attr          # self-attr it is stored to, or None
        self.local = local        # local name it is bound to, or None
        self.target = target
        self.line = line
        self.daemon = daemon
        self.created_in = created_in
        self.node = node


class _Resource:
    __slots__ = ("kind", "attr", "line", "created_in", "node")

    def __init__(self, kind: str, attr: str, line: int, created_in: str,
                 node: ast.AST):
        self.kind = kind
        self.attr = attr
        self.line = line
        self.created_in = created_in
        self.node = node


class _ClassLife:
    """Per-class lifecycle model: workers, owned resources, join/release
    sites, stop-signal writes, and the close-path closure — built on
    concurrency.py's per-class call graph."""

    def __init__(self, cls: ast.ClassDef, closable_classes: Set[str]):
        self.cls = cls
        self.model = _ClassModel(cls)
        self.workers: List[_Worker] = []
        self.resources: List[_Resource] = []
        #: attr -> methods that join()/cancel() it
        self.join_sites: Dict[str, Set[str]] = {}
        #: method -> human-readable stop-signal writes in its body
        self.stop_signals: Dict[str, List[str]] = {}
        #: method -> self-attrs it calls a release method on
        self.release_sites: Dict[str, Set[str]] = {}
        #: method -> unguarded ``X.shutdown()`` lines (idempotency)
        self.unguarded_shutdowns: Dict[str, List[int]] = {}
        self.close_methods = sorted(
            q for q in self.model.funcs
            if "." not in q and _is_close_name(q))
        self._closable = closable_classes
        self._collect()
        self.close_closure: Set[str] = set()
        for m in self.close_methods:
            self.close_closure |= self.model.closure({m})
        self.close_closure |= set(self.close_methods)

    # -- collection -------------------------------------------------------
    def _collect(self) -> None:
        for qual, fn in self.model.funcs.items():
            self._collect_func(qual, fn.node)
        self._apply_daemon_assigns()

    def _apply_daemon_assigns(self) -> None:
        """``t.daemon = True`` / ``self._t.daemon = True`` after the
        ctor daemonizes the worker just as surely as the kwarg."""
        daemon_locals: Set[Tuple[str, str]] = set()   # (qual, local)
        daemon_attrs: Set[str] = set()
        for qual, fn in self.model.funcs.items():
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Assign) or \
                        not isinstance(node.value, ast.Constant) or \
                        not node.value.value:
                    continue
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Attribute) or \
                            tgt.attr != "daemon":
                        continue
                    if isinstance(tgt.value, ast.Name):
                        daemon_locals.add((qual, tgt.value.id))
                    else:
                        attr = _self_attr(tgt.value)
                        if attr:
                            daemon_attrs.add(attr)
        for w in self.workers:
            if w.attr in daemon_attrs or \
                    (w.local and (w.created_in, w.local) in daemon_locals):
                w.daemon = True

    def _collect_func(self, qual: str, func: ast.AST) -> None:
        try_stack: List[ast.Try] = []

        def walk(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not func:
                return  # nested defs are their own _Func units
            if isinstance(node, ast.Try):
                try_stack.append(node)
                for child in ast.iter_child_nodes(node):
                    walk(child)
                try_stack.pop()
                return
            if isinstance(node, ast.Assign):
                self._on_assign(qual, node)
            elif isinstance(node, ast.Call):
                self._on_call(qual, node, in_try=bool(try_stack))
            for child in ast.iter_child_nodes(node):
                walk(child)

        walk(func)

    def _on_assign(self, qual: str, node: ast.Assign) -> None:
        if not isinstance(node.value, ast.Call):
            self._flag_assign(qual, node)
            return
        call = node.value
        kind = _worker_ctor(call)
        for tgt in node.targets:
            attr = _self_attr(tgt)
            sub_attr = None
            if isinstance(tgt, ast.Subscript):
                sub_attr = _self_attr(tgt.value)
            local = tgt.id if isinstance(tgt, ast.Name) else None
            if kind:
                self.workers.append(_Worker(
                    kind, attr, local, _target_name(call, kind),
                    node.lineno, _daemon_of(call), qual, node))
            else:
                rkind = _acquire_kind(call, self._closable)
                if rkind and (attr or sub_attr):
                    self.resources.append(_Resource(
                        rkind, attr or sub_attr, node.lineno, qual, node))

    def _flag_assign(self, qual: str, node: ast.Assign) -> None:
        """Record ``self._closed = True`` style stop-flag writes."""
        if not isinstance(node.value, ast.Constant) or \
                not isinstance(node.value.value, bool):
            return
        for tgt in node.targets:
            attr = _self_attr(tgt)
            if attr and _stoppish(attr):
                self.stop_signals.setdefault(qual, []).append(
                    f"{attr}={node.value.value}")

    def _on_call(self, qual: str, node: ast.Call, in_try: bool) -> None:
        if not isinstance(node.func, ast.Attribute):
            # anonymous ``Thread(...).start()`` never binds a name
            return
        attr_call = node.func.attr
        recv = node.func.value
        recv_attr = _self_attr(recv)
        if isinstance(recv, ast.Call):
            kind = _worker_ctor(recv)
            if kind and attr_call == "start":
                self.workers.append(_Worker(
                    kind, None, None, _target_name(recv, kind),
                    node.lineno, _daemon_of(recv), qual, node))
            return
        if recv_attr is None:
            return
        if attr_call in ("join", "cancel"):
            self.join_sites.setdefault(recv_attr, set()).add(qual)
            if _threadish(recv_attr) or _stoppish(recv_attr):
                self.stop_signals.setdefault(qual, []).append(
                    f"{recv_attr}.{attr_call}()")
        elif attr_call == "set" and _stoppish(recv_attr):
            self.stop_signals.setdefault(qual, []).append(
                f"{recv_attr}.set()")
        elif attr_call in ("put", "put_nowait") and _queueish(recv_attr):
            self.stop_signals.setdefault(qual, []).append(
                f"{recv_attr}.{attr_call}(<sentinel>)")
        elif attr_call in ("kill", "disconnect") or \
                attr_call.startswith(_CLOSE_PREFIXES):
            self.release_sites.setdefault(qual, set()).add(recv_attr)
            if _sockish(recv_attr) or attr_call.startswith(
                    _CLOSE_PREFIXES):
                # tearing the socket a reader blocks on IS its stop;
                # so is cascading teardown into an owned delegate
                # (router.stop -> physical.stop_receive_message)
                self.stop_signals.setdefault(qual, []).append(
                    f"{recv_attr}.{attr_call}()")
            if attr_call == "shutdown" and not in_try:
                self.unguarded_shutdowns.setdefault(qual, []).append(
                    node.lineno)

    # -- queries ----------------------------------------------------------
    def attr_in_close_path(self, attr: str) -> bool:
        for qual in self.close_closure:
            fn = self.model.funcs.get(qual)
            if fn and any(a.attr == attr for a in fn.accesses):
                return True
        return False

    def joined_from_close(self, attr: str) -> bool:
        return bool(self.join_sites.get(attr, set()) & self.close_closure)

    def close_stop_signals(self) -> List[str]:
        out: List[str] = []
        for qual in sorted(self.close_closure):
            out.extend(self.stop_signals.get(qual, []))
        return out


def _life(ctx: FileContext, cls: ast.ClassDef) -> _ClassLife:
    cache = ctx.__dict__.setdefault("_lifecycle_models", {})
    key = id(cls)
    if key not in cache:
        cache[key] = _ClassLife(cls, _closable_classes(ctx.tree))
    return cache[key]


def _gate(ctx: FileContext, *tokens: str) -> bool:
    """Textual pre-gate keeping the ``--changed-only`` lane cheap: a
    file that never mentions the construct cannot violate the rule."""
    return any(tok in ctx.source for tok in tokens)


# -- FT020 --------------------------------------------------------------------

class ThreadLifecycleRule(Rule):
    id = "FT020"
    title = ("non-daemon Thread/Timer with no join/cancel path from "
             "close/stop/shutdown (orphaned worker outlives its owner)")
    hint = ("pass daemon=True, join/cancel the worker from the owner's "
            "close path, or pragma a deliberately process-lifetime "
            "thread: # ft: allow[FT020] why")

    def applies(self, relpath: str) -> bool:
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _gate(ctx, "Thread(", "Timer("):
            return
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(ctx, cls)
        yield from self._check_module_funcs(ctx)

    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        life = _life(ctx, cls)
        for w in life.workers:
            if w.daemon:
                continue
            if w.attr is not None:
                if life.joined_from_close(w.attr):
                    continue
                where = (f"join/cancel self.{w.attr} from "
                         f"{', '.join(life.close_methods) or 'a close()'}"
                         )
                yield ctx.finding(
                    self, w.node,
                    f"{cls.name}.{w.created_in} starts non-daemon "
                    f"{w.kind} self.{w.attr} but no close/stop/shutdown "
                    f"path ever joins or cancels it — the worker "
                    "outlives its owner and pins interpreter exit "
                    f"({where})")
            elif w.local is not None:
                if self._local_ok(life.model.funcs[w.created_in].node,
                                  w.local):
                    continue
                yield ctx.finding(
                    self, w.node,
                    f"{cls.name}.{w.created_in} starts non-daemon "
                    f"{w.kind} {w.local!r} that is neither joined here "
                    "nor handed to a caller — nothing can ever tear "
                    "it down")
            else:
                yield ctx.finding(
                    self, w.node,
                    f"{cls.name}.{w.created_in} starts an anonymous "
                    f"non-daemon {w.kind} — unjoinable by construction")

    def _check_module_funcs(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.tree.body:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign) or \
                        not isinstance(sub.value, ast.Call):
                    continue
                kind = _worker_ctor(sub.value)
                if not kind or _daemon_of(sub.value):
                    continue
                local = next((t.id for t in sub.targets
                              if isinstance(t, ast.Name)), None)
                if local is None:
                    continue  # stored elsewhere: escapes
                if self._local_ok(node, local):
                    continue
                yield ctx.finding(
                    self, sub,
                    f"{node.name}() starts non-daemon {kind} {local!r} "
                    "that is neither joined in this function nor "
                    "returned/stored — it leaks past every caller")

    @staticmethod
    def _local_ok(func: ast.AST, local: str) -> bool:
        """Joined/cancelled in-function, or escapes to the caller."""
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("join", "cancel") and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == local:
                    return True
                for arg in list(node.args) + [k.value for k in
                                              node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id == local:
                        return True  # handed off (append/register/...)
            elif isinstance(node, (ast.Return, ast.Yield)) and \
                    node.value is not None:
                for n in ast.walk(node.value):
                    if isinstance(n, ast.Name) and n.id == local:
                        return True
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            tgt.attr == "daemon" and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == local and \
                            isinstance(node.value, ast.Constant) and \
                            node.value.value:
                        return True  # t.daemon = True after the ctor
                if isinstance(node.value, ast.Name) and \
                        node.value.id == local:
                    return True  # aliased/stored (self.x = t, d[k] = t)
                for n in ast.walk(node.value):
                    if isinstance(n, ast.Name) and n.id == local and \
                            not isinstance(node.value, ast.Call):
                        return True  # packed into a container literal
        return False


# -- FT021 --------------------------------------------------------------------

class LeakOnRaiseRule(Rule):
    id = "FT021"
    title = ("resource acquired then lost on a raising path (no "
             "finally/with release) or owned by a class with no close "
             "path — the EADDRINUSE-on-relaunch shape")
    hint = ("wrap the acquisition in try/finally or a with block, close "
            "before the raising call, or give the owning class a "
            "close() that releases it")

    def applies(self, relpath: str) -> bool:
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _gate(ctx, "open(", "socket", "Popen(", "def close"):
            return
        closable = _closable_classes(ctx.tree)
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            life = _life(ctx, cls)
            if life.close_methods:
                continue  # escaped-to-owner: release edges are FT023's
            for res in life.resources:
                yield ctx.finding(
                    self, res.node,
                    f"{cls.name} acquires {res.kind} self.{res.attr} "
                    "but defines no close/stop/shutdown method — the "
                    "handle can never be released and leaks for the "
                    "process lifetime (add a close() and call it from "
                    "the owner's teardown)")
        for func, in_class in self._functions(ctx.tree):
            yield from self._check_locals(ctx, func, closable, in_class)

    @staticmethod
    def _functions(tree: ast.Module):
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node, False

    def _check_locals(self, ctx: FileContext, func: ast.AST,
                      closable: Set[str],
                      in_class: bool) -> Iterator[Finding]:
        # straight-line scan per statement block: an acquisition into a
        # local must be protected (try/finally, with, or immediate
        # escape) before the next raise-capable statement
        for block in self._blocks(func):
            yield from self._scan_block(ctx, func, block, closable)

    @staticmethod
    def _blocks(func: ast.AST) -> List[List[ast.stmt]]:
        """Statement lists of ``func`` NOT under a Try (a surrounding
        try is assumed to release in its handler/finally) and not
        inside nested defs."""
        out: List[List[ast.stmt]] = []

        def walk(stmts: List[ast.stmt], protected: bool) -> None:
            if not protected:
                out.append(stmts)
            for s in stmts:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue
                if isinstance(s, ast.Try):
                    walk(s.body, True)
                    for h in s.handlers:
                        walk(h.body, protected)
                    walk(s.orelse, protected)
                    walk(s.finalbody, protected)
                elif isinstance(s, (ast.If,)):
                    walk(s.body, protected)
                    walk(s.orelse, protected)
                elif isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
                    walk(s.body, protected)
                    walk(s.orelse, protected)
                elif isinstance(s, (ast.With, ast.AsyncWith)):
                    walk(s.body, protected)

        walk(getattr(func, "body", []), False)
        return out

    def _scan_block(self, ctx: FileContext, func: ast.AST,
                    stmts: List[ast.stmt],
                    closable: Set[str]) -> Iterator[Finding]:
        for i, stmt in enumerate(stmts):
            if not isinstance(stmt, ast.Assign) or \
                    not isinstance(stmt.value, ast.Call):
                continue
            kind = _acquire_kind(stmt.value, closable)
            if kind is None:
                continue
            local = next((t.id for t in stmt.targets
                          if isinstance(t, ast.Name)), None)
            if local is None:
                continue  # self-attr case handled per class above
            verdict = self._follow(stmts[i + 1:], local)
            if verdict is not None:
                yield ctx.finding(
                    self, stmt,
                    f"{getattr(func, 'name', '<fn>')}() acquires {kind} "
                    f"{local!r} and {verdict} — a raise in between "
                    "leaks the handle (EADDRINUSE / fd exhaustion on "
                    "the relaunch path); release it in a finally or a "
                    "with block")

    @staticmethod
    def _follow(rest: List[ast.stmt], local: str) -> Optional[str]:
        """None when the local is safely released/escaped; otherwise a
        description of the unprotected window."""
        def mentions(node: ast.AST) -> bool:
            return any(isinstance(n, ast.Name) and n.id == local
                       for n in ast.walk(node))

        def stored_away(node: ast.AST) -> bool:
            """Handed to a container/registry METHOD (x.append(local),
            registry.register(local)) — a plain function call taking the
            local as an argument does NOT transfer ownership (Popen can
            raise without adopting the handle)."""
            for n in ast.walk(node):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute):
                    for arg in list(n.args) + [k.value for k in
                                               n.keywords]:
                        if isinstance(arg, ast.Name) and arg.id == local:
                            return True
            return False

        def aliased(value: ast.expr) -> bool:
            if isinstance(value, ast.Name) and value.id == local:
                return True
            if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
                return any(isinstance(e, ast.Name) and e.id == local
                           for e in value.elts)
            if isinstance(value, ast.Dict):
                return any(isinstance(e, ast.Name) and e.id == local
                           for e in value.values)
            return False

        def releases(node: ast.AST) -> bool:
            for n in ast.walk(node):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        isinstance(n.func.value, ast.Name) and \
                        n.func.value.id == local and \
                        (n.func.attr in ("kill",)
                         or n.func.attr.startswith(_CLOSE_PREFIXES)):
                    return True
            return False

        raised = False
        for stmt in rest:
            # escape: returned, yielded, aliased/stored, or handed to a
            # container method — ownership moves before a raise can
            # strand the handle
            if isinstance(stmt, ast.Return) and stmt.value is not None \
                    and mentions(stmt.value):
                return None
            if isinstance(stmt, ast.Assign) and aliased(stmt.value):
                return None
            if stored_away(stmt):
                return None
            if isinstance(stmt, ast.Try):
                # a try immediately after the acquisition that releases
                # the local in a handler or finally is the sanctioned
                # pattern
                for part in ([h for h in stmt.handlers]
                             + [stmt]):
                    body = part.finalbody if part is stmt else part.body
                    if any(releases(s) for s in body):
                        return None
                raised = True  # try body can raise past the handlers
                continue
            # release on the straight line
            if releases(stmt):
                return ("releases it only after raise-capable "
                        "statements with no try/finally"
                        if raised else None)
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if mentions(item.context_expr):
                        return None  # managed from here on
            # raise-capable?
            if isinstance(stmt, ast.Raise):
                return "raises before releasing it"
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call):
                    raised = True
                    break
        if raised:
            return ("never releases it on this path (and raise-capable "
                    "statements follow the acquisition)")
        return None


# -- FT022 --------------------------------------------------------------------

#: a held lock whose last component contains one of these is exempt —
#: device gates serialize dispatch on purpose; send/write locks exist
#: to serialize exactly the socket writes FT022 would flag; writer/
#: flusher locks belong to dedicated writer threads (the async
#: checkpoint/flush pattern) whose entire job is to hold the I/O
_EXEMPT_LOCK_TOKENS = ("device", "gate", "send", "write", "wlock", "io",
                       "writer", "flusher")

_SOCKET_BLOCKERS = frozenset({"sendall", "recv", "recv_into", "accept",
                              "create_connection"})
_DEVICE_BLOCKERS = frozenset({"device_put", "block_until_ready"})


def _lock_exempt(name: str) -> bool:
    last = name.split(".")[-1].lower()
    return any(tok in last for tok in _EXEMPT_LOCK_TOKENS)


def _blocking_site(node: ast.Call) -> Optional[str]:
    """A human-readable description when ``node`` can block
    indefinitely, else None."""
    callee = dotted_name(node.func) or ""
    last = callee.split(".")[-1]
    if last in _DEVICE_BLOCKERS:
        return f"device dispatch {last}()"
    if callee in ("os.fsync", "fsync"):
        # a disk barrier is a blocking device wait in disguise: ms on an
        # idle SSD, unbounded on a contended one — round/receive threads
        # must hand durability to a writer thread (exempt tokens above)
        # or batch it (group commit), never hold a shared lock across it
        return "durable os.fsync()"
    if not isinstance(node.func, ast.Attribute):
        return None
    recv = node.func.value
    recv_name = dotted_name(recv) or ""
    attr = node.func.attr
    kwargs = {k.arg for k in node.keywords}
    has_timeout = "timeout" in kwargs or any(
        k.arg == "block" and isinstance(k.value, ast.Constant)
        and k.value.value is False for k in node.keywords)
    if attr in ("put", "get") and _queueish(recv_name):
        if not has_timeout and len(node.args) < (2 if attr == "put" else 1):
            return f"blocking {recv_name}.{attr}() with no timeout"
    if attr in _SOCKET_BLOCKERS and not isinstance(recv, ast.Constant) \
            and "timeout" not in kwargs:
        return f"socket {recv_name or '<expr>'}.{attr}()"
    if attr == "join" and not node.args and not kwargs and \
            _threadish(recv_name):
        return f"unbounded {recv_name}.join()"
    if attr == "wait" and not node.args and not has_timeout and \
            recv_name and not _lock_name(recv) and \
            not isinstance(recv, ast.Constant):
        return f"unbounded {recv_name}.wait()"
    return None


class _HoldScan(ast.NodeVisitor):
    """Lock-hold dataflow for one function body: lexical ``with``
    nesting plus ``lk = self._lock`` aliases. Records (lock, site,
    node) blocking triples and (lock, callee, node) call edges."""

    def __init__(self, root: ast.AST):
        self.root = root
        self.lock_stack: List[str] = []
        self.aliases: Dict[str, str] = {}
        self.blocked: List[Tuple[str, str, ast.AST]] = []
        self.calls_under: List[Tuple[str, str, ast.AST]] = []

    def _lockname(self, expr: ast.expr) -> Optional[str]:
        name = _lock_name(expr)
        if name:
            return name
        if isinstance(expr, ast.Name) and expr.id in self.aliases:
            return self.aliases[expr.id]
        return None

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is self.root:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign) -> None:
        value_lock = _lock_name(node.value) if not isinstance(
            node.value, ast.Call) else None
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and value_lock:
                self.aliases[tgt.id] = value_lock
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        taken = [ln for item in node.items
                 if (ln := self._lockname(item.context_expr))]
        self.lock_stack.extend(taken)
        for stmt in node.body:
            self.visit(stmt)
        for _ in taken:
            self.lock_stack.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call) -> None:
        # innermost-lock semantics: device/send/write work placed under
        # its own dedicated gate is sanctioned even when an outer
        # coarse lock is also held (the endpoint's swap-then-device
        # nesting) — the outer lock is then that gate's client
        if self.lock_stack and not _lock_exempt(self.lock_stack[-1]):
            lock = self.lock_stack[-1]
            site = _blocking_site(node)
            if site:
                self.blocked.append((lock, site, node))
            callee = dotted_name(node.func) or ""
            if callee.startswith("self.") and callee.count(".") == 1:
                self.calls_under.append(
                    (lock, callee[len("self."):], node))
        self.generic_visit(node)


class BlockingUnderLockRule(Rule):
    id = "FT022"
    title = ("blocking call (queue put/get, socket send/recv, join, "
             "device dispatch, fsync) while holding a lock — every "
             "other path needing that lock stalls behind it")
    hint = ("move the blocking call outside the with block (snapshot "
            "under the lock, block outside), add a timeout, or pragma "
            "a deliberate serialization point: # ft: allow[FT022] why")

    def applies(self, relpath: str) -> bool:
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _gate(ctx, "Lock", "lock", "Condition", "mutex"):
            return
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(ctx, cls)
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan = _HoldScan(node)
                scan.visit(node)
                yield from self._emit(ctx, node.name, scan, None)

    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        life = _life(ctx, cls)
        scans: Dict[str, _HoldScan] = {}
        for qual, fn in life.model.funcs.items():
            scan = _HoldScan(fn.node)
            scan.visit(fn.node)
            scans[qual] = scan
        for qual, scan in scans.items():
            yield from self._emit(ctx, f"{cls.name}.{qual}", scan, None)
            # one call level: a held lock survives into same-class calls
            for lock, callee, node in scan.calls_under:
                res = life.model._resolve(qual, callee)
                if res is None or res not in scans:
                    continue
                for _, site, _n in self._bare_sites(scans[res]):
                    yield from self._one(
                        ctx, node, lock,
                        f"{site} (inside self.{callee}(), called here "
                        f"while {lock} is held)")
                    break  # one finding per call edge is enough

    @staticmethod
    def _bare_sites(scan: _HoldScan):
        """Blocking sites in a callee that run under the CALLER's lock:
        everything not already attributed to a lock of its own."""
        seen_nodes = {id(n) for _, _, n in scan.blocked}
        out = []
        for node in ast.walk(scan.root):
            if isinstance(node, ast.Call) and id(node) not in seen_nodes:
                site = _blocking_site(node)
                if site:
                    out.append((None, site, node))
        return out

    def _emit(self, ctx: FileContext, where: str, scan: _HoldScan,
              _unused) -> Iterator[Finding]:
        for lock, site, node in scan.blocked:
            yield from self._one(ctx, node, lock, site, where)

    def _one(self, ctx: FileContext, node: ast.AST, lock: str,
             site: str, where: str = "") -> Iterator[Finding]:
        prefix = f"{where}: " if where else ""
        yield ctx.finding(
            self, node,
            f"{prefix}{site} while holding {lock} — every thread "
            "needing this lock (heartbeats, counters, the close path) "
            "stalls behind a peer/device that may never answer; the "
            "serve-tier deadlock was exactly this shape")


# -- FT023 --------------------------------------------------------------------

class ShutdownReachabilityRule(Rule):
    id = "FT023"
    title = ("close() path missing a teardown edge: started worker "
             "with no stop signal, owned resource never released, or "
             "non-idempotent close (unguarded shutdown())")
    hint = ("set the worker's stop flag/Event/sentinel from close, "
            "release every owned handle there, and guard "
            "sock.shutdown() with try/except OSError so a second "
            "close is a no-op")

    def applies(self, relpath: str) -> bool:
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _gate(ctx, "Thread(", "Timer(", "def close", "def stop",
                     "def shutdown"):
            return
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            life = _life(ctx, cls)
            if not life.close_methods:
                continue
            signals = life.close_stop_signals()
            for w in life.workers:
                if w.attr is None:
                    continue
                if signals or life.joined_from_close(w.attr):
                    continue
                yield ctx.finding(
                    self, w.node,
                    f"{cls.name} starts {w.kind} self.{w.attr} but "
                    f"{'/'.join(life.close_methods)} sets no stop "
                    "signal (no closed flag, stop-Event set(), queue "
                    "sentinel, cancel, or socket teardown) — the "
                    "worker never learns the owner is gone and spins "
                    "until process exit")
            for res in life.resources:
                if life.attr_in_close_path(res.attr):
                    continue
                yield ctx.finding(
                    self, res.node,
                    f"{cls.name} acquires {res.kind} self.{res.attr} "
                    f"but the close path "
                    f"({'/'.join(life.close_methods)}) never touches "
                    "it — the handle outlives the owner (the leaked "
                    "TCP listener / EADDRINUSE shape)")
            for qual in life.close_methods:
                for line in life.unguarded_shutdowns.get(qual, []):
                    snippet = (ctx.lines[line - 1].strip()
                               if 0 < line <= len(ctx.lines) else "")
                    f = Finding(
                        rule=self.id, path=ctx.relpath, line=line,
                        message=f"{cls.name}.{qual} calls shutdown() "
                                "outside try/except — socket.shutdown "
                                "raises OSError on an already-closed "
                                "socket, so the second close() crashes "
                                "instead of no-opping (close must be "
                                "idempotent)",
                        hint=self.hint, snippet=snippet)
                    yield f


# -- FT024 --------------------------------------------------------------------

class SubmitAfterCloseRule(Rule):
    id = "FT024"
    title = ("public enqueue method does not check the closed flag "
             "before a blocking put — submit() after close() parks the "
             "caller on a dead worker")
    hint = ("read the closed flag (or stop-Event) first and shed "
            "immediately; the worker that would drain the queue is "
            "gone")

    def applies(self, relpath: str) -> bool:
        return not is_test_path(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _gate(ctx, ".put("):
            return
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            life = _life(ctx, cls)
            if not life.close_methods:
                continue
            flags = self._close_flags(life)
            if not flags:
                continue
            for qual, fn in life.model.funcs.items():
                if "." in qual or qual.startswith("_") or \
                        _is_close_name(qual):
                    continue
                reads = {a.attr for a in fn.accesses if not a.is_write}
                if reads & flags:
                    continue
                for node in ast.walk(fn.node):
                    if not isinstance(node, ast.Call) or \
                            not isinstance(node.func, ast.Attribute):
                        continue
                    if node.func.attr != "put":
                        continue
                    recv = _self_attr(node.func.value)
                    if recv is None or not _queueish(recv):
                        continue
                    yield ctx.finding(
                        self, node,
                        f"{cls.name}.{qual}() blocks on "
                        f"self.{recv}.put() without reading the closed "
                        f"flag ({'/'.join(sorted(flags))}) first — "
                        "after close() nothing drains the queue and "
                        "the caller parks for the full timeout (the "
                        "30 s dead-worker hang)")

    @staticmethod
    def _close_flags(life: _ClassLife) -> Set[str]:
        """Attrs the close path uses as its closed signal (flag assigns
        and stop-Event sets — the signals a submit CAN check)."""
        flags: Set[str] = set()
        for qual in life.close_methods:
            for sig in life.stop_signals.get(qual, []):
                name = sig.split("=")[0].split(".")[0]
                if _stoppish(name) and ("=" in sig or ".set()" in sig):
                    flags.add(name)
        return flags


# -- shutdown graph (FT025) ---------------------------------------------------

_FT025_HINT = ("review the worker/resource change, then "
               "--write-shutdown-graph")


def _module_of(relpath: str) -> str:
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    return p.replace("/", ".")


def extract_shutdown_graph(ctxs: Sequence[FileContext]) -> Dict:
    """-> the line-bearing resource/shutdown graph over every library
    class that owns a worker or a resource (the ``runs/`` artifact):
    the reviewer's shutdown map, one entry per owner."""
    classes: List[Dict] = []
    for ctx in ctxs:
        if is_test_path(ctx.relpath):
            continue
        if not _gate(ctx, "Thread(", "Timer(", "socket", "open(",
                     "Popen("):
            continue
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            life = _life(ctx, cls)
            if not life.workers and not life.resources:
                continue
            workers = []
            for w in sorted(life.workers, key=lambda w: w.line):
                workers.append({
                    "kind": w.kind,
                    "attr": w.attr,
                    "target": w.target,
                    "daemon": w.daemon,
                    "created_in": w.created_in,
                    "line": w.line,
                    "joined_in": sorted(life.join_sites.get(w.attr, ()))
                    if w.attr else [],
                })
            resources = []
            for r in sorted(life.resources, key=lambda r: r.line):
                resources.append({
                    "kind": r.kind,
                    "attr": r.attr,
                    "created_in": r.created_in,
                    "line": r.line,
                    "released_in": sorted(
                        q for q, attrs in life.release_sites.items()
                        if r.attr in attrs),
                })
            classes.append({
                "class": cls.name,
                "module": _module_of(ctx.relpath),
                "path": ctx.relpath,
                "workers": workers,
                "resources": resources,
                "close_methods": life.close_methods,
                "stop_signals": sorted(set(life.close_stop_signals())),
            })
    classes.sort(key=lambda c: (c["module"], c["class"]))
    return {"version": GRAPH_VERSION, "classes": classes}


def normalize_graph(graph: Dict) -> Dict:
    """Line-free, path-free shape for the checked-in snapshot."""
    classes = []
    for c in graph["classes"]:
        classes.append({
            "class": c["class"],
            "module": c["module"],
            "workers": [{k: v for k, v in w.items() if k != "line"}
                        for w in c["workers"]],
            "resources": [{k: v for k, v in r.items() if k != "line"}
                          for r in c["resources"]],
            "close_methods": c["close_methods"],
            "stop_signals": c["stop_signals"],
        })
    payload = {"version": GRAPH_VERSION,
               "classes": sorted(classes,
                                 key=lambda c: (c["module"], c["class"]))}
    blob = json.dumps(payload, sort_keys=True)
    payload["fingerprint"] = hashlib.sha1(blob.encode()).hexdigest()[:16]
    return payload


def snapshot_findings(graph: Dict, snapshot_path: Path) -> List[Finding]:
    norm = normalize_graph(graph)
    path = Path(snapshot_path)
    if not path.exists():
        return [Finding(
            rule="FT025", path=str(snapshot_path), line=0,
            message="shutdown-graph snapshot is MISSING — worker/"
                    "resource teardown edges cannot drift-check, and a "
                    "silently skipped check is the failure mode this "
                    "pass exists to prevent",
            hint=_FT025_HINT)]
    try:
        old = json.loads(path.read_text())
    except (OSError, ValueError) as exc:
        return [Finding(
            rule="FT025", path=str(snapshot_path), line=0,
            message=f"shutdown-graph snapshot is unreadable ({exc}) — "
                    "regenerate it",
            hint=_FT025_HINT)]
    if old.get("fingerprint") == norm["fingerprint"]:
        return []
    key = lambda c: f"{c['module']}.{c['class']}"  # noqa: E731
    old_c = {key(c): c for c in old.get("classes", [])}
    new_c = {key(c): c for c in norm["classes"]}
    changes: List[str] = []
    for k in sorted(set(new_c) - set(old_c)):
        changes.append(f"new owner {k}")
    for k in sorted(set(old_c) - set(new_c)):
        changes.append(f"removed owner {k}")
    for k in sorted(set(old_c) & set(new_c)):
        if old_c[k] != new_c[k]:
            diff = [part for part in ("workers", "resources",
                                      "close_methods", "stop_signals")
                    if old_c[k].get(part) != new_c[k].get(part)]
            changes.append(f"{k}: {'/'.join(diff) or 'shape'} changed")
    detail = "; ".join(changes) or "graph fingerprint changed"
    return [Finding(
        rule="FT025", path=str(snapshot_path), line=0,
        message="shutdown graph drifted from the checked-in snapshot: "
                f"{detail}",
        hint=_FT025_HINT)]


def write_graph(graph: Dict, artifact_path: Path,
                snapshot_path: Optional[Path] = None) -> None:
    artifact_path = Path(artifact_path)
    artifact_path.parent.mkdir(parents=True, exist_ok=True)
    artifact_path.write_text(json.dumps(graph, indent=2, sort_keys=True)
                             + "\n")
    if snapshot_path is not None:
        snapshot_path = Path(snapshot_path)
        snapshot_path.parent.mkdir(parents=True, exist_ok=True)
        snapshot_path.write_text(
            json.dumps(normalize_graph(graph), indent=2, sort_keys=True)
            + "\n")


def check_lifecycle(ctxs: Sequence[FileContext], snapshot_path: Path,
                    artifact_path: Optional[Path] = None,
                    write_snapshot: bool = False
                    ) -> Tuple[List[Finding], Dict]:
    """The CLI entry for the whole-program half: extract the shutdown
    graph, emit the artifact, snapshot-check (FT020–FT024 are per-file
    Rules and run in the lint pass). ``write_snapshot`` refreshes
    instead of comparing — a snapshot never launders a rule finding,
    only the graph shape."""
    graph = extract_shutdown_graph(ctxs)
    if artifact_path is not None:
        write_graph(graph, artifact_path)
    findings: List[Finding] = []
    if write_snapshot:
        snapshot_path = Path(snapshot_path)
        snapshot_path.parent.mkdir(parents=True, exist_ok=True)
        snapshot_path.write_text(
            json.dumps(normalize_graph(graph), indent=2, sort_keys=True)
            + "\n")
    else:
        findings.extend(snapshot_findings(graph, snapshot_path))
    return findings, graph
