"""FT016 — flag/env conformance over the whole tree.

The shared arg set (``experiments/args.py``) is the launchers' single
config surface, and ``$FEDML_TPU_*`` env vars are its out-of-band
overrides — but nothing checked that surface against reality: a flag
nobody reads is dead weight that silently no-ops a launch, an env knob
nobody documents is tribal knowledge, and a shared-arg-set flag missing
from the README table is invisible to users. This pass extracts, from
ONE parse of the tree (the same contexts every other pass shares):

- **flag definitions**: every ``parser.add_argument("--name", ...)``
  call with a literal flag string, tagged with whether it lives in the
  SHARED arg set (a module named ``args.py``) or is launcher-local;
- **flag reads**: every attribute access ``<expr>.name`` and every
  ``getattr(x, "name")`` with a literal string — line breaks and
  default-carrying getattr chains resolve naturally through the AST
  (a regex would miss ``getattr(\\n    args, "name", None)``);
- **env reads**: ``os.environ.get(X)`` / ``os.environ[X]`` /
  ``os.getenv(X)`` where X is a string literal or a module-level
  string constant (the tree's ``ENV_VAR = "FEDML_TPU_..."`` idiom).

Findings (all FT016, pragma-able at the definition/read line):

- a flag defined anywhere but read nowhere in the tree (dead flag);
- a SHARED-arg-set flag absent from the README flag table
  (``--name`` must appear literally in ``README.md``);
- a ``FEDML_TPU_*`` env read whose variable name does not appear in
  ``README.md`` (undocumented knob).

README-dependent checks are skipped when the analysis root has no
``README.md`` (throwaway test dirs); the dead-flag check always runs.
Whole-program by construction: skipped under ``--changed-only``.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import FileContext, dotted_name

RULE_ID = "FT016"

_HINT = ("delete the dead flag (or wire it into a launcher/driver), add "
         "the --flag row to the README flag table, or document the "
         "$FEDML_TPU_* variable in README.md; deliberate exceptions "
         "carry # ft: allow[FT016] why")

ENV_PREFIX = "FEDML_TPU_"


class _FlagDef:
    __slots__ = ("name", "ctx", "line", "shared")

    def __init__(self, name: str, ctx: FileContext, line: int,
                 shared: bool):
        self.name = name
        self.ctx = ctx
        self.line = line
        self.shared = shared


class _EnvRead:
    __slots__ = ("var", "ctx", "line")

    def __init__(self, var: str, ctx: FileContext, line: int):
        self.var = var
        self.ctx = ctx
        self.line = line


def _is_shared_argset(relpath: str) -> bool:
    return Path(relpath).name == "args.py"


def _module_str_consts(tree: ast.AST) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _env_key(node: ast.expr, consts: Dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def extract_flags(ctxs: Sequence[FileContext]
                  ) -> Tuple[List[_FlagDef], Set[str], List[_EnvRead]]:
    """-> (flag definitions, attribute/getattr read names, env reads)."""
    defs: List[_FlagDef] = []
    reads: Set[str] = set()
    env_reads: List[_EnvRead] = []
    for ctx in ctxs:
        consts = _module_str_consts(ctx.tree)
        shared = _is_shared_argset(ctx.relpath)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                # Load contexts only: a STORE (``self.name = ...``) is
                # not evidence anyone consumes the flag — a dead flag
                # mirrored into a config field must still be caught
                if isinstance(node.ctx, ast.Load):
                    reads.add(node.attr)
                continue
            if isinstance(node, ast.Subscript) \
                    and dotted_name(node.value) == "os.environ":
                var = _env_key(node.slice, consts)
                if var:
                    env_reads.append(_EnvRead(var, ctx, node.lineno))
                continue
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            last = name.split(".")[-1]
            if last == "add_argument" and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str) \
                    and node.args[0].value.startswith("--"):
                flag = node.args[0].value.lstrip("-").replace("-", "_")
                # an explicit dest= overrides the derived attribute name
                for kw in node.keywords:
                    if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                        flag = str(kw.value.value)
                defs.append(_FlagDef(flag, ctx, node.lineno, shared))
            elif last == "getattr" and len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                reads.add(node.args[1].value)
            elif name in ("os.environ.get", "os.getenv") and node.args:
                var = _env_key(node.args[0], consts)
                if var:
                    env_reads.append(_EnvRead(var, ctx, node.lineno))
    return defs, reads, env_reads


def flags_report(ctxs: Sequence[FileContext],
                 extraction: Optional[Tuple] = None) -> Dict:
    """Summary dict for the JSON report / runs artifact."""
    defs, reads, env_reads = extraction or extract_flags(ctxs)
    return {
        "flags_defined": len(defs),
        "flags_shared": sum(1 for d in defs if d.shared),
        "env_reads": sorted({e.var for e in env_reads
                             if e.var.startswith(ENV_PREFIX)}),
    }


def conformance_findings(ctxs: Sequence[FileContext],
                         root: Optional[Path] = None,
                         extraction: Optional[Tuple] = None
                         ) -> List[Finding]:
    """FT016 findings over the shared contexts (pragma suppression via
    each originating context, like every pass). ``extraction`` shares
    one :func:`extract_flags` result with :func:`flags_report`."""
    defs, reads, env_reads = extraction or extract_flags(ctxs)
    readme_text: Optional[str] = None
    if root is not None:
        readme = Path(root) / "README.md"
        if readme.is_file():
            readme_text = readme.read_text()

    findings: List[Finding] = []

    def emit(ctx: FileContext, line: int, message: str) -> None:
        if ctx.allowed(RULE_ID, line):
            return
        snippet = (ctx.lines[line - 1].strip()
                   if 0 < line <= len(ctx.lines) else "")
        findings.append(Finding(rule=RULE_ID, path=ctx.relpath, line=line,
                                message=message, hint=_HINT,
                                snippet=snippet))

    for d in defs:
        if d.name not in reads:
            where = "shared arg set" if d.shared else "launcher"
            emit(d.ctx, d.line,
                 f"flag --{d.name} is defined in the {where} but read "
                 "nowhere in the tree — a dead flag silently no-ops the "
                 "launch that passes it")
        elif d.shared and readme_text is not None \
                and f"--{d.name}" not in readme_text:
            emit(d.ctx, d.line,
                 f"shared-arg-set flag --{d.name} is missing from the "
                 "README flag table — undocumented config surface")
    if readme_text is not None:
        documented_lines: Set[Tuple[str, int]] = set()
        for e in env_reads:
            if not e.var.startswith(ENV_PREFIX):
                continue
            if e.var in readme_text:
                continue
            key = (e.ctx.relpath, e.line)
            if key in documented_lines:
                continue
            documented_lines.add(key)
            emit(e.ctx, e.line,
                 f"${e.var} is read here but never documented in "
                 "README.md — an undocumented env knob is tribal "
                 "knowledge")
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
