"""Shared per-class AST model for the concurrency-family passes.

FT010/FT011 (``rules/concurrency.py``) and FT020–FT025
(``lifecycle.py``) all reason about the same substrate: which functions
a class body defines (methods plus nested defs handed to
Thread/Timer), which thread roots the runtime actually spawns, which
``self.<attr>`` state each function touches under which lexical locks,
and the same-class call closure. That substrate lives here — rule
modules import it instead of each other, so the ``rules`` package
init (which imports every rule) can never form a cycle with a pass
module.

Names keep their original leading underscores: they are internal to
the analysis layer, re-exported by ``rules/concurrency.py`` for its
tests, and not part of the public analysis API.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from fedml_tpu.analysis.lint import dotted_name

#: substrings that mark a ``with`` context expression as a mutual
#: exclusion (matched on the LAST dotted component, lowercased)
_LOCKISH = ("lock", "cond", "mutex", "rlock", "semaphore")
_LOCK_CTORS = frozenset({"locked_global_numpy_rng"})

#: method calls that mutate their receiver in place (kept narrow — a
#: false "write" flags thread-safe primitives like Event.set)
_MUTATORS = frozenset({"append", "appendleft", "extend", "insert",
                       "setdefault", "pop", "popitem", "clear",
                       "update", "remove", "discard"})

#: methods that belong to the receive root besides registered handlers
_RECEIVE_ROOT_EXTRAS = ("run", "receive_message")


def _lock_name(expr: ast.expr) -> Optional[str]:
    """Normalized lock identity of a with-item context expr, or None.
    ``self._lock`` and ``_lock`` normalize apart (different objects);
    a call ``locked_global_numpy_rng()`` normalizes to its callee."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    name = dotted_name(expr)
    if not name:
        return None
    last = name.split(".")[-1].lower()
    if any(tok in last for tok in _LOCKISH) or \
            name.split(".")[-1] in _LOCK_CTORS:
        return name
    return None


class _Access:
    __slots__ = ("attr", "line", "node", "is_write", "locks")

    def __init__(self, attr: str, line: int, node: ast.AST,
                 is_write: bool, locks: Tuple[str, ...]):
        self.attr = attr
        self.line = line
        self.node = node
        self.is_write = is_write
        self.locks = frozenset(locks)


class _Func:
    """One analyzable function body: a method or a nested def inside a
    method (``qual`` = "method" or "method.<nested>")."""

    def __init__(self, qual: str, node: ast.AST):
        self.qual = qual
        self.node = node
        self.accesses: List[_Access] = []
        self.calls: Set[str] = set()          # self.X() / local nested defs
        self.acquire_pairs: List[Tuple[str, str, int]] = []  # (held, taken)
        self.calls_under_lock: List[Tuple[str, str]] = []  # (lock, callee)


def _self_attr(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _FuncVisitor(ast.NodeVisitor):
    """Collect accesses / calls / lock orderings for ONE function body,
    tracking the lexical with-lock stack. Nested defs are NOT entered —
    they are separate _Func units."""

    def __init__(self, func: _Func):
        self.func = func
        self.lock_stack: List[str] = []
        self._root = func.node

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is self._root:
            self.generic_visit(node)
        # else: nested def — its own unit

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.generic_visit(node)  # lambdas stay part of the enclosing body

    def visit_With(self, node: ast.With) -> None:
        taken = [ln for item in node.items
                 if (ln := _lock_name(item.context_expr))]
        for ln in taken:
            for held in self.lock_stack:
                if held != ln:
                    self.func.acquire_pairs.append((held, ln, node.lineno))
        self.lock_stack.extend(taken)
        self.generic_visit(node)
        for _ in taken:
            self.lock_stack.pop()

    visit_AsyncWith = visit_With

    def _record(self, attr: Optional[str], node: ast.AST,
                is_write: bool) -> None:
        if attr:
            self.func.accesses.append(_Access(
                attr, getattr(node, "lineno", 0), node, is_write,
                tuple(self.lock_stack)))

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._record(_self_attr(tgt), node, True)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record(_self_attr(node.target), node, True)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATORS:
                self._record(_self_attr(node.func.value), node, True)
            callee = dotted_name(node.func)
            if callee and callee.startswith("self."):
                name = callee[len("self."):]
                if "." not in name:
                    self.func.calls.add(name)
                    for held in self.lock_stack:
                        self.func.calls_under_lock.append((held, name))
        elif isinstance(node.func, ast.Name):
            self.func.calls.add(node.func.id)  # maybe a nested def
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load):
            self._record(_self_attr(node), node, False)
        self.generic_visit(node)


def _callable_target(expr: ast.expr) -> Optional[str]:
    """``self.M`` -> "M"; bare ``fire`` -> "fire"; else None."""
    name = dotted_name(expr)
    if not name:
        return None
    if name.startswith("self.") and name.count(".") == 1:
        return name[len("self."):]
    if "." not in name:
        return name
    return None


class _ClassModel:
    def __init__(self, cls: ast.ClassDef):
        self.cls = cls
        self.funcs: Dict[str, _Func] = {}
        #: root label -> entry function quals
        self.roots: Dict[str, Set[str]] = {}
        self._collect()

    def _collect(self) -> None:
        for method in self.cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            self.funcs[method.name] = _Func(method.name, method)
            for child in ast.walk(method):
                if child is method:
                    continue
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    # nested defs (timer `fire`, thread `runner`) are
                    # their own units, qualified under the method
                    qual = f"{method.name}.{child.name}"
                    if qual not in self.funcs:
                        self.funcs[qual] = _Func(qual, child)
        for fn in list(self.funcs.values()):
            _FuncVisitor(fn).visit(fn.node)
        self._infer_roots()

    def _resolve(self, caller_qual: str, name: str) -> Optional[str]:
        """A name referenced inside ``caller_qual``: nested def first,
        then a plain method."""
        nested = f"{caller_qual.split('.')[0]}.{name}"
        if nested in self.funcs:
            return nested
        if name in self.funcs:
            return name
        return None

    def _infer_roots(self) -> None:
        receive: Set[str] = set()
        for qual, fn in self.funcs.items():
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted_name(node.func) or ""
                last = callee.split(".")[-1]
                if last == "register_message_receive_handler" \
                        and len(node.args) >= 2:
                    target = _callable_target(node.args[1])
                    if target:
                        res = self._resolve(qual, target)
                        if res:
                            receive.add(res)
                elif last == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = _callable_target(kw.value)
                            if target:
                                res = self._resolve(qual, target)
                                if res:
                                    self.roots.setdefault(
                                        f"thread:{target}", set()).add(res)
                elif last == "Timer" and len(node.args) >= 2:
                    target = _callable_target(node.args[1])
                    if target:
                        res = self._resolve(qual, target)
                        if res:
                            self.roots.setdefault(
                                f"timer:{target}", set()).add(res)
                elif last == "RoundPrefetcher":
                    producers = []
                    if node.args:
                        producers.append(_callable_target(node.args[0]))
                    for kw in node.keywords:
                        if kw.arg == "next_key":
                            producers.append(_callable_target(kw.value))
                    for target in producers:
                        if target:
                            res = self._resolve(qual, target)
                            if res:
                                self.roots.setdefault(
                                    "prefetch", set()).add(res)
        for extra in _RECEIVE_ROOT_EXTRAS:
            if extra in self.funcs:
                receive.add(extra)
        if receive:
            self.roots["receive"] = receive

    def closure(self, entries: Set[str]) -> Set[str]:
        """Entry quals expanded through same-class calls. ``__init__``
        itself is excluded (construction precedes every thread) — but a
        nested def INSIDE ``__init__`` handed to a Thread/Timer runs
        after start() and stays in."""
        seen: Set[str] = set()
        work = [q for q in entries if q in self.funcs]
        while work:
            qual = work.pop()
            if qual in seen or qual == "__init__":
                continue
            seen.add(qual)
            for name in self.funcs[qual].calls:
                res = self._resolve(qual, name)
                if res and res not in seen:
                    work.append(res)
        return seen
