"""High-level composition of the analysis passes over a file set.

The CLI (``__main__``) orchestrates the same pieces with snapshot and
baseline I/O; this module is the snapshot-free core used by the corpus
tests (and any embedder): parse once, lint, run the protocol,
round-shape, and flag/env conformance passes over the same contexts,
then the unused-pragma pass — the full finding stream a file set
produces on its own merits.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import (build_contexts, lint_contexts,
                                     unused_pragmas)

PROTOCOL_RULE_IDS = ("FT201", "FT202", "FT203")
ROUNDSHAPE_RULE_IDS = ("FT301", "FT302", "FT303", "FT304")
FLAGS_RULE_IDS = ("FT016",)


def analyze_files(paths: Sequence[Path], root: Optional[Path] = None,
                  strict_pragmas: bool = False,
                  protocol: bool = True,
                  roundshape: bool = True,
                  flags: bool = True) -> List[Finding]:
    """Every finding the lint + protocol + round-shape + flag/env
    (+pragma) passes produce over ``paths`` — no snapshots, no
    baselines (the CLI's job)."""
    from fedml_tpu.analysis.rules import all_rules
    ctxs, findings = build_contexts(paths, root=root)
    rules = all_rules()
    findings.extend(lint_contexts(ctxs, rules=rules))
    active = {r.id for r in rules}
    from fedml_tpu.analysis.lint import is_test_path
    lib_ctxs = [c for c in ctxs if not is_test_path(c.relpath)]
    if protocol:
        from fedml_tpu.analysis.protocol import (conformance_findings,
                                                 extract_protocol)
        findings.extend(conformance_findings(extract_protocol(lib_ctxs),
                                             lib_ctxs))
        active |= set(PROTOCOL_RULE_IDS)
    if roundshape:
        from fedml_tpu.analysis import roundshape as rs
        findings.extend(rs.conformance_findings(ctxs))
        active |= set(ROUNDSHAPE_RULE_IDS)
    if flags:
        from fedml_tpu.analysis import flagsconf
        findings.extend(flagsconf.conformance_findings(lib_ctxs, root=root))
        active |= set(FLAGS_RULE_IDS)
    _, pragma_findings = unused_pragmas(ctxs, active,
                                        strict=strict_pragmas)
    findings.extend(pragma_findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
