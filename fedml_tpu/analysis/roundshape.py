"""FT30x — round-shape conformance: the machine-checked map of the
driver zoo.

``algorithms/`` holds 17 files that hand-copy the same
sample→pack→train→aggregate skeleton; PRs 2/4/5 each re-wired prefetch,
compression, and fault tolerance through several of them by hand, and
the ROADMAP's round-engine unification refactor needs a ground truth to
hold parity against. This pass extracts, from the same one-parse
contexts every other pass shares, a normalized **round-shape IR** per
driver:

- **sampling** — the cohort-selection hook and its seed source
  (``core.sampling.sample_clients``'s seeded host contract, the fused
  scan's ``jax.random.choice``, a seeded ``np.random.RandomState``, the
  locked global stream, or structural full participation);
- **pack** — cohort packing (``pack_clients`` pad-and-mask, cohort
  bucketing) and the async **prefetch** binding (``RoundPrefetcher`` /
  the shared ``_host_round_inputs`` path);
- **train** — the local-train entry point (shared functional trainer,
  a module-local ``make_*_local_train``, a module-level jitted step);
- **aggregate** — the server combination rule (sample-weighted mean,
  robust unweighted rules, normalized-gradient recombination, secure
  additive shares, staleness-weighted async mix, server optimizer);
- **comm** — in-process vs actor messages, and the compression-policy
  hooks;
- **failure** — liveness beat, deadline close, rejoin/heartbeat, chaos
  hooks.

Stages a driver does not define locally resolve through its base
classes (``FedOptAPI(FedAvgAPI)`` inherits sampling/pack/prefetch from
``fedavg``), so the map records *where each driver really gets each
stage* — hand-copied divergence becomes a finding, not tribal
knowledge:

- **FT300** — the checked-in snapshot ``ci/round_engine_map.json`` is
  missing/unreadable: the drift check must fail loudly, never skip.
- **FT301** — a driver re-implements a skeleton helper the shared
  modules provide (a local ``def sample_clients``/``tree_weighted_mean``
  shadowing ``core.sampling``/``core.pytree``).
- **FT302** — the skeleton's prefetch wiring is absent in a driver that
  does its own per-round sample+pack (the exact class of divergence
  PRs 2/4/5 fixed piecemeal, one driver at a time).
- **FT303** — an aggregation hook that takes the reported client
  weights but never reads them (weight-dropping aggregation; the
  deliberately unweighted robust rules carry a pragma with the
  rationale).
- **FT304** — a driver-local env knob (``os.environ`` read inside
  ``algorithms/``) bypassing the shared arg set.
- **FT305** — the extracted map drifted from the snapshot; accept
  deliberately with ``--write-round-map``.

The ``runs/round_engine_map.json`` artifact (line-bearing evidence) is
the parity oracle the unification refactor will diff itself against —
exactly as ``ci/collective_baseline.json`` guards the SPMD item.

A module participates as a driver when it lives under an
``algorithms/`` directory or declares ``FT_ROUNDSHAPE_DRIVER = True``
(how the analysis corpus plants driver-shaped violations).
"""

from __future__ import annotations

import ast
import hashlib
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from fedml_tpu.analysis.finding import Finding
from fedml_tpu.analysis.lint import FileContext, dotted_name, is_test_path

MAP_VERSION = 1

STAGES = ("sampling", "pack", "train", "aggregate", "comm", "failure")

_HINTS = {
    "FT300": ("regenerate the snapshot: python -m fedml_tpu.analysis "
              "--write-round-map"),
    "FT301": ("import the shared helper instead of redefining it — one "
              "definition is the parity contract the unification "
              "refactor diffs against"),
    "FT302": ("route the round's host side through the shared "
              "FedAvgAPI._host_round_inputs prefetch path (PRs 2/4/5 "
              "re-wired this per driver by hand), or pragma a driver "
              "whose round structure genuinely cannot pipeline: "
              "# ft: allow[FT302] why"),
    "FT303": ("weight the aggregation by the reported client sample "
              "counts, or pragma a deliberately unweighted rule with "
              "the rationale: # ft: allow[FT303] why"),
    "FT304": ("read config through the shared arg set / the driver's "
              "Config dataclass — driver-local env knobs are invisible "
              "to launchers and to the README flag table"),
    "FT305": ("review the round-shape change, then refresh the "
              "snapshot: python -m fedml_tpu.analysis --write-round-map"),
}

#: shared skeleton helpers a driver must import, not redefine
#: (helper name -> canonical home path suffix)
_SHARED_HELPERS = {
    "sample_clients": "core/sampling.py",
    "round_keys": "core/sampling.py",
    "eval_subsample": "core/sampling.py",
    "pack_clients": "data/base.py",
    "cohort_padded_len": "data/base.py",
    "client_weights": "data/base.py",
    "tree_weighted_mean": "core/pytree.py",
    "tree_weighted_mean_pallas": "ops/aggregate.py",
    "make_local_train": "trainer/functional.py",
    "make_eval": "trainer/functional.py",
    "make_batch_schedule": "trainer/functional.py",
    "resolve_compression": "comm/policy.py",
    "make_vmapped_body": "algorithms/fedavg.py",
}

#: aggregation-hook parameter names that carry reported client weights
_WEIGHT_PARAMS = frozenset({"weights", "ratios", "sample_nums",
                            "client_weights"})
_AGG_NAME_TOKENS = ("aggregate", "mean", "hook", "defended", "combine")


def _module_of(relpath: str) -> str:
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _is_driver_module(ctx: FileContext, names: Set[str]) -> bool:
    parts = Path(ctx.relpath).parts
    if "algorithms" in parts:
        return True
    return "FT_ROUNDSHAPE_DRIVER" in names


class _ModuleFacts:
    """Everything the stage resolver needs about one module, from one
    AST walk: call names, attribute names, bare names, function defs,
    classes with their base-name spellings, and the import table."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.module = _module_of(ctx.relpath)
        self.calls: Dict[str, int] = {}        # dotted call name -> first line
        self.attrs: Set[str] = set()
        self.names: Set[str] = set()
        #: EVERY def per name — two classes may define same-named hooks
        #: and FT301/FT303 must see them all
        self.funcdefs: Dict[str, List[ast.AST]] = {}
        self.classes: Dict[str, List[str]] = {}  # class -> base spellings
        self.imports: Dict[str, str] = {}        # local name -> module
        self.env_reads: List[int] = []           # lines of os.environ reads
        self.range_over_client_num = False
        self._collect()

    def _collect(self) -> None:
        tree = self.ctx.tree
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name:
                    self.calls.setdefault(name, node.lineno)
                    if name in ("os.environ.get", "os.getenv"):
                        self.env_reads.append(node.lineno)
                if isinstance(node.func, ast.Name) \
                        and node.func.id == "range":
                    for arg in node.args:
                        for sub in ast.walk(arg):
                            d = dotted_name(sub) if isinstance(
                                sub, (ast.Attribute, ast.Name)) else None
                            if d and d.split(".")[-1] in (
                                    "client_num", "client_num_in_total",
                                    "worker_num"):
                                self.range_over_client_num = True
            elif isinstance(node, ast.Subscript) \
                    and dotted_name(node.value) == "os.environ":
                self.env_reads.append(node.lineno)
            elif isinstance(node, ast.Attribute):
                self.attrs.add(node.attr)
            elif isinstance(node, ast.Name):
                self.names.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcdefs.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = [
                    b for b in (dotted_name(base) for base in node.bases)
                    if b]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = node.module

    # -- marker predicates ---------------------------------------------------
    def has_call(self, *suffixes: str) -> Optional[int]:
        """First line of a call whose last dotted component matches."""
        for name, line in self.calls.items():
            if name.split(".")[-1] in suffixes:
                return line
        return None

    def evidence(self) -> Set[str]:
        """The flat marker universe: call suffixes + attrs + names."""
        out = {name.split(".")[-1] for name in self.calls}
        out |= self.attrs
        out |= self.names
        return out


#: (stage, hook label, required marker sets) — a marker set matches when
#: ANY of its entries is in the module's evidence; rules are tried in
#: order, first hit is the primary hook (later hits join the evidence)
_STAGE_RULES: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {
    "sampling": [
        ("seeded_host_sampler", ("sample_clients", "client_sampling")),
        ("device_choice", ("__jax_choice__",)),
        ("locked_global_rng", ("locked_global_numpy_rng",)),
        ("full_population", ("__range_client_num__",)),
        ("seeded_host_rng", ("RandomState",)),
    ],
    "pack": [
        ("pad_and_mask_pack", ("pack_clients",)),
        ("shared_fedavg_pack", ("_host_round_inputs", "_prepare_round",
                                "_pack_cohort", "_pack_round")),
        ("per_client_host_batches", ("train_data_local_dict",)),
    ],
    "train": [
        ("shared_functional_local_train", ("make_local_train",
                                           "_shared_local_train")),
        ("module_local_train", ("__local_train_def__",)),
        ("module_jit_step", ("value_and_grad", "grad", "apply_updates")),
        ("flax_trainer", ("FlaxModelTrainer",)),
        ("pluggable_local_fn", ("local_compute", "local_fn", "_local_fn")),
    ],
    "aggregate": [
        ("robust_rules_unweighted", ("ROBUST_AGGREGATORS", "apply_defense")),
        ("secure_additive_shares", ("gen_additive_ss", "SecureAggregator",
                                    "lcc_encoding", "quantize")),
        ("staleness_weighted_mix", ("tree_axpy", "staleness_weight")),
        ("normalized_grad_recombination", ("tau_eff",)),
        ("sample_weighted_mean", ("tree_weighted_mean",
                                  "tree_weighted_mean_pallas")),
        ("gossip_mix", ("__gossip__",)),
        ("sum_reduce", ("_tree_sum", "tree_add")),
    ],
    "comm": [
        ("actor_messages", ("register_message_receive_handler",
                            "send_message", "launch_federation")),
    ],
    "failure": [
        ("liveness_deadline_rejoin", ("__ft_markers__",)),
    ],
}

#: a prefetch BINDING (not a mere config field: FedNovaConfig carries
#: prefetch_depth "for launcher symmetry" while packing serially — that
#: must count as absent, it is the FT302 divergence class itself)
_PREFETCH_MARKERS = ("RoundPrefetcher", "bind_prefetcher", "consume",
                     "resolve_prefetch_depth", "_round_prefetcher",
                     "_host_round_inputs")
_COMPRESSION_MARKERS = ("resolve_compression", "CompressionPolicy",
                        "compress_for_policy", "is_compressed",
                        "_decode_model_payload", "_encode_broadcast",
                        "compression")
_FT_MARKERS = {
    "liveness": ("SiloLivenessTable", "liveness", "observe_report_latency"),
    "deadline": ("_arm_deadline", "round_deadline_s",
                 "handle_round_timeout", "MSG_TYPE_ROUND_TIMEOUT"),
    "rejoin": ("handle_message_join", "MSG_TYPE_C2S_JOIN",
               "rejoin_idle_s", "rejoins"),
    "heartbeat": ("heartbeat_s", "handle_message_heartbeat",
                  "MSG_TYPE_C2S_HEARTBEAT"),
    "chaos": ("fault_plan", "FaultPlan"),
}
_SEED_MARKERS = (
    ("fold_in_keychain", ("round_keys", "fold_in", "key")),
    ("seeded_rng", ("RandomState", "SeedSequence")),
    ("locked_global_stream", ("locked_global_numpy_rng",)),
    ("global_seed", ("seed",)),
)


def _kind_of(facts: _ModuleFacts) -> str:
    if not facts.funcdefs and not facts.classes:
        return "reexport"
    ev = facts.evidence()
    if "register_message_receive_handler" in ev \
            or "launch_federation" in ev \
            or any("Manager" in b for bases in facts.classes.values()
                   for b in bases):
        return "actor"
    return "sim"


def _local_markers(facts: _ModuleFacts) -> Set[str]:
    ev = facts.evidence()
    if facts.range_over_client_num:
        ev.add("__range_client_num__")
    if "jax.random.choice" in facts.calls:
        # full dotted match: a host RandomState's .choice must not read
        # as device-side sampling
        ev.add("__jax_choice__")
    if any(name.startswith("make_") and "local" in name
           and "train" in name for name in facts.funcdefs):
        ev.add("__local_train_def__")
    if "einsum" in ev and ("TopologyManager" in " ".join(
            b for bases in facts.classes.values() for b in bases)
            or any("Topology" in c for c in facts.calls)):
        ev.add("__gossip__")
    if any(m in ev for group in _FT_MARKERS.values() for m in group):
        ev.add("__ft_markers__")
    return ev


class _Resolver:
    """Stage resolution with transitive base-module inheritance."""

    def __init__(self, facts_by_module: Dict[str, _ModuleFacts]):
        self.facts = facts_by_module
        #: class name -> defining module (last definition wins; driver
        #: class names are unique in this tree)
        self.class_home: Dict[str, str] = {}
        for mod, f in facts_by_module.items():
            for cls in f.classes:
                self.class_home[cls] = mod
        self._markers: Dict[str, Set[str]] = {
            mod: _local_markers(f) for mod, f in facts_by_module.items()}

    def base_modules(self, module: str) -> List[str]:
        """Modules (in the analyzed set) that define this module's base
        classes, transitively, nearest first."""
        out: List[str] = []
        seen = {module}
        frontier = [module]
        while frontier:
            mod = frontier.pop(0)
            f = self.facts.get(mod)
            if f is None:
                continue
            for bases in f.classes.values():
                for base in bases:
                    name = base.split(".")[-1]
                    home = self.class_home.get(name)
                    if home is None and name in f.imports:
                        home = f.imports[name]
                    if home and home in self.facts and home not in seen:
                        seen.add(home)
                        out.append(home)
                        frontier.append(home)
        return out

    def resolve_stage(self, module: str, stage: str, kind: str
                      ) -> Dict[str, str]:
        chain = [(module, "local")] + [
            (b, f"inherited:{b}") for b in self.base_modules(module)]
        # rules outer, chain inner: a higher-priority hook anywhere in
        # the inheritance chain beats a lower-priority local one — a
        # subclass driver's incidental helper (fedavg_robust's poisoning
        # RandomState) must not shadow the skeleton stage it inherits
        for hook, wanted in _STAGE_RULES[stage]:
            for mod, via in chain:
                markers = self._markers.get(mod, set())
                if any(w in markers for w in wanted):
                    return {"hook": hook, "via": via}
        # structural defaults: explicit, never "unknown"
        if stage == "comm":
            return {"hook": "in_process", "via": "structural"}
        if stage == "failure":
            if kind == "actor":
                return {"hook": "none_strict_barrier", "via": "structural"}
            return {"hook": "n/a_in_process", "via": "structural"}
        if stage == "sampling":
            return {"hook": "n/a_no_cohort", "via": "structural"}
        if stage == "pack":
            return {"hook": "n/a_no_cohort_pack", "via": "structural"}
        if stage == "aggregate":
            return {"hook": "n/a_no_model_averaging", "via": "structural"}
        return {"hook": "unknown", "via": "unresolved"}

    def feature(self, module: str, markers: Tuple[str, ...]
                ) -> Tuple[str, str]:
        """(value, via) for a cross-cutting feature like prefetch."""
        chain = [(module, "local")] + [
            (b, f"inherited:{b}") for b in self.base_modules(module)]
        for mod, via in chain:
            got = sorted(m for m in markers
                         if m in self._markers.get(mod, set()))
            if got:
                return ("+".join(got), via)
        return ("none", "structural")

    def seed_source(self, module: str) -> str:
        markers = self._markers.get(module, set())
        for label, wanted in _SEED_MARKERS:
            if any(w in markers for w in wanted):
                return label
        for base in self.base_modules(module):
            bm = self._markers.get(base, set())
            for label, wanted in _SEED_MARKERS:
                if any(w in bm for w in wanted):
                    return f"{label} (inherited:{base})"
        return "none"

    def failure_hooks(self, module: str) -> Tuple[str, str]:
        chain = [(module, "local")] + [
            (b, f"inherited:{b}") for b in self.base_modules(module)]
        best: Optional[Tuple[str, str]] = None
        for mod, via in chain:
            markers = self._markers.get(mod, set())
            got = sorted(k for k, wanted in _FT_MARKERS.items()
                         if any(w in markers for w in wanted))
            if got:
                got_s = "+".join(got)
                if best is None:
                    best = (got_s, via)
                elif via.startswith("inherited") and best[0] != got_s:
                    # merge: a subclass driver keeps the base's hooks
                    merged = sorted(set(best[0].split("+")) | set(got))
                    best = ("+".join(merged), best[1])
        return best if best else ("none", "structural")


class _Analysis:
    """The one-build substrate both the map extractor and the
    conformance checker consume — module facts and the inheritance
    resolver are computed exactly once per run."""

    def __init__(self, ctxs: Sequence[FileContext]):
        lib = [c for c in ctxs if not is_test_path(c.relpath)]
        self.facts_by_module: Dict[str, _ModuleFacts] = {}
        self.drivers: List[_ModuleFacts] = []
        for ctx in lib:
            f = _ModuleFacts(ctx)
            self.facts_by_module[f.module] = f
            if _is_driver_module(ctx, f.names):
                self.drivers.append(f)
        self.resolver = _Resolver(self.facts_by_module)


def analyze(ctxs: Sequence[FileContext]) -> _Analysis:
    return _Analysis(ctxs)


def extract_round_shapes(ctxs: Sequence[FileContext],
                         analysis: Optional[_Analysis] = None) -> Dict:
    """-> the line-bearing round-engine map over every driver module in
    ``ctxs`` (the ``runs/`` artifact shape)."""
    analysis = analysis or _Analysis(ctxs)
    resolver = analysis.resolver

    drivers: List[Dict] = []
    for f in sorted(analysis.drivers, key=lambda f: f.module):
        module = f.module
        kind = _kind_of(f)
        entry: Dict = {"module": module, "path": f.ctx.relpath,
                       "kind": kind, "stages": {}}
        if kind == "reexport":
            entry["stages"] = {s: {"hook": "n/a_reexport",
                                   "via": "structural"} for s in STAGES}
            drivers.append(entry)
            continue
        for stage in STAGES:
            entry["stages"][stage] = resolver.resolve_stage(module, stage,
                                                            kind)
        entry["stages"]["sampling"]["seed_source"] = \
            resolver.seed_source(module)
        prefetch, pvia = resolver.feature(module, _PREFETCH_MARKERS)
        entry["stages"]["pack"]["prefetch"] = prefetch
        entry["stages"]["pack"]["prefetch_via"] = pvia
        comp, cvia = resolver.feature(module, _COMPRESSION_MARKERS)
        entry["stages"]["comm"]["compression"] = comp
        entry["stages"]["comm"]["compression_via"] = cvia
        hooks, hvia = resolver.failure_hooks(module)
        entry["stages"]["failure"]["hooks"] = hooks
        entry["stages"]["failure"]["hooks_via"] = hvia
        entry["bases"] = resolver.base_modules(module)
        drivers.append(entry)
    return {"version": MAP_VERSION, "drivers": drivers}


# -- conformance findings (FT301-FT304) --------------------------------------

def _finding(rule: str, path: str, line: int, message: str,
             snippet: str = "") -> Finding:
    return Finding(rule=rule, path=path, line=line, message=message,
                   hint=_HINTS[rule], snippet=snippet)


def conformance_findings(ctxs: Sequence[FileContext],
                         analysis: Optional[_Analysis] = None
                         ) -> List[Finding]:
    """FT301-FT304 over the driver modules, pragma suppression through
    each originating context."""
    analysis = analysis or _Analysis(ctxs)
    drivers = analysis.drivers
    resolver = analysis.resolver

    findings: List[Finding] = []

    def emit(rule: str, ctx: FileContext, line: int, message: str) -> None:
        if ctx.allowed(rule, line):
            return
        snippet = (ctx.lines[line - 1].strip()
                   if 0 < line <= len(ctx.lines) else "")
        findings.append(_finding(rule, ctx.relpath, line, message, snippet))

    for f in drivers:
        ctx = f.ctx
        # FT301: local redefinition of a shared skeleton helper
        for name, nodes in f.funcdefs.items():
            home = _SHARED_HELPERS.get(name)
            if home is None or ctx.relpath.endswith(home):
                continue
            for node in nodes:
                emit("FT301", ctx, node.lineno,
                     f"driver defines its own {name}() — the shared "
                     f"skeleton helper lives in {home}; a local copy "
                     "forks the parity contract the round-engine "
                     "unification will diff against")
        # FT302: per-round sample+pack without the prefetch pipeline
        if _kind_of(f) == "sim":
            sample_line = f.has_call("sample_clients")
            pack_line = f.has_call("pack_clients")
            prefetch, _ = resolver.feature(f.module, _PREFETCH_MARKERS)
            if sample_line and pack_line and prefetch == "none":
                emit("FT302", ctx, pack_line,
                     "driver samples and packs each round on the "
                     "critical path with NO prefetch binding — the "
                     "skeleton's async round pipeline "
                     "(FedAvgAPI._host_round_inputs / RoundPrefetcher) "
                     "is wired into every FedAvg-family driver; this is "
                     "the exact divergence class PRs 2/4/5 fixed "
                     "piecemeal")
        # FT303: aggregation hook that ignores its weights parameter
        for name, nodes in f.funcdefs.items():
            if not any(tok in name.lower() for tok in _AGG_NAME_TOKENS):
                continue
            for node in nodes:
                a = node.args
                params = {p.arg for p in
                          a.args + a.kwonlyargs
                          + getattr(a, "posonlyargs", [])}
                wparams = params & _WEIGHT_PARAMS
                if not wparams:
                    continue
                loaded = {n.id for n in ast.walk(node)
                          if isinstance(n, ast.Name)
                          and isinstance(n.ctx, ast.Load)}
                for w in sorted(wparams - loaded):
                    emit("FT303", ctx, node.lineno,
                         f"aggregation hook {name}() takes the reported "
                         f"client weights ({w!r}) but never reads them — "
                         "sample-count weighting is silently dropped "
                         "(deliberately unweighted robust rules pragma "
                         "this with the rationale)")
        # FT304: driver-local env knob
        for line in sorted(set(f.env_reads)):
            emit("FT304", ctx, line,
                 "driver reads an environment variable directly — "
                 "config must flow through the shared arg set / the "
                 "driver Config dataclass so launches are reproducible "
                 "from their recorded flags")
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# -- snapshot (FT300/FT305) ---------------------------------------------------

def normalize_map(full_map: Dict) -> Dict:
    """Line-free, path-free shape for the checked-in snapshot."""
    drivers = []
    for d in full_map["drivers"]:
        drivers.append({
            "module": d["module"],
            "kind": d["kind"],
            "stages": {s: dict(d["stages"][s]) for s in STAGES
                       if s in d["stages"]},
        })
    payload = {"version": MAP_VERSION,
               "drivers": sorted(drivers, key=lambda d: d["module"])}
    blob = json.dumps(payload, sort_keys=True)
    payload["fingerprint"] = hashlib.sha1(blob.encode()).hexdigest()[:16]
    return payload


def snapshot_findings(full_map: Dict, snapshot_path: Path) -> List[Finding]:
    norm = normalize_map(full_map)
    path = Path(snapshot_path)
    if not path.exists():
        return [_finding(
            "FT300", str(snapshot_path), 0,
            "round-engine-map snapshot is MISSING — the unification "
            "refactor's parity oracle cannot drift-check, and a "
            "silently skipped check is the failure mode this pass "
            "exists to prevent")]
    try:
        old = json.loads(path.read_text())
    except (OSError, ValueError) as exc:
        return [_finding(
            "FT300", str(snapshot_path), 0,
            f"round-engine-map snapshot is unreadable ({exc}) — "
            "regenerate it")]
    if old.get("fingerprint") == norm["fingerprint"]:
        return []
    old_d = {d["module"]: d for d in old.get("drivers", [])}
    new_d = {d["module"]: d for d in norm["drivers"]}
    changes: List[str] = []
    for mod in sorted(set(new_d) - set(old_d)):
        changes.append(f"new driver {mod}")
    for mod in sorted(set(old_d) - set(new_d)):
        changes.append(f"removed driver {mod}")
    for mod in sorted(set(old_d) & set(new_d)):
        if old_d[mod] != new_d[mod]:
            diff_stages = [s for s in STAGES
                           if old_d[mod].get("stages", {}).get(s)
                           != new_d[mod].get("stages", {}).get(s)]
            changes.append(f"{mod}: {'/'.join(diff_stages) or 'kind'} "
                           "changed")
    detail = "; ".join(changes) or "map fingerprint changed"
    return [_finding(
        "FT305", str(snapshot_path), 0,
        f"round-shape map drifted from the checked-in snapshot: {detail}")]


def write_map(full_map: Dict, artifact_path: Path,
              snapshot_path: Optional[Path] = None) -> None:
    artifact_path = Path(artifact_path)
    artifact_path.parent.mkdir(parents=True, exist_ok=True)
    artifact_path.write_text(json.dumps(full_map, indent=2, sort_keys=True)
                             + "\n")
    if snapshot_path is not None:
        snapshot_path = Path(snapshot_path)
        snapshot_path.parent.mkdir(parents=True, exist_ok=True)
        snapshot_path.write_text(
            json.dumps(normalize_map(full_map), indent=2, sort_keys=True)
            + "\n")


def check_round_shapes(ctxs: Sequence[FileContext], snapshot_path: Path,
                       artifact_path: Optional[Path] = None,
                       write_snapshot: bool = False
                       ) -> Tuple[List[Finding], Dict]:
    """The CLI entry: extract, emit the artifact, check conformance +
    snapshot. ``write_snapshot`` refreshes instead of comparing
    (conformance findings still apply — a snapshot must never launder
    an FT301)."""
    analysis = _Analysis(ctxs)
    full_map = extract_round_shapes(ctxs, analysis=analysis)
    if artifact_path is not None:
        write_map(full_map, artifact_path)
    findings = conformance_findings(ctxs, analysis=analysis)
    if write_snapshot:
        snapshot_path = Path(snapshot_path)
        snapshot_path.parent.mkdir(parents=True, exist_ok=True)
        snapshot_path.write_text(
            json.dumps(normalize_map(full_map), indent=2, sort_keys=True)
            + "\n")
    else:
        findings.extend(snapshot_findings(full_map, snapshot_path))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, full_map
