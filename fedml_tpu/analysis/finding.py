"""Finding: the one record type both analysis layers emit.

A finding is identified across runs by its *fingerprint* — a hash of
(rule, path, normalized source snippet), deliberately NOT the line
number, so a baseline entry survives unrelated edits above it and goes
stale only when the flagged code itself changes or disappears (the same
scheme detect-secrets and ruff's --add-noqa baselines use).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, Optional


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str          #: rule id, e.g. "FT001" (lint) / "FT104" (audit)
    path: str          #: repo-relative posix path, or "<entry:NAME>" for audit
    line: int          #: 1-based line, 0 for audit findings
    message: str       #: what is wrong, concretely
    hint: str = ""     #: how to fix it
    snippet: str = ""  #: the offending source line, stripped

    @property
    def fingerprint(self) -> str:
        payload = "|".join((self.rule, self.path, " ".join(self.snippet.split())))
        return hashlib.sha1(payload.encode()).hexdigest()[:16]

    def to_json(self) -> Dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "hint": self.hint,
            "snippet": self.snippet,
            "fingerprint": self.fingerprint,
        }

    def format_text(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        out = f"{loc}: {self.rule} {self.message}"
        if self.snippet:
            out += f"\n    | {self.snippet}"
        if self.hint:
            out += f"\n    fix: {self.hint}"
        return out


def audit_finding(rule: str, entry: str, message: str,
                  hint: str = "", detail: Optional[str] = None) -> Finding:
    """Finding for a jaxpr-audit check: anchored to a registered entry
    point instead of a source line (``detail`` lands in the snippet slot
    so it participates in the fingerprint)."""
    return Finding(rule=rule, path=f"<entry:{entry}>", line=0,
                   message=message, hint=hint, snippet=detail or "")
