"""Checked-in finding baseline (``ci/analysis_baseline.json``).

The workflow ruff/detect-secrets users know: a finding the team decides
to live with is recorded by fingerprint (rule + path + normalized
snippet — NOT line number, see finding.py) with a human note. The CLI
then exits zero as long as every current finding is either fixed or
baselined, and WARNS when a baseline entry no longer matches anything
(the flagged code was fixed or deleted — remove the stale entry so it
cannot mask a future regression at the same spot).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from fedml_tpu.analysis.finding import Finding

VERSION = 1


def load_baseline(path: Path) -> List[Dict]:
    data = json.loads(Path(path).read_text())
    if data.get("version") != VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r} "
            f"(this tool writes version {VERSION})")
    entries = data.get("entries", [])
    for e in entries:
        if "fingerprint" not in e or "rule" not in e:
            raise ValueError(f"baseline {path}: malformed entry {e!r}")
    return entries


def save_baseline(path: Path, findings: Sequence[Finding],
                  note: str = "",
                  notes_by_fingerprint: Dict[str, str] = None) -> None:
    """Write every finding's fingerprint as a baseline entry (the
    ``--write-baseline`` escape hatch for adopting the tool on a tree
    with known, accepted findings). ``notes_by_fingerprint`` carries
    prior entries' handwritten notes through a refresh."""
    notes = notes_by_fingerprint or {}
    entries = [{"rule": f.rule, "path": f.path,
                "fingerprint": f.fingerprint,
                "snippet": f.snippet,
                "note": notes.get(f.fingerprint) or note}
               for f in findings]
    Path(path).write_text(json.dumps(
        {"version": VERSION, "entries": entries}, indent=2) + "\n")


def apply_baseline(findings: Sequence[Finding], entries: Sequence[Dict]
                   ) -> Tuple[List[Finding], List[Finding], List[Dict]]:
    """-> (active, suppressed, stale_entries)."""
    by_fp = {e["fingerprint"] for e in entries}
    active = [f for f in findings if f.fingerprint not in by_fp]
    suppressed = [f for f in findings if f.fingerprint in by_fp]
    seen = {f.fingerprint for f in suppressed}
    stale = [e for e in entries if e["fingerprint"] not in seen]
    return active, suppressed, stale
