"""GKT split ResNets — small client feature extractor + large server model.

Reference: fedml_api/model/cv/resnet56_gkt/{resnet_client.py:230 resnet8_56,
resnet_server.py:200 resnet56_server}. The client is the CIFAR stem + stage-1
Bottleneck blocks with an auxiliary classifier head, returning
``(logits, feature_maps)``; the server model is the remaining stages
(resnet_server.py forward, :186-198 — stem commented out, consumes feature
maps directly) ending in the usual pool + fc. Flax convs infer input
channel counts, so the client/server channel seam needs no hand-wiring.
NHWC layout; BatchNorm via the ``batch_stats`` collection.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.common import bn
from fedml_tpu.models.resnet import BottleneckBlock


class ResNetClientGKT(nn.Module):
    """resnet8_56 role: stem + ``num_blocks`` stage-1 bottlenecks; returns
    (logits from the aux head, extracted feature maps [B, H, W, 64])."""

    num_blocks: int = 2
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False) -> Tuple[jnp.ndarray,
                                                        jnp.ndarray]:
        x = nn.Conv(16, (3, 3), padding=1, use_bias=False)(x)
        x = nn.relu(bn(train)(x))
        for _ in range(self.num_blocks):
            x = BottleneckBlock(16, stride=1)(x, train=train)
        features = x
        pooled = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(self.num_classes)(pooled)
        return logits, features


class ResNetServerGKT(nn.Module):
    """resnet56_server role: stages over the received feature maps."""

    stage_sizes: Sequence[int] = (6, 6, 6)
    num_classes: int = 10

    @nn.compact
    def __call__(self, features, train: bool = False) -> jnp.ndarray:
        x = features
        for stage, blocks in enumerate(self.stage_sizes):
            planes = 16 * (2 ** stage)
            for b in range(blocks):
                stride = 2 if (stage > 0 and b == 0) else 1
                x = BottleneckBlock(planes, stride)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def resnet8_56(num_classes: int = 10) -> ResNetClientGKT:
    return ResNetClientGKT(num_blocks=2, num_classes=num_classes)


def resnet56_server(num_classes: int = 10) -> ResNetServerGKT:
    return ResNetServerGKT(stage_sizes=(6, 6, 6), num_classes=num_classes)
