"""Shared building blocks for the model zoo."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn


def bn(train: bool, sync_axis: Optional[str] = None) -> nn.BatchNorm:
    """The zoo-wide BatchNorm configuration (torch defaults: momentum 0.1 ->
    flax momentum 0.9, eps 1e-5), running stats in the ``batch_stats``
    collection, frozen in eval mode.

    ``sync_axis``: a mesh axis name to synchronize batch statistics over —
    the TPU re-expression of the reference's SynchronizedBatchNorm
    (fedml_api/model/cv/batchnorm_utils.py, the DataParallel cross-GPU
    stats shim). Inside ``shard_map``/``vmap`` over that named axis, flax
    psums the mean/var so every shard normalizes with the *global* batch
    statistics; no extra machinery needed (tests/test_sync_bn.py proves
    shard==global parity)."""
    return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                        epsilon=1e-5, axis_name=sync_axis)
