"""Shared building blocks for the model zoo."""

from __future__ import annotations

import flax.linen as nn


def bn(train: bool) -> nn.BatchNorm:
    """The zoo-wide BatchNorm configuration (torch defaults: momentum 0.1 ->
    flax momentum 0.9, eps 1e-5), running stats in the ``batch_stats``
    collection, frozen in eval mode."""
    return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                        epsilon=1e-5)
