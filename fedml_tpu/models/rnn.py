"""LSTM language models for the federated text benchmarks.

Reference: fedml_api/model/nlp/rnn.py —
- ``RNN_OriginalFedAvg`` (:5): the McMahan et al. AISTATS'17 Shakespeare
  char-LM: embedding(8, pad=0) -> 2x LSTM(256) -> dense(vocab 90). LEAF
  shakespeare predicts the single next char from the final hidden state;
  the TFF ``fed_shakespeare`` variant scores every position
  (``seq_output=True``, the commented branch in the reference forward).
- ``RNN_StackOverflow`` (:41): Adaptive Federated Optimization Table 9
  next-word model: embedding(96, extended vocab 10000+4 for pad/bos/eos/oov,
  pad=0) -> LSTM(670) -> dense(96) -> dense(extended vocab), scoring every
  position.

Both run the LSTM as ``nn.RNN`` (a lax.scan over OptimizedLSTMCell) with
fresh zero carries per batch, matching the reference's stateless batches.
"""

from __future__ import annotations

import flax.linen as nn


class RNN_OriginalFedAvg(nn.Module):
    vocab_size: int = 90
    embedding_dim: int = 8
    hidden_size: int = 256
    seq_output: bool = False  # True for fed_shakespeare (score every step)
    # nn.RNN's internal scan carry is created unvarying inside shard_map
    # bodies; the spmd layer reads this flag to relax its vma check
    flax_rnn_carry = True

    @nn.compact
    def __call__(self, input_seq, train: bool = False):
        x = nn.Embed(self.vocab_size, self.embedding_dim)(input_seq)
        x = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(x)
        x = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(x)
        if not self.seq_output:
            x = x[:, -1]
        return nn.Dense(self.vocab_size)(x)


class RNN_StackOverflow(nn.Module):
    vocab_size: int = 10000
    num_oov_buckets: int = 1
    embedding_size: int = 96
    latent_size: int = 670
    num_layers: int = 1
    flax_rnn_carry = True  # see RNN_OriginalFedAvg

    @nn.compact
    def __call__(self, input_seq, train: bool = False):
        extended = self.vocab_size + 3 + self.num_oov_buckets
        x = nn.Embed(extended, self.embedding_size)(input_seq)
        for _ in range(self.num_layers):
            x = nn.RNN(nn.OptimizedLSTMCell(self.latent_size))(x)
        x = nn.Dense(self.embedding_size)(x)
        return nn.Dense(extended)(x)  # [B, T, extended_vocab]
