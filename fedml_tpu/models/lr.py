"""Logistic regression (reference: fedml_api/model/linear/lr.py:4-13).

The reference applies a sigmoid to the linear output *and then* feeds it to
``nn.CrossEntropyLoss`` — a quirk, not a spec; we emit raw logits and let the
task head apply the proper link (softmax CE for classification, sigmoid BCE
for multi-label tag prediction), which is both numerically saner and what the
cited benchmark model actually is.
"""

from __future__ import annotations

import flax.linen as nn


class LogisticRegression(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes)(x)
