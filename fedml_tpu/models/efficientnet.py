"""EfficientNet B0-B7 in flax/NHWC.

Reference: fedml_api/model/cv/efficientnet.py:138 (EfficientNet with MBConv
blocks, squeeze-excitation, swish, width/depth compound scaling per
efficientnet_utils.py's coefficient table). Implemented from the documented
architecture (Tan & Le 2019): stem conv, 7 MBConv stages, head conv, pool,
classifier. Drop-connect is implemented as per-example stochastic depth
under the ``dropout`` rng.

TPU notes: depthwise convs map to MXU poorly relative to dense convs, but
XLA fuses the SE and swish elementwise chains into the surrounding convs;
everything static-shaped. BatchNorm via ``batch_stats``.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

# (width_mult, depth_mult, resolution, dropout) per variant
PARAMS = {
    "efficientnet-b0": (1.0, 1.0, 224, 0.2),
    "efficientnet-b1": (1.0, 1.1, 240, 0.2),
    "efficientnet-b2": (1.1, 1.2, 260, 0.3),
    "efficientnet-b3": (1.2, 1.4, 300, 0.3),
    "efficientnet-b4": (1.4, 1.8, 380, 0.4),
    "efficientnet-b5": (1.6, 2.2, 456, 0.4),
    "efficientnet-b6": (1.8, 2.6, 528, 0.5),
    "efficientnet-b7": (2.0, 3.1, 600, 0.5),
}

# (expand, channels, repeats, stride, kernel) — B0 baseline stages
BASE_STAGES: Sequence[Tuple[int, int, int, int, int]] = (
    (1, 16, 1, 1, 3),
    (6, 24, 2, 2, 3),
    (6, 40, 2, 2, 5),
    (6, 80, 3, 2, 3),
    (6, 112, 3, 1, 5),
    (6, 192, 4, 2, 5),
    (6, 320, 1, 1, 3),
)


def round_filters(filters: int, width_mult: float, divisor: int = 8) -> int:
    filters *= width_mult
    new = max(divisor, int(filters + divisor / 2) // divisor * divisor)
    if new < 0.9 * filters:
        new += divisor
    return int(new)


def round_repeats(repeats: int, depth_mult: float) -> int:
    return int(math.ceil(depth_mult * repeats))


def _bn(train: bool):
    return nn.BatchNorm(use_running_average=not train, momentum=0.99,
                        epsilon=1e-3)


class MBConv(nn.Module):
    C_out: int
    expand: int
    kernel: int
    stride: int
    se_ratio: float = 0.25
    drop_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        C_in = x.shape[-1]
        h = x
        if self.expand != 1:
            h = nn.Conv(C_in * self.expand, (1, 1), use_bias=False)(h)
            h = nn.swish(_bn(train)(h))
        C_mid = h.shape[-1]
        h = nn.Conv(C_mid, (self.kernel, self.kernel), strides=self.stride,
                    feature_group_count=C_mid, use_bias=False)(h)
        h = nn.swish(_bn(train)(h))
        # squeeze-excitation
        se_ch = max(1, int(C_in * self.se_ratio))
        s = jnp.mean(h, axis=(1, 2), keepdims=True)
        s = nn.swish(nn.Conv(se_ch, (1, 1))(s))
        s = jax.nn.sigmoid(nn.Conv(C_mid, (1, 1))(s))
        h = h * s
        h = nn.Conv(self.C_out, (1, 1), use_bias=False)(h)
        h = _bn(train)(h)
        if self.stride == 1 and C_in == self.C_out:
            if train and self.drop_rate > 0:
                keep = 1.0 - self.drop_rate
                rng = self.make_rng("dropout")
                mask = jax.random.bernoulli(
                    rng, keep, (h.shape[0], 1, 1, 1)).astype(h.dtype)
                h = h / keep * mask
            h = h + x
        return h


class EfficientNet(nn.Module):
    variant: str = "efficientnet-b0"
    num_classes: int = 1000

    @nn.compact
    def __call__(self, x, train: bool = False):
        width, depth, _, dropout = PARAMS[self.variant]
        h = nn.Conv(round_filters(32, width), (3, 3), strides=2,
                    use_bias=False)(x)
        h = nn.swish(_bn(train)(h))
        total_blocks = sum(round_repeats(r, depth)
                           for _, _, r, _, _ in BASE_STAGES)
        block_idx = 0
        for expand, channels, repeats, stride, kernel in BASE_STAGES:
            C_out = round_filters(channels, width)
            for r in range(round_repeats(repeats, depth)):
                drop = 0.2 * block_idx / total_blocks  # linearly scaled
                h = MBConv(C_out, expand, kernel,
                           stride if r == 0 else 1,
                           drop_rate=drop)(h, train=train)
                block_idx += 1
        h = nn.Conv(round_filters(1280, width), (1, 1), use_bias=False)(h)
        h = nn.swish(_bn(train)(h))
        h = jnp.mean(h, axis=(1, 2))
        if train and dropout > 0:
            h = nn.Dropout(rate=dropout)(h, deterministic=False)
        return nn.Dense(self.num_classes)(h)


def efficientnet(variant: str = "efficientnet-b0",
                 num_classes: int = 1000) -> EfficientNet:
    assert variant in PARAMS, f"unknown variant {variant}"
    return EfficientNet(variant=variant, num_classes=num_classes)
