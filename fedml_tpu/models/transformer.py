"""Decoder-only transformer LM — the long-context extension of the zoo.

The reference's NLP models are LSTMs (fedml_api/model/nlp/rnn.py:5,41); this
model serves the same federated next-word-prediction task contract (logits
for every position, [B, T, V], like ``RNN_StackOverflow``) but scales to
long sequences: its attention is an injectable callable over [B, S, H, D],
so the same module runs

* single-device: the plain softmax oracle (default), or
* sequence-parallel: ``ring_attention``/``ulysses_attention`` from
  fedml_tpu/parallel/sequence.py, with the whole ``apply`` wrapped in
  ``shard_map`` over a ('seq',) — or ('clients', 'seq') — mesh.

TPU notes: widths default to MXU-friendly multiples of 128; everything is
static-shaped; the causal mask lives inside the attention callable so the
sequence axis can be sharded without materializing [S, S] anywhere.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

AttnFn = Callable[..., jnp.ndarray]  # (q, k, v, causal=...) -> out


def _default_attention(q, k, v, causal: bool = True):
    from fedml_tpu.parallel.sequence import reference_attention
    return reference_attention(q, k, v, causal=causal)


_AUTO_ATTENTION = None


def _auto_attention(q, k, v, causal: bool = True):
    """``attn_fn="auto"``: per-shape winner between the Pallas kernel's
    tuned blocks and the XLA reference, resolved (and memoized on disk)
    by :mod:`fedml_tpu.ops.autotune`. Process-wide singleton so every
    block and every model instance shares one decision memo."""
    global _AUTO_ATTENTION
    if _AUTO_ATTENTION is None:
        from fedml_tpu.ops.autotune import make_autotuned_attention
        _AUTO_ATTENTION = make_autotuned_attention()
    return _AUTO_ATTENTION(q, k, v, causal=causal)


def _resolve_attn(attn_fn) -> AttnFn:
    if attn_fn == "auto":
        return _auto_attention
    return attn_fn or _default_attention


class MoeFFN(nn.Module):
    """Switch-style MoE replacement for the block MLP (top-1 routing,
    fixed capacity; parallel/expert.py holds the routing math and the
    expert-parallel ``all_to_all`` version of the same computation). The
    load-balancing aux loss is sowed under ``intermediates/moe_aux``.

    ``ep_axis``: when the module runs inside ``shard_map`` over an expert-
    parallel mesh axis, set it (and pass ``n_shards``) to dispatch tokens
    with one all_to_all each way; the expert params must then be sharded
    [E/N, ...] on that axis (parallel/expert.expert_sharded_params spec).
    """

    n_experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    ep_axis: Optional[str] = None
    n_shards: int = 1

    @nn.compact
    def __call__(self, x):
        from fedml_tpu.parallel.expert import (expert_parallel_ffn,
                                               moe_ffn_local)

        b, s, w = x.shape
        tokens = x.reshape(b * s, w)
        hidden = self.mlp_ratio * w
        init = nn.initializers.lecun_normal()
        e_local = (self.n_experts // self.n_shards
                   if self.ep_axis else self.n_experts)
        params = {
            "router": self.param("router", init, (w, self.n_experts)),
            "w_up": self.param("w_up", init, (e_local, w, hidden)),
            "w_dn": self.param("w_dn", init, (e_local, hidden, w)),
        }
        capacity = max(1, int(self.capacity_factor * b * s
                              / self.n_experts))
        if self.ep_axis:
            out, aux = expert_parallel_ffn(tokens, params, self.n_experts,
                                           capacity, self.n_shards,
                                           self.ep_axis)
        else:
            out, aux = moe_ffn_local(tokens, params, capacity)
        self.sow("intermediates", "moe_aux", aux)
        return out.reshape(b, s, w)


class TransformerBlock(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    # None = plain softmax oracle; "auto" = ops.autotune per-shape winner
    attn_fn: Optional[AttnFn] = None
    moe_experts: int = 0  # >0: Switch MoE FFN instead of the dense MLP
    moe_ep_axis: Optional[str] = None  # expert-parallel mesh axis
    moe_n_shards: int = 1
    moe_capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, s, width = x.shape
        head_dim = width // self.num_heads
        attn = _resolve_attn(self.attn_fn)

        h = nn.LayerNorm()(x)
        qkv = nn.Dense(3 * width, use_bias=False)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape4 = (b, s, self.num_heads, head_dim)
        out = attn(q.reshape(shape4), k.reshape(shape4), v.reshape(shape4),
                   causal=True)
        out = nn.Dense(width, use_bias=False)(out.reshape(b, s, width))
        out = nn.Dropout(self.dropout, deterministic=not train)(out)
        x = x + out

        h = nn.LayerNorm()(x)
        if self.moe_experts > 0:
            h = MoeFFN(self.moe_experts, self.mlp_ratio,
                       capacity_factor=self.moe_capacity_factor,
                       ep_axis=self.moe_ep_axis,
                       n_shards=self.moe_n_shards)(h)
        else:
            h = nn.Dense(self.mlp_ratio * width)(h)
            h = nn.gelu(h)
            h = nn.Dense(width)(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return x + h


class TransformerLM(nn.Module):
    """Causal LM scoring every position (same output contract as
    RNN_StackOverflow: [B, T, vocab])."""

    vocab_size: int = 10004
    width: int = 256
    depth: int = 4
    num_heads: int = 4
    max_len: int = 2048
    dropout: float = 0.0
    # None = plain softmax oracle; "auto" = ops.autotune per-shape winner
    # (tuned Pallas blocks vs XLA reference, decision cached on disk);
    # or any (q, k, v, causal=...) callable, e.g. ring/ulysses attention
    attn_fn: Optional[AttnFn] = None
    moe_experts: int = 0   # >0: every `moe_every`-th block is a Switch MoE
    moe_every: int = 2
    moe_ep_axis: Optional[str] = None  # run MoE FFNs expert-parallel
    moe_n_shards: int = 1
    moe_capacity_factor: float = 1.25
    # rematerialize each block on the backward pass (jax.checkpoint):
    # activation memory drops from O(depth * S * width) to O(S * width)
    # at ~1/3 extra FLOPs — the standard long-context training trade
    remat: bool = False

    @nn.compact
    def __call__(self, input_seq, train: bool = False, pos_offset=0):
        # pos_offset: global position of this shard's first token — pass
        # axis_index('seq') * s_local when the sequence axis is sharded so
        # every shard reads its own slice of the learned position table
        # (the GLOBAL sequence must still fit in max_len; the static check
        # below can only see this shard's length)
        b, s = input_seq.shape
        if s > self.max_len:
            raise ValueError(f"sequence length {s} > max_len {self.max_len}; "
                             "nn.Embed would silently clamp positions")
        x = nn.Embed(self.vocab_size, self.width)(input_seq)
        pos = nn.Embed(self.max_len, self.width,
                       name="pos_embed")(jnp.arange(s) + pos_offset)
        x = x + pos[None]
        # nn.remat numbers args with the module instance at index 0, so in
        # __call__(self, x, train) the train flag is argnum 2; it must stay
        # static (it picks dropout branches)
        block_cls = (nn.remat(TransformerBlock, static_argnums=(2,))
                     if self.remat else TransformerBlock)
        for i in range(self.depth):
            is_moe = (self.moe_experts > 0
                      and (i + 1) % self.moe_every == 0)
            # explicit name: nn.remat would otherwise prefix the module
            # ("CheckpointTransformerBlock_i"), breaking param-tree
            # compatibility with the non-remat model and the TP specs
            x = block_cls(self.num_heads, dropout=self.dropout,
                          attn_fn=self.attn_fn,
                          moe_experts=(self.moe_experts
                                       if is_moe else 0),
                          moe_ep_axis=self.moe_ep_axis,
                          moe_n_shards=self.moe_n_shards,
                          moe_capacity_factor=(
                              self.moe_capacity_factor),
                          name=f"TransformerBlock_{i}")(x, train)
        x = nn.LayerNorm()(x)
        return nn.Dense(self.vocab_size)(x)
