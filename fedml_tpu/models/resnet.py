"""CIFAR ResNet-56/110 (bottleneck), flax/NHWC.

Reference: fedml_api/model/cv/resnet.py — CIFAR-style stem (3x3 conv, 16
channels, no maxpool), three stages at 16/32/64 planes with Bottleneck blocks
(expansion 4) of depth [6,6,6] (resnet56, :202) / [12,12,12] (resnet110,
:225), BatchNorm throughout, global average pool, linear head. The ``kd``
flag returns (features, logits) — used by GKT/knowledge-distillation setups
(resnet.py forward, KD branch).

BatchNorm runs through flax's ``batch_stats`` collection; the trainer treats
any non-``params`` collection as mutable in train mode and FedAvg aggregates
it like the reference averages the full state_dict.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.common import bn


class BottleneckBlock(nn.Module):
    planes: int
    stride: int = 1
    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = lambda: bn(train)
        identity = x
        out = nn.Conv(self.planes, (1, 1), use_bias=False)(x)
        out = nn.relu(norm()(out))
        out = nn.Conv(self.planes, (3, 3), strides=(self.stride, self.stride),
                      padding=1, use_bias=False)(out)
        out = nn.relu(norm()(out))
        out = nn.Conv(self.planes * self.expansion, (1, 1), use_bias=False)(out)
        out = norm()(out)
        if self.stride != 1 or x.shape[-1] != self.planes * self.expansion:
            identity = nn.Conv(self.planes * self.expansion, (1, 1),
                               strides=(self.stride, self.stride),
                               use_bias=False)(x)
            identity = norm()(identity)
        return nn.relu(out + identity)


class CifarResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 10
    kd: bool = False  # return (features, logits) for distillation

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), padding=1, use_bias=False)(x)
        x = nn.relu(bn(train)(x))
        for stage, blocks in enumerate(self.stage_sizes):
            planes = 16 * (2 ** stage)
            for b in range(blocks):
                stride = 2 if (stage > 0 and b == 0) else 1
                x = BottleneckBlock(planes, stride)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        logits = nn.Dense(self.num_classes)(x)
        if self.kd:
            return x, logits
        return logits


def resnet56(num_classes: int = 10, **kw) -> CifarResNet:
    return CifarResNet(stage_sizes=[6, 6, 6], num_classes=num_classes, **kw)


def resnet110(num_classes: int = 10, **kw) -> CifarResNet:
    return CifarResNet(stage_sizes=[12, 12, 12], num_classes=num_classes, **kw)
