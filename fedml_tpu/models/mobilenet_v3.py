"""MobileNetV3 (LARGE / SMALL) with squeeze-excite and h-swish.

Reference: fedml_api/model/cv/mobilenet_v3.py:137 ``MobileNetV3``:
inverted-residual bottlenecks with per-block expand size, optional SE module
(reduction 4, hard-sigmoid gate), ReLU or h-swish nonlinearity, width
multiplier, dropout before the classifier. NB: the LARGE table here follows
the paper (Howard et al., arXiv:1905.02244, Table 1) — the reference file's
last 160-stage differs slightly from the paper (stride-2 on its second block
with exp 672/672/960 instead of the paper's first-block stride-2 with
672/960/960); we keep the paper layout, so reference checkpoints for that
stage would not map 1:1.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.common import bn

# (kernel, exp_size, out_ch, SE, nonlinearity, stride) per block
LARGE: Sequence[Tuple] = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hswish", 2),
    (3, 200, 80, False, "hswish", 1),
    (3, 184, 80, False, "hswish", 1),
    (3, 184, 80, False, "hswish", 1),
    (3, 480, 112, True, "hswish", 1),
    (3, 672, 112, True, "hswish", 1),
    (5, 672, 160, True, "hswish", 2),
    (5, 960, 160, True, "hswish", 1),
    (5, 960, 160, True, "hswish", 1),
]
SMALL: Sequence[Tuple] = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hswish", 2),
    (5, 240, 40, True, "hswish", 1),
    (5, 240, 40, True, "hswish", 1),
    (5, 120, 48, True, "hswish", 1),
    (5, 144, 48, True, "hswish", 1),
    (5, 288, 96, True, "hswish", 2),
    (5, 576, 96, True, "hswish", 1),
    (5, 576, 96, True, "hswish", 1),
]


def hswish(x):
    return x * nn.relu6(x + 3.0) / 6.0


def hsigmoid(x):
    return nn.relu6(x + 3.0) / 6.0


def act(name: str):
    return hswish if name == "hswish" else nn.relu


class SqueezeExcite(nn.Module):
    reduction: int = 4

    @nn.compact
    def __call__(self, x):
        ch = x.shape[-1]
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(max(1, ch // self.reduction))(s))
        s = hsigmoid(nn.Dense(ch)(s))
        return x * s[:, None, None, :]


class InvertedResidual(nn.Module):
    kernel: int
    exp_size: int
    out_channels: int
    se: bool
    nonlinearity: str
    stride: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = lambda: bn(train)
        fn = act(self.nonlinearity)
        identity = x
        out = nn.Conv(self.exp_size, (1, 1), use_bias=False)(x)
        out = fn(norm()(out))
        out = nn.Conv(self.exp_size, (self.kernel, self.kernel),
                      strides=(self.stride, self.stride),
                      padding=self.kernel // 2,
                      feature_group_count=self.exp_size, use_bias=False)(out)
        out = fn(norm()(out))
        if self.se:
            out = SqueezeExcite()(out)
        out = nn.Conv(self.out_channels, (1, 1), use_bias=False)(out)
        out = norm()(out)
        if self.stride == 1 and x.shape[-1] == self.out_channels:
            out = out + identity
        return out


class MobileNetV3(nn.Module):
    num_classes: int = 1000
    model_mode: str = "LARGE"
    multiplier: float = 1.0
    dropout_rate: float = 0.0
    small_images: bool = True  # stride-1 stem for CIFAR-size inputs

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = LARGE if self.model_mode.upper() == "LARGE" else SMALL
        m = self.multiplier
        norm = lambda: bn(train)
        stem_stride = 1 if self.small_images else 2
        x = nn.Conv(int(16 * m), (3, 3), strides=(stem_stride, stem_stride),
                    padding=1, use_bias=False)(x)
        x = hswish(norm()(x))
        for k, exp, out, se, nl, s in cfg:
            x = InvertedResidual(k, int(exp * m), int(out * m), se, nl,
                                 s)(x, train=train)
        last_exp = int((960 if cfg is LARGE else 576) * m)
        x = nn.Conv(last_exp, (1, 1), use_bias=False)(x)
        x = hswish(norm()(x))
        x = jnp.mean(x, axis=(1, 2))
        x = hswish(nn.Dense(1280 if cfg is LARGE else 1024)(x))
        if self.dropout_rate:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)
