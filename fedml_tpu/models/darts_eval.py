"""DARTS evaluation network — train a *derived* genotype from scratch.

Reference: fedml_api/model/cv/darts/model.py (Cell:8-61 compiles the
discrete genotype into fixed ops with per-op drop-path,
AuxiliaryHeadCIFAR:64-83, NetworkCIFAR:111-160) and utils.py drop_path.
This is the second half of the NAS workflow: FedNAS searches with
models/darts.DartsNetwork, ``parse_genotype`` discretizes the alphas, and
this module retrains the winning architecture (affine BN, drop-path
regularization, optional auxiliary head at 2/3 depth).

TPU notes: the cell graph is static (op list fixed by the genotype), so the
whole network jits into one program; drop-path is a per-sample bernoulli
mask driven by a flax ``drop_path`` RNG collection.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models.darts import (DilConv, FactorizedReduce, Genotype,
                                    ReLUConvBN, SepConv, _bn, _pool)


def drop_path(x, rate: float, rng) -> jnp.ndarray:
    """Per-sample stochastic depth (reference utils.py drop_path): zero a
    sample's whole residual branch with prob ``rate``, rescale survivors."""
    keep = jax.random.bernoulli(rng, 1.0 - rate,
                                (x.shape[0],) + (1,) * (x.ndim - 1))
    return x * keep.astype(x.dtype) / (1.0 - rate)


class _FixedOp(nn.Module):
    """One discrete primitive with affine BN (reference OPS[name](C, stride,
    affine=True), model.py:44-46)."""

    prim: str
    C: int
    stride: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        p = self.prim
        if p == "max_pool_3x3":
            return _bn(train, True)(_pool(x, "max", self.stride))
        if p == "avg_pool_3x3":
            return _bn(train, True)(_pool(x, "avg", self.stride))
        if p == "skip_connect":
            return (x if self.stride == 1
                    else FactorizedReduce(self.C, affine=True)(x,
                                                               train=train))
        if p == "sep_conv_3x3":
            return SepConv(self.C, 3, self.stride, affine=True)(x,
                                                                train=train)
        if p == "sep_conv_5x5":
            return SepConv(self.C, 5, self.stride, affine=True)(x,
                                                                train=train)
        if p == "dil_conv_3x3":
            return DilConv(self.C, 3, self.stride, affine=True)(x,
                                                                train=train)
        if p == "dil_conv_5x5":
            return DilConv(self.C, 5, self.stride, affine=True)(x,
                                                                train=train)
        raise ValueError(f"primitive {self.prim!r} cannot appear in a "
                         "derived genotype")


class GenotypeCell(nn.Module):
    """Fixed cell compiled from one genotype half (reference Cell._compile /
    forward, model.py:28-61)."""

    genotype: Genotype
    C: int
    reduction: bool
    reduction_prev: bool
    drop_path_rate: float = 0.0

    @nn.compact
    def __call__(self, s0, s1, train: bool = False):
        if self.reduction_prev:
            s0 = FactorizedReduce(self.C, affine=True)(s0, train=train)
        else:
            s0 = ReLUConvBN(self.C, affine=True)(s0, train=train)
        s1 = ReLUConvBN(self.C, affine=True)(s1, train=train)

        gene = (self.genotype.reduce if self.reduction
                else self.genotype.normal)
        concat = (self.genotype.reduce_concat if self.reduction
                  else self.genotype.normal_concat)
        states = [s0, s1]
        for i in range(len(gene) // 2):
            h = None
            for prim, j in gene[2 * i:2 * i + 2]:
                stride = 2 if self.reduction and j < 2 else 1
                out = _FixedOp(prim, self.C, stride)(states[j], train=train)
                # drop-path skips identity ops (reference model.py:52-57)
                is_identity = prim == "skip_connect" and stride == 1
                if train and self.drop_path_rate > 0 and not is_identity:
                    out = drop_path(out, self.drop_path_rate,
                                    self.make_rng("drop_path"))
                h = out if h is None else h + out
            states.append(h)
        return jnp.concatenate([states[i] for i in concat], axis=-1)


class AuxiliaryHeadCIFAR(nn.Module):
    """8x8 feature maps -> aux logits (reference model.py:64-83)."""

    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(x)
        x = nn.avg_pool(x, (5, 5), strides=(3, 3))
        x = nn.Conv(128, (1, 1), use_bias=False)(x)
        x = nn.relu(_bn(train, True)(x))
        x = nn.Conv(768, (2, 2), use_bias=False, padding="VALID")(x)
        x = nn.relu(_bn(train, True)(x))
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(self.num_classes)(x)


class GenotypeNetwork(nn.Module):
    """NetworkCIFAR (reference model.py:111-160): stem, ``layers`` fixed
    cells with reductions at 1/3 and 2/3 depth, optional auxiliary head
    after the second reduction, pool + classifier.

    Returns logits, or (logits, aux_logits) when ``auxiliary`` and train.
    """

    genotype: Genotype
    C: int = 36
    num_classes: int = 10
    layers: int = 20
    auxiliary: bool = False
    stem_multiplier: int = 3
    drop_path_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        C_curr = self.stem_multiplier * self.C
        x = nn.Conv(C_curr, (3, 3), padding=1, use_bias=False)(x)
        x = _bn(train, True)(x)
        s0 = s1 = x
        C_curr = self.C
        reduction_prev = False
        aux_logits = None
        for i in range(self.layers):
            reduction = i in (self.layers // 3, 2 * self.layers // 3)
            if reduction:
                C_curr *= 2
            s0, s1 = s1, GenotypeCell(
                self.genotype, C_curr, reduction, reduction_prev,
                drop_path_rate=self.drop_path_rate)(s0, s1, train=train)
            reduction_prev = reduction
            # create the head whenever auxiliary so the params exist in both
            # modes (torch modules exist regardless of training state);
            # only the train-mode return includes its logits
            if i == 2 * self.layers // 3 and self.auxiliary:
                aux_logits = AuxiliaryHeadCIFAR(self.num_classes)(
                    s1, train=train)
        out = jnp.mean(s1, axis=(1, 2))
        logits = nn.Dense(self.num_classes)(out)
        if self.auxiliary and train:
            return logits, aux_logits
        return logits
