"""Vertical-FL party networks (reference fedml_api/model/finance/).

``VFLFeatureExtractor`` mirrors the 2-layer dense extractors of
vfl_models_standalone.py / vfl_feature_extractor.py (LocalModel: linear →
ReLU per layer); ``VFLDenseModel`` mirrors DenseModel (one linear unit that
maps party features to a scalar logit component; guest has bias, hosts do
not — party_models.py:21,90).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class VFLFeatureExtractor(nn.Module):
    hidden_dims: Sequence[int] = (32, 16)

    @nn.compact
    def __call__(self, x):
        for d in self.hidden_dims:
            x = nn.relu(nn.Dense(d)(x))
        return x

    @property
    def output_dim(self) -> int:
        return self.hidden_dims[-1]


class VFLDenseModel(nn.Module):
    output_dim: int = 1
    use_bias: bool = True

    @nn.compact
    def __call__(self, z):
        return nn.Dense(self.output_dim, use_bias=self.use_bias)(z)
