"""Segmentation model — a compact DeepLab-style encoder/ASPP/decoder in flax.

The reference's FedSeg rides torchvision DeepLab backbones plus its own
utils (fedml_api/distributed/fedseg/). Here the model is a TPU-friendly
fully-convolutional net: strided-conv encoder (output stride 4), an
atrous-spatial-pyramid ASPP block (parallel dilated 3x3 convs — all MXU
matmuls after im2col, cheap to fuse), and a bilinear-upsample head back to
input resolution. GroupNorm rather than BatchNorm so the same network is
robust under tiny federated client batches.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


def _gn(x, groups: int = 8):
    return nn.GroupNorm(num_groups=min(groups, x.shape[-1]))(x)


class ASPP(nn.Module):
    channels: int
    rates: Sequence[int] = (1, 2, 4)

    @nn.compact
    def __call__(self, x):
        branches = [nn.Conv(self.channels, (1, 1), use_bias=False)(x)]
        for r in self.rates:
            branches.append(
                nn.Conv(self.channels, (3, 3), kernel_dilation=r,
                        use_bias=False)(x))
        # image-level pooling branch
        pooled = jnp.mean(x, axis=(1, 2), keepdims=True)
        pooled = nn.Conv(self.channels, (1, 1), use_bias=False)(pooled)
        pooled = jnp.broadcast_to(
            pooled, x.shape[:3] + (self.channels,))
        cat = jnp.concatenate(branches + [pooled], axis=-1)
        out = nn.Conv(self.channels, (1, 1), use_bias=False)(cat)
        return nn.relu(_gn(out))


class SegNet(nn.Module):
    """Encoder (stride 4) -> ASPP -> classifier -> bilinear upsample."""

    num_classes: int = 21
    width: int = 32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h, w = x.shape[1], x.shape[2]
        y = nn.Conv(self.width, (3, 3), strides=2, use_bias=False)(x)
        y = nn.relu(_gn(y))
        y = nn.Conv(self.width * 2, (3, 3), strides=2, use_bias=False)(y)
        y = nn.relu(_gn(y))
        y = nn.Conv(self.width * 2, (3, 3), use_bias=False)(y)
        y = nn.relu(_gn(y))
        y = ASPP(self.width * 2)(y)
        logits = nn.Conv(self.num_classes, (1, 1))(y)
        return jax.image.resize(
            logits, (x.shape[0], h, w, self.num_classes), "bilinear")
