"""Flax model zoo — TPU-native re-expression of ``fedml_api/model``.

All modules share one calling convention: ``module.apply(variables, x,
train=bool)`` with NHWC image layout (TPU-friendly; the reference uses torch
NCHW). ``create_model`` mirrors the reference's experiment-level factory
(fedml_experiments/distributed/fedavg/main_fedavg.py:229-266).
"""

from fedml_tpu.models.lr import LogisticRegression
from fedml_tpu.models.cnn import CNN_DropOut


def create_model(model_name: str, output_dim: int = 10, **kw):
    """Model factory with reference naming (main_fedavg.py:229-266)."""
    if model_name == "lr":
        return LogisticRegression(num_classes=output_dim)
    if model_name == "cnn":
        return CNN_DropOut(only_digits=(output_dim == 10))
    raise ValueError(f"unknown model: {model_name!r}")
