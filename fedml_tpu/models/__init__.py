"""Flax model zoo — TPU-native re-expression of ``fedml_api/model``.

All modules share one calling convention: ``module.apply(variables, x,
train=bool)`` with NHWC image layout (TPU-friendly; the reference uses torch
NCHW). ``create_model`` mirrors the reference's experiment-level factory
(fedml_experiments/distributed/fedavg/main_fedavg.py:229-266).
"""

from fedml_tpu.models.lr import LogisticRegression
from fedml_tpu.models.cnn import CNN_DropOut


def create_model(model_name: str, output_dim: int = 10, **kw):
    """Model factory with reference naming (main_fedavg.py:229-266)."""
    if model_name == "lr":
        return LogisticRegression(num_classes=output_dim)
    if model_name == "cnn":
        return CNN_DropOut(only_digits=(output_dim == 10))
    if model_name in ("resnet18_gn", "resnet18"):
        from fedml_tpu.models.resnet_gn import resnet18_gn
        return resnet18_gn(num_classes=output_dim, **kw)
    if model_name == "resnet56":
        from fedml_tpu.models.resnet import resnet56
        return resnet56(num_classes=output_dim, **kw)
    if model_name == "resnet110":
        from fedml_tpu.models.resnet import resnet110
        return resnet110(num_classes=output_dim, **kw)
    if model_name == "mobilenet":
        from fedml_tpu.models.mobilenet import MobileNet
        return MobileNet(num_classes=output_dim, **kw)
    if model_name == "mobilenet_v3":
        from fedml_tpu.models.mobilenet_v3 import MobileNetV3
        return MobileNetV3(num_classes=output_dim, **kw)
    if model_name == "rnn":
        from fedml_tpu.models.rnn import RNN_OriginalFedAvg
        return RNN_OriginalFedAvg(**kw)
    if model_name == "rnn_seq":
        # per-position scoring over output_dim chars — the variant the
        # shakespeare/fed_shakespeare loaders need: both emit full shifted
        # target sequences [N, T] for the per-token nwp head (data/leaf.py
        # convert, data/tff_h5.py), so the LM must score every step
        from fedml_tpu.models.rnn import RNN_OriginalFedAvg
        return RNN_OriginalFedAvg(
            **{"vocab_size": output_dim, "seq_output": True, **kw})
    if model_name == "rnn_stackoverflow":
        from fedml_tpu.models.rnn import RNN_StackOverflow
        return RNN_StackOverflow(**kw)
    if model_name == "transformer":
        from fedml_tpu.models.transformer import TransformerLM
        return TransformerLM(vocab_size=output_dim, **kw)
    if model_name in ("vgg11", "vgg13", "vgg16", "vgg19"):
        from fedml_tpu.models.vgg import VGG
        return VGG(arch=model_name, num_classes=output_dim, **kw)
    if model_name.startswith("efficientnet"):
        from fedml_tpu.models.efficientnet import efficientnet
        return efficientnet(model_name, num_classes=output_dim)
    if model_name == "resnet8_gkt":
        from fedml_tpu.models.resnet_gkt import resnet8_56
        return resnet8_56(num_classes=output_dim)
    if model_name == "resnet56_gkt_server":
        from fedml_tpu.models.resnet_gkt import resnet56_server
        return resnet56_server(num_classes=output_dim)
    if model_name == "segnet":
        from fedml_tpu.models.segnet import SegNet
        return SegNet(num_classes=output_dim, **kw)
    if model_name == "darts":
        from fedml_tpu.models.darts import DartsNetwork
        return DartsNetwork(num_classes=output_dim, **kw)
    raise ValueError(f"unknown model: {model_name!r}")
