"""ResNet-18 with GroupNorm — the fed_cifar100 benchmark model.

Reference: fedml_api/model/cv/resnet_gn.py:183 ``resnet18`` with
``GroupNorm2d`` (group_normalization.py) in place of BatchNorm — the
normalization choice "Adaptive Federated Optimization" (arXiv:2003.00295)
uses for cross-device FL, since BN running statistics are ill-defined across
non-IID clients. GroupNorm has no running state, so the model's variables are
pure ``params`` (no mutable collections) — ideal for vmapped client training.

ImageNet-style basic-block layout [2,2,2,2] at 64/128/256/512 planes; for the
24x24 fed_cifar100 crops the 7x7-stride-2 stem + maxpool is replaced by a 3x3
stem (``small_images=True``, the standard CIFAR adaptation).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class GNBasicBlock(nn.Module):
    planes: int
    stride: int = 1
    channels_per_group: int = 32

    def _norm(self, channels):
        return nn.GroupNorm(
            num_groups=max(1, channels // self.channels_per_group))

    @nn.compact
    def __call__(self, x, train: bool = False):
        identity = x
        out = nn.Conv(self.planes, (3, 3), strides=(self.stride, self.stride),
                      padding=1, use_bias=False)(x)
        out = nn.relu(self._norm(self.planes)(out))
        out = nn.Conv(self.planes, (3, 3), padding=1, use_bias=False)(out)
        out = self._norm(self.planes)(out)
        if self.stride != 1 or x.shape[-1] != self.planes:
            identity = nn.Conv(self.planes, (1, 1),
                               strides=(self.stride, self.stride),
                               use_bias=False)(x)
            identity = self._norm(self.planes)(identity)
        return nn.relu(out + identity)


class ResNetGN(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 100
    channels_per_group: int = 32
    small_images: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = nn.GroupNorm(num_groups=max(1, 64 // self.channels_per_group))
        if self.small_images:
            x = nn.Conv(64, (3, 3), padding=1, use_bias=False)(x)
            x = nn.relu(norm(x))
        else:
            x = nn.Conv(64, (7, 7), strides=(2, 2), padding=3,
                        use_bias=False)(x)
            x = nn.relu(norm(x))
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, blocks in enumerate(self.stage_sizes):
            planes = 64 * (2 ** stage)
            for b in range(blocks):
                stride = 2 if (stage > 0 and b == 0) else 1
                x = GNBasicBlock(planes, stride,
                                 self.channels_per_group)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def resnet18_gn(num_classes: int = 100, channels_per_group: int = 32,
                small_images: bool = True) -> ResNetGN:
    return ResNetGN(stage_sizes=[2, 2, 2, 2], num_classes=num_classes,
                    channels_per_group=channels_per_group,
                    small_images=small_images)
