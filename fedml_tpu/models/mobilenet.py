"""MobileNet v1 (depthwise-separable), CIFAR variant.

Reference: fedml_api/model/cv/mobilenet.py:60 — stride-1 stem for 32x32
inputs, 3x3 depthwise + 1x1 pointwise blocks with BN+ReLU after each, width
multiplier alpha, channel ladder 32-64-128-256-512(x5)-1024, global average
pool + linear head (the cross-silo CIFAR/CINIC benchmark model,
benchmark rows 108-110).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.common import bn


class DepthwiseSeparable(nn.Module):
    out_channels: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = lambda: bn(train)
        in_ch = x.shape[-1]
        # depthwise: feature_group_count == in_channels
        x = nn.Conv(in_ch, (3, 3), strides=(self.stride, self.stride),
                    padding=1, feature_group_count=in_ch, use_bias=False)(x)
        x = nn.relu(norm()(x))
        x = nn.Conv(self.out_channels, (1, 1), use_bias=False)(x)
        return nn.relu(norm()(x))


class MobileNet(nn.Module):
    num_classes: int = 100
    width_multiplier: float = 1.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        a = self.width_multiplier
        ch = lambda c: int(c * a)
        norm = bn(train)
        x = nn.Conv(ch(32), (3, 3), padding=1, use_bias=False)(x)
        x = nn.relu(norm(x))
        x = DepthwiseSeparable(ch(64))(x, train)
        x = DepthwiseSeparable(ch(128), stride=2)(x, train)
        x = DepthwiseSeparable(ch(128))(x, train)
        x = DepthwiseSeparable(ch(256), stride=2)(x, train)
        x = DepthwiseSeparable(ch(256))(x, train)
        x = DepthwiseSeparable(ch(512), stride=2)(x, train)
        for _ in range(5):
            x = DepthwiseSeparable(ch(512))(x, train)
        x = DepthwiseSeparable(ch(1024), stride=2)(x, train)
        x = DepthwiseSeparable(ch(1024))(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)
