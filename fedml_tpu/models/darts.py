"""DARTS search space in flax — the FedNAS model family.

Reference: fedml_api/model/cv/darts/{operations.py:4 OPS,
model_search.py:10 MixedOp, :26 Cell, :172 Network, genotypes.py:5
PRIMITIVES, model_search.py:262 genotype parsing}.

TPU-first deltas:
- The architecture parameters (alphas) are NOT module parameters; the
  softmaxed mixing weights are explicit *inputs* to ``apply``. Bilevel
  optimization then falls out of ``jax.grad`` argnums — no parameter-group
  bookkeeping, no ``Architect`` object mutating ``.grad`` fields
  (architect.py:13), and the alternating weight/arch steps jit into one
  scanned program (algorithms/fednas.py).
- A MixedOp evaluates all primitive branches and contracts them with the
  mixing weights — on TPU the branches are independent convs XLA schedules
  back-to-back on the MXU; the contraction fuses into the epilogue.
- NHWC, BatchNorm in ``batch_stats`` (affine=False inside the search cells,
  as in the reference ops).
"""

from __future__ import annotations

from collections import namedtuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

Genotype = namedtuple("Genotype", "normal normal_concat reduce reduce_concat")

PRIMITIVES = [
    "none",
    "max_pool_3x3",
    "avg_pool_3x3",
    "skip_connect",
    "sep_conv_3x3",
    "sep_conv_5x5",
    "dil_conv_3x3",
    "dil_conv_5x5",
]


def _bn(train: bool, affine: bool = False):
    return nn.BatchNorm(use_running_average=not train, use_scale=affine,
                        use_bias=affine, momentum=0.9, epsilon=1e-5)


class ReLUConvBN(nn.Module):
    C_out: int
    kernel: int = 1
    stride: int = 1
    affine: bool = False  # search cells: affine-free BN; eval nets: True

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(x)
        x = nn.Conv(self.C_out, (self.kernel, self.kernel),
                    strides=self.stride, use_bias=False)(x)
        return _bn(train, self.affine)(x)


class FactorizedReduce(nn.Module):
    C_out: int
    affine: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(x)
        a = nn.Conv(self.C_out // 2, (1, 1), strides=2, use_bias=False)(x)
        b = nn.Conv(self.C_out - self.C_out // 2, (1, 1), strides=2,
                    use_bias=False)(x[:, 1:, 1:, :])
        return _bn(train, self.affine)(jnp.concatenate([a, b], axis=-1))


class SepConv(nn.Module):
    C_out: int
    kernel: int
    stride: int
    affine: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        C_in = x.shape[-1]
        k = (self.kernel, self.kernel)
        x = nn.relu(x)
        x = nn.Conv(C_in, k, strides=self.stride, feature_group_count=C_in,
                    use_bias=False)(x)
        x = nn.Conv(C_in, (1, 1), use_bias=False)(x)
        x = _bn(train, self.affine)(x)
        x = nn.relu(x)
        x = nn.Conv(C_in, k, feature_group_count=C_in, use_bias=False)(x)
        x = nn.Conv(self.C_out, (1, 1), use_bias=False)(x)
        return _bn(train, self.affine)(x)


class DilConv(nn.Module):
    C_out: int
    kernel: int
    stride: int
    dilation: int = 2
    affine: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        C_in = x.shape[-1]
        x = nn.relu(x)
        x = nn.Conv(C_in, (self.kernel, self.kernel), strides=self.stride,
                    kernel_dilation=self.dilation, feature_group_count=C_in,
                    use_bias=False)(x)
        x = nn.Conv(self.C_out, (1, 1), use_bias=False)(x)
        return _bn(train, self.affine)(x)


def _pool(x, kind: str, stride: int):
    window = (3, 3)
    strides = (stride, stride)
    if kind == "max":
        return nn.max_pool(x, window, strides=strides, padding="SAME")
    # count_include_pad=False semantics: normalize by the true window size
    ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
    summed = nn.avg_pool(x, window, strides=strides, padding="SAME") * 9.0
    counts = nn.avg_pool(ones, window, strides=strides, padding="SAME") * 9.0
    return summed / counts


class MixedOp(nn.Module):
    """All primitives evaluated, contracted with the mixing weights w
    (reference MixedOp.forward, model_search.py:21-23)."""

    C: int
    stride: int

    @nn.compact
    def __call__(self, x, w, train: bool = False):
        outs = []
        for prim in PRIMITIVES:
            if prim == "none":
                if self.stride == 1:
                    out = jnp.zeros_like(x)
                else:
                    out = jnp.zeros(
                        (x.shape[0], x.shape[1] // self.stride,
                         x.shape[2] // self.stride, self.C), x.dtype)
            elif prim == "max_pool_3x3":
                out = _bn(train)(_pool(x, "max", self.stride))
            elif prim == "avg_pool_3x3":
                out = _bn(train)(_pool(x, "avg", self.stride))
            elif prim == "skip_connect":
                out = (x if self.stride == 1
                       else FactorizedReduce(self.C)(x, train=train))
            elif prim == "sep_conv_3x3":
                out = SepConv(self.C, 3, self.stride)(x, train=train)
            elif prim == "sep_conv_5x5":
                out = SepConv(self.C, 5, self.stride)(x, train=train)
            elif prim == "dil_conv_3x3":
                out = DilConv(self.C, 3, self.stride)(x, train=train)
            elif prim == "dil_conv_5x5":
                out = DilConv(self.C, 5, self.stride)(x, train=train)
            outs.append(out)
        stacked = jnp.stack(outs, axis=0)  # [ops, B, H, W, C]
        return jnp.einsum("o,obhwc->bhwc", w, stacked)


class Cell(nn.Module):
    """steps intermediate nodes, each summing MixedOps from all predecessor
    states; output concat of the last ``multiplier`` states (reference Cell,
    model_search.py:26-60)."""

    steps: int
    multiplier: int
    C: int
    reduction: bool
    reduction_prev: bool

    @nn.compact
    def __call__(self, s0, s1, weights, train: bool = False):
        if self.reduction_prev:
            s0 = FactorizedReduce(self.C)(s0, train=train)
        else:
            s0 = ReLUConvBN(self.C)(s0, train=train)
        s1 = ReLUConvBN(self.C)(s1, train=train)
        states = [s0, s1]
        offset = 0
        for i in range(self.steps):
            s = None
            for j, h in enumerate(states):
                stride = 2 if self.reduction and j < 2 else 1
                out = MixedOp(self.C, stride)(h, weights[offset + j],
                                              train=train)
                s = out if s is None else s + out
            offset += len(states)
            states.append(s)
        return jnp.concatenate(states[-self.multiplier:], axis=-1)


class DartsNetwork(nn.Module):
    """Search network (reference Network, model_search.py:172-231): stem,
    ``layers`` cells with reductions at 1/3 and 2/3 depth, pool + classifier.
    ``weights_normal`` / ``weights_reduce`` are the softmaxed alphas
    [k, num_ops] — inputs, not parameters."""

    C: int = 16
    num_classes: int = 10
    layers: int = 8
    steps: int = 4
    multiplier: int = 4
    stem_multiplier: int = 3

    @staticmethod
    def num_edges(steps: int) -> int:
        return sum(2 + i for i in range(steps))

    @nn.compact
    def __call__(self, x, weights_normal, weights_reduce,
                 train: bool = False):
        C_curr = self.stem_multiplier * self.C
        x = nn.Conv(C_curr, (3, 3), padding=1, use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        s0 = s1 = x
        C_curr = self.C
        reduction_prev = False
        for i in range(self.layers):
            reduction = i in (self.layers // 3, 2 * self.layers // 3)
            if reduction:
                C_curr *= 2
            w = weights_reduce if reduction else weights_normal
            s0, s1 = s1, Cell(self.steps, self.multiplier, C_curr, reduction,
                              reduction_prev)(s0, s1, w, train=train)
            reduction_prev = reduction
        out = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes)(out)


def init_alphas(steps: int, rng: np.random.RandomState):
    """1e-3 * randn [k, num_ops] for normal + reduce (reference
    _initialize_alphas, model_search.py:232-241)."""
    k = DartsNetwork.num_edges(steps)
    return (np.asarray(1e-3 * rng.randn(k, len(PRIMITIVES)), np.float32),
            np.asarray(1e-3 * rng.randn(k, len(PRIMITIVES)), np.float32))


def gumbel_softmax_weights(key, alphas, tau: float, hard: bool = True):
    """GDAS single-path sampling (reference Network_GumbelSoftmax.forward,
    model_search_gdas.py:122-133: ``F.gumbel_softmax(alphas, tau, True)``).

    Straight-through estimator: forward sees a one-hot per edge (one primitive
    active), backward flows through the soft gumbel-softmax. The reference's
    eager-mode trick of *skipping* zero-weight branches
    (model_search_gdas.py MixedOp.forward cpu_weights test) is
    data-dependent control flow XLA can't tile; here all branches run and the
    one-hot contraction selects — on the MXU the branch convs are batched
    back-to-back and the masked sum fuses into their epilogue, which is
    faster than eight ``lax.cond`` branches serializing.
    """
    import jax
    import jax.numpy as jnp_

    gumbel = -jnp_.log(-jnp_.log(
        jax.random.uniform(key, alphas.shape, minval=1e-20, maxval=1.0)))
    soft = jax.nn.softmax((alphas + gumbel) / tau, axis=-1)
    if not hard:
        return soft
    onehot = jax.nn.one_hot(jnp_.argmax(soft, axis=-1), soft.shape[-1],
                            dtype=soft.dtype)
    return onehot + soft - jax.lax.stop_gradient(soft)  # ST gradient


def gdas_tau(epoch: int, total_epochs: int, tau_max: float = 10.0,
             tau_min: float = 0.1) -> float:
    """Linear temperature annealing tau_max → tau_min over the search
    (the schedule GDAS drives through the reference's ``set_tau``,
    model_search_gdas.py:117-120; the paper's 10 → 0.1 default)."""
    if total_epochs <= 1:
        return tau_min
    frac = min(max(epoch / (total_epochs - 1), 0.0), 1.0)
    return tau_max + (tau_min - tau_max) * frac


def parse_genotype(alphas_normal: np.ndarray,
                   alphas_reduce: np.ndarray, steps: int = 4,
                   multiplier: int = 4) -> Genotype:
    """Discretize softmaxed alphas into the best-2-edges-per-node genotype
    (reference Network.genotype, model_search.py:262-296)."""

    def softmax(a):
        e = np.exp(a - a.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)

    none_idx = PRIMITIVES.index("none")

    def _parse(weights):
        gene = []
        start, n = 0, 2
        for i in range(steps):
            W = weights[start:start + n]
            edges = sorted(
                range(n),
                key=lambda j: -max(W[j][k] for k in range(len(W[j]))
                                   if k != none_idx))[:2]
            for j in edges:
                k_best = max((k for k in range(len(W[j])) if k != none_idx),
                             key=lambda k: W[j][k])
                gene.append((PRIMITIVES[k_best], j))
            start += n
            n += 1
        return tuple(gene)  # hashable: genotypes feed flax module fields

    concat = tuple(range(2 + steps - multiplier, steps + 2))
    return Genotype(normal=_parse(softmax(alphas_normal)),
                    normal_concat=concat,
                    reduce=_parse(softmax(alphas_reduce)),
                    reduce_concat=concat)
