"""VGG-11/13/16/19 with optional BatchNorm.

Reference: fedml_api/model/cv/vgg.py:13-159 — the torchvision config-letter
construction ('A'/'B'/'D'/'E' channel lists with 'M' maxpools) and factory
functions vgg11..vgg19_bn. CIFAR-sized head: the flattened features feed a
4096-4096-classes classifier with dropout.
"""

from __future__ import annotations

from typing import Sequence, Union

import flax.linen as nn

from fedml_tpu.models.common import bn

CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}
ARCH_TO_CFG = {"vgg11": "A", "vgg13": "B", "vgg16": "D", "vgg19": "E"}


class VGG(nn.Module):
    arch: str = "vgg11"
    num_classes: int = 10
    batch_norm: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.shape[1] < 32 or x.shape[2] < 32:
            raise ValueError(
                f"VGG needs inputs >= 32x32 (five 2x2 maxpools); got "
                f"{x.shape[1]}x{x.shape[2]}")
        cfg: Sequence[Union[int, str]] = CFGS[ARCH_TO_CFG[self.arch]]
        for v in cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(v), (3, 3), padding=1)(x)
                if self.batch_norm:
                    x = bn(train)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)
