"""Genotype visualization — the role of the reference's graphviz plotter.

Reference (fedml_api/model/cv/darts/visualize.py:6-39): builds a Digraph
with c_{k-2}/c_{k-1} input nodes, one node per intermediate step, edges
labelled by primitive, all steps feeding c_{k}; rendered to PDF via the
graphviz binary. This environment has no graphviz, so :func:`genotype_to_dot`
emits the same graph as portable DOT source (renderable anywhere with
``dot -Tpdf``), :func:`plot` writes ``<name>.dot`` files, and
:func:`format_genotype` gives a terminal-friendly summary for round logs
(the FedNAS aggregator logs the genotype every round,
FedNASAggregator.py:173).
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple

Edge = Tuple[str, int]  # (primitive, predecessor index)

_NODE_STYLE = ('style=filled shape=rect align=center fontsize=20 '
               'height=0.5 width=0.5 penwidth=2 fontname=times')


def _src_name(j: int) -> str:
    if j == 0:
        return "c_{k-2}"
    if j == 1:
        return "c_{k-1}"
    return str(j - 2)


def genotype_to_dot(gene: Sequence[Edge], name: str = "cell") -> str:
    """DOT source for one cell (normal or reduce): 2 edges per step."""
    if len(gene) % 2:
        raise ValueError(f"genotype has odd edge count {len(gene)}")
    steps = len(gene) // 2
    lines: List[str] = [
        f'digraph "{name}" {{',
        "  rankdir=LR;",
        f"  node [{_NODE_STYLE}];",
        '  edge [fontsize=20 fontname=times];',
        '  "c_{k-2}" [fillcolor=darkseagreen2];',
        '  "c_{k-1}" [fillcolor=darkseagreen2];',
    ]
    for i in range(steps):
        lines.append(f'  "{i}" [fillcolor=lightblue];')
    lines.append('  "c_{k}" [fillcolor=palegoldenrod];')
    for i in range(steps):
        for op, j in gene[2 * i:2 * i + 2]:
            lines.append(f'  "{_src_name(j)}" -> "{i}" [label="{op}"];')
    for i in range(steps):
        lines.append(f'  "{i}" -> "c_{{k}}";')
    lines.append("}")
    return "\n".join(lines) + "\n"


def plot(genotype, directory: str, prefix: str = "") -> List[str]:
    """Write ``<prefix>normal.dot`` / ``<prefix>reduction.dot`` (the two
    files the reference renders, visualize.py:55-56). Returns the paths."""
    os.makedirs(directory, exist_ok=True)
    paths = []
    for cell_name, gene in (("normal", genotype.normal),
                            ("reduction", genotype.reduce)):
        path = os.path.join(directory, f"{prefix}{cell_name}.dot")
        with open(path, "w") as fh:
            fh.write(genotype_to_dot(gene, name=cell_name))
        paths.append(path)
    return paths


def format_genotype(genotype) -> str:
    """One-line-per-node text rendering for round logs."""
    out = []
    for cell_name, gene, concat in (
            ("normal", genotype.normal, genotype.normal_concat),
            ("reduce", genotype.reduce, genotype.reduce_concat)):
        out.append(f"{cell_name} (concat {list(concat)}):")
        for i in range(len(gene) // 2):
            edges = ", ".join(f"{op}({_src_name(j)})"
                              for op, j in gene[2 * i:2 * i + 2])
            out.append(f"  node {i} <- {edges}")
    return "\n".join(out)
