"""The FedAvg 2-conv CNN ("Adaptive Federated Optimization", arXiv:2003.00295).

Reference: fedml_api/model/cv/cnn.py:75-144 ``CNN_DropOut`` (NB the reference
file is corrupted by a bad F->self replace — ``nn.selflatten`` etc.; we build
the documented architecture from its own summary table):

    28x28x1 -> conv3x3(32) VALID + relu -> conv3x3(64) VALID + relu
    -> maxpool2x2 -> dropout(.25) -> flatten(9216) -> dense(128) + relu
    -> dropout(.5) -> dense(10 | 62)

1,199,882 params for the 10-class variant. NHWC layout; accepts [B, 28, 28]
or [B, 28, 28, 1].
"""

from __future__ import annotations

import flax.linen as nn


class CNN_DropOut(nn.Module):
    only_digits: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID")(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(10 if self.only_digits else 62)(x)
