from fedml_tpu.parallel.spmd import (
    build_mesh,
    make_spmd_round,
    make_hierarchical_spmd_round,
    DistributedFedAvgAPI,
)
