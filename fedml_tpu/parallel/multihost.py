"""Multi-host execution: rendezvous, global meshes, per-host data feeding.

The reference scales out with ``mpirun -np N -hostfile mpi_host_file``
(run_fedavg_distributed_pytorch.sh:19-22) and mpi4py point-to-point sends.
The TPU-native equivalent has no application-level messaging at all:

1. every host calls :func:`initialize` (``jax.distributed.initialize`` —
   coordinator rendezvous over DCN, the role of the MPI hostfile);
2. :func:`global_client_mesh` builds one mesh over ALL hosts' devices —
   XLA then routes ``psum`` over ICI within a slice and DCN across slices,
   replacing rank-0 aggregation entirely;
3. each host feeds only the shards it owns (:func:`local_client_slice` /
   :func:`host_local_to_global`), the multi-host analogue of the
   reference's per-rank dataset virtualization (FedAVGTrainer.update_dataset).

Single-host runs need none of this — every helper degrades gracefully to
process_count == 1 (which is also how unit tests cover the logic).
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


_initialized = False

# env vars whose presence means jax.distributed.initialize() can auto-detect
# the cluster (TPU pod metadata / Slurm / explicit JAX coordinator)
_CLUSTER_ENV_VARS = ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                     "MEGASCALE_COORDINATOR_ADDRESS", "SLURM_JOB_ID",
                     "TPU_WORKER_HOSTNAMES")


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               **kw) -> Tuple[int, int]:
    """Join the multi-host job (idempotent). Returns (process_id, count).

    MUST run before any other JAX call (touching jax.devices() or
    jax.process_count() first initializes the local backend, after which
    rendezvous is impossible — jax.distributed.initialize raises). With no
    arguments, attempts environment auto-detection when a cluster env var
    is present; otherwise single-host, returning (0, 1).
    """
    global _initialized
    import os

    explicit = coordinator_address is not None
    if not _initialized and (explicit or any(v in os.environ
                                             for v in _CLUSTER_ENV_VARS)):
        try:
            if explicit:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id, **kw)
            else:
                jax.distributed.initialize(**kw)  # env auto-detection
            _initialized = True
        except (RuntimeError, ValueError) as exc:
            if explicit:
                # the caller asked for multi-host; degrading silently would
                # leave every host training in isolation
                raise RuntimeError(
                    "multi-host rendezvous failed — initialize() must be "
                    "the first JAX call in the process: " + str(exc)
                ) from exc
            logging.warning("distributed auto-init failed, running "
                            "single-host: %s", exc)
    return jax.process_index(), jax.process_count()


def global_client_mesh(clients_per_host: Optional[int] = None,
                       group_axis_from_hosts: bool = False) -> Mesh:
    """One mesh over every device of every host.

    ``group_axis_from_hosts=True`` maps hierarchical FL onto the physical
    topology: hosts become the ``group`` axis (edge servers), each host's
    devices the ``clients`` axis — so group aggregation's psum rides ICI
    and only the cloud step crosses DCN.
    """
    devs = jax.devices()
    if group_axis_from_hosts:
        n_hosts = jax.process_count()
        per_host = len(devs) // n_hosts
        rows = [[d for d in devs if d.process_index == h][:per_host]
                for h in range(n_hosts)]
        return Mesh(np.asarray(rows, dtype=object), ("group", "clients"))
    if clients_per_host:
        # take k devices from EVERY host (jax.devices() orders by process,
        # so a flat [:k*hosts] slice would use only the first hosts)
        picked = [d for h in range(jax.process_count())
                  for d in [x for x in devs if x.process_index == h]
                  [:clients_per_host]]
        return Mesh(np.asarray(picked), ("clients",))
    return Mesh(np.asarray(devs), ("clients",))


def local_client_slice(mesh: Mesh, n_items: int,
                       axis: str = "clients") -> Tuple[int, int]:
    """[start, stop) of the global client-batch rows THIS host must feed.

    The multi-host data contract: every host materializes only its slice of
    the stacked per-client arrays (the reference instead sent each rank its
    sampled client's data by re-pointing the loader, fedavg_api.py:65-70).
    """
    if mesh.devices.ndim != 1:
        raise ValueError(
            "local_client_slice addresses a 1-D client mesh; for a "
            "('group', 'clients') mesh the stacked arrays are sharded over "
            "both axes — build the global array directly with "
            "host_local_to_global/make_array_from_process_local_data")
    axis_size = mesh.shape[axis]
    if n_items % axis_size:
        raise ValueError(f"{n_items} rows not divisible by {axis} axis "
                         f"({axis_size})")
    per_shard = n_items // axis_size
    # which shard indices live on this process
    my = [i for i, d in enumerate(mesh.devices)
          if d.process_index == jax.process_index()]
    if not my:
        return 0, 0
    if my != list(range(my[0], my[-1] + 1)):
        raise ValueError(
            f"this host's shard indices {my} are not contiguous on the "
            f"{axis!r} axis; reorder the mesh devices by process so each "
            "host feeds one contiguous row block")
    return my[0] * per_shard, (my[-1] + 1) * per_shard


def host_local_to_global(mesh: Mesh, local_arrays, n_global: int,
                         axis: str = "clients"):
    """Assemble a global device array from each host's local rows
    (``jax.make_array_from_process_local_data``); single-process: identity
    device_put with the mesh sharding."""
    spec = P(axis)
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.tree.map(lambda a: jax.device_put(a, sharding),
                            local_arrays)
    return jax.tree.map(
        lambda a: jax.make_array_from_process_local_data(sharding, a),
        local_arrays)


def all_hosts_agree(value: int) -> bool:
    """Cheap cross-host desync detector (round index, sampled-client hash):
    allgather the value and check every host reported the same. Single
    host: trivially True."""
    if jax.process_count() == 1:
        return True
    from jax.experimental import multihost_utils  # pragma: no cover

    gathered = multihost_utils.process_allgather(np.asarray([value]))
    return bool(np.all(gathered == value))
