"""Expert parallelism: a Switch-style MoE FFN sharded over an ``ep`` axis.

The reference has nothing in this class (its models top out at ResNet-56);
this is the framework's expert-parallel axis so federated LM training can
scale parameters past one chip's HBM. Design follows the standard TPU MoE
recipe (Switch Transformer):

- top-1 router over E experts, with a fixed per-expert ``capacity`` so every
  shape is static (overflow tokens fall through on the residual path);
- experts live sharded over the ``ep`` axis (each device owns E/N experts'
  FFN weights) — the parameter memory scales with the mesh;
- dispatch/return are each ONE ``all_to_all`` over ICI: tokens are binned
  into per-expert capacity buffers with a one-hot matmul (static shapes, no
  scatter), exchanged, FFN'd by the owning device, and exchanged back.

Everything is a pure function of per-shard arrays under ``shard_map``;
composes with the other axes (('clients', 'ep') gives each federated
client an expert-parallel sub-mesh).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.utils.jax_compat import install_jax_compat

install_jax_compat()


def init_moe_params(key, n_experts: int, width: int, hidden: int):
    """Stacked expert FFN params: w_up [E, w, h], w_dn [E, h, w], and the
    router [w, E]."""
    k1, k2, k3 = jax.random.split(key, 3)
    scale_up = 1.0 / np.sqrt(width)
    scale_dn = 1.0 / np.sqrt(hidden)
    return {
        "router": jax.random.normal(k1, (width, n_experts)) * scale_up,
        "w_up": jax.random.normal(k2, (n_experts, width, hidden)) * scale_up,
        "w_dn": jax.random.normal(k3, (n_experts, hidden, width)) * scale_dn,
    }


def _aux_loss(frac, mean_prob):
    """Switch load-balancing loss from its two statistics: E * Σ_e
    (token fraction to e) * (mean router prob of e)."""
    return frac.shape[-1] * jnp.sum(frac * mean_prob)


def _route_top1(x, router, n_experts: int, capacity: int):
    """Top-1 routing with capacity: returns (dispatch [T, E, C] one-hot,
    combine [T, E, C] prob-weighted, (frac, mean_prob) aux statistics)."""
    T = x.shape[0]
    logits = x @ router                               # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)               # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], -1)[:, 0]

    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)  # [T, E]
    # position of each token within its expert's queue (cumsum trick)
    pos = jnp.cumsum(onehot, axis=0) * onehot         # [T, E], 1-based
    pos = jnp.sum(pos, axis=-1) - 1.0                 # [T], 0-based
    keep = pos < capacity
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=jnp.float32)        # [T, C]
    dispatch = (onehot * keep[:, None])[:, :, None] * pos_oh[:, None, :]
    combine = dispatch * gate[:, None, None]

    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    return dispatch, combine, (frac, mean_prob)


def moe_ffn_local(x, params, capacity: int):
    """Single-device MoE FFN (the oracle for the sharded version).
    x: [T, w] -> [T, w]."""
    n_experts = params["router"].shape[-1]
    dispatch, combine, (frac, mean_prob) = _route_top1(
        x, params["router"], n_experts, capacity)
    aux = _aux_loss(frac, mean_prob)
    # [E, C, w] expert input buffers via one-hot contraction (no scatter)
    buffers = jnp.einsum("tec,tw->ecw", dispatch, x)
    h = jax.nn.gelu(jnp.einsum("ecw,ewh->ech", buffers, params["w_up"]))
    out_buf = jnp.einsum("ech,ehw->ecw", h, params["w_dn"])
    out = jnp.einsum("tec,ecw->tw", combine, out_buf)
    return out, aux


def expert_parallel_ffn(x, params, n_experts: int, capacity: int,
                        n_shards: int, axis: str = "ep"):
    """The collective MoE FFN body (call inside shard_map): x [T_local, w]
    tokens sharded on the batch axis, params sharded with router replicated
    and w_up/w_dn [E_local, ...] on the same ``axis``; one all_to_all each
    way."""
    dispatch, combine, (frac, mean_prob) = _route_top1(
        x, params["router"], n_experts, capacity)
    # globalize the statistics BEFORE the product so the sharded aux
    # equals the single-device aux exactly (the loss is nonlinear)
    aux = _aux_loss(jax.lax.pmean(frac, axis),
                    jax.lax.pmean(mean_prob, axis))
    buffers = jnp.einsum("tec,tw->ecw", dispatch, x)  # [E, C, w]
    # exchange: every shard sends each expert-group its buffers;
    # arrives as [E_local, N*C, w] after re-gluing the shard axis
    buffers = buffers.reshape(n_shards, n_experts // n_shards,
                              capacity, x.shape[-1])
    recv = jax.lax.all_to_all(buffers, axis, split_axis=0,
                              concat_axis=0, tiled=False)
    # recv: [N, E_local, C, w] — N source shards' queues per local expert
    e_loc = n_experts // n_shards
    recv = recv.transpose(1, 0, 2, 3).reshape(
        e_loc, n_shards * capacity, x.shape[-1])
    h = jax.nn.gelu(jnp.einsum("ecw,ewh->ech", recv, params["w_up"]))
    out_buf = jnp.einsum("ech,ehw->ecw", h, params["w_dn"])
    # return trip: split back per source shard and all_to_all home
    out_buf = out_buf.reshape(e_loc, n_shards, capacity,
                              x.shape[-1]).transpose(1, 0, 2, 3)
    back = jax.lax.all_to_all(out_buf, axis, split_axis=0,
                              concat_axis=0, tiled=False)
    back = back.reshape(n_experts, capacity, x.shape[-1])
    out = jnp.einsum("tec,ecw->tw", combine, back)
    return out, aux


def make_expert_parallel_ffn(mesh: Mesh, n_experts: int, capacity: int,
                             axis: str = "ep"):
    """Build ``ffn(x_local, params_sharded) -> (out_local, aux)`` to run
    under shard_map (see :func:`expert_parallel_ffn`)."""
    n_shards = mesh.shape[axis]
    if n_experts % n_shards:
        raise ValueError(f"n_experts={n_experts} must divide over "
                         f"{axis}={n_shards}")

    def ffn(x, params):
        return expert_parallel_ffn(x, params, n_experts, capacity, n_shards,
                                   axis)

    return ffn


def expert_sharded_params(params, mesh: Mesh, axis: str = "ep"):
    """Place MoE params: experts split over ``ep``, router replicated."""
    from jax.sharding import NamedSharding

    specs = {"router": P(), "w_up": P(axis, None, None),
             "w_dn": P(axis, None, None)}
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()}


def make_moe_step(mesh: Mesh, n_experts: int, capacity: int,
                  axis: str = "ep"):
    """Jitted shard_map wrapper: x sharded on tokens, params on experts."""
    ffn = make_expert_parallel_ffn(mesh, n_experts, capacity, axis)
    pspecs = {"router": P(), "w_up": P(axis, None, None),
              "w_dn": P(axis, None, None)}
    return jax.jit(jax.shard_map(
        ffn, mesh=mesh, in_specs=(P(axis), pspecs),
        out_specs=(P(axis), P())))
