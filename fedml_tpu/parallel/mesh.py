"""Named multi-axis SPMD federation mesh — data x fsdp x tp on ONE mesh.

Before this module the parallel layer had three disjoint 1-D/2-D meshes:
the fused round scan lived on a ``('clients',)`` shard_map mesh
(parallel/spmd.py), ZeRO sharding on an ``('fsdp',)`` mesh with its own
largest-axis rule (parallel/fsdp.py), and Megatron TP on a ``('tp',)``
mesh with its own name rules (parallel/tensor.py). They could not
compose: a federated round was either data-parallel OR model-sharded,
and every measured bench row ran one chip while the multichip story
lived in a dryrun artifact (``MULTICHIP_r*.json``).

This module promotes all of it to one canonical named mesh:

- ``data``  — sampled clients (the federation axis; what spmd.py calls
  ``clients``). The cross-client weighted FedAvg mean reduces over it.
- ``fsdp``  — ZeRO-3 parameter sharding: each leaf sharded on its
  largest divisible axis, small leaves replicated (the fsdp.py rule,
  imported — ONE copy).
- ``tp``    — Megatron tensor parallelism for the transformer's Dense
  kernels (column/row split sets imported from tensor.py — ONE copy).

:class:`SpecLayout` is the single canonical per-parameter PartitionSpec
assignment: name/shape rules that reduce exactly to ``fsdp_specs`` when
only ``fsdp`` is present and to ``transformer_tp_specs`` when only
``tp`` is present (pinned by tests/test_mesh_layout.py), and compose
both on a 3-D mesh. Divisibility is guarded per-dimension — a dim is
never oversharded past its size — and the replicated ``P()`` fallback
is explicit.

The round programs are pure GSPMD (``jax.jit`` + ``NamedSharding``,
like gspmd_round.py): shard_map's replicated-params contract cannot
express parameters that are *sharded* over ``fsdp``/``tp`` while the
client batch varies over ``data``, so XLA's SPMD partitioner inserts
the collectives the layout implies. ``make_mesh_block_multiround`` is
the fused sampled-round scan (the spmd.make_spmd_block_multiround
program shape) on the named mesh; its round body is literally the sim
driver's (``make_vmapped_body`` + ``pt.tree_weighted_mean`` + the
shared ``round_keys`` fold_in chain), so a ``{data: 1}`` mesh
reproduces the sim trajectory bit-exactly.

CLI (used by ci/run_fast.sh and bench.py):

    python -m fedml_tpu.parallel.mesh --smoke
    python -m fedml_tpu.parallel.mesh --bench-worker --workload \
        transformer_flash_s2048 --mesh data=8 --force-host
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.core.sampling import round_keys
from fedml_tpu.parallel.fsdp import leaf_fsdp_spec
from fedml_tpu.parallel.tensor import COLUMN_PARALLEL, ROW_PARALLEL
from fedml_tpu.trainer.functional import (TrainConfig, make_local_train,
                                          round_lr_scale)

#: canonical axis order — every named federation mesh declares its axes
#: in this order so mesh shapes print/compare stably
MESH_AXES = ("data", "fsdp", "tp")


def parse_mesh_shape(spec: str) -> Dict[str, int]:
    """``"data=4,fsdp=2"`` -> ``{"data": 4, "fsdp": 2}`` (canonical axis
    order, unknown axis names rejected loudly)."""
    shape: Dict[str, int] = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"mesh shape needs axis=size entries, got {part!r} "
                f"(e.g. 'data=4,fsdp=2')")
        name, _, size = part.partition("=")
        name = name.strip()
        if name not in MESH_AXES:
            raise ValueError(
                f"unknown mesh axis {name!r}; valid axes: {MESH_AXES}")
        n = int(size)
        if n < 1:
            raise ValueError(f"mesh axis {name}={n} must be >= 1")
        shape[name] = n
    if "data" not in shape:
        raise ValueError("mesh shape must include the 'data' axis")
    return {a: shape[a] for a in MESH_AXES if a in shape}


def build_named_mesh(shape: Dict[str, int],
                     devices: Optional[list] = None) -> Mesh:
    """Named federation mesh in canonical axis order. Unlike
    ``spmd.build_mesh`` the mesh may span a PREFIX of the local devices
    (a 2-device mesh on an 8-virtual-device CI host), so parity tests
    can build {1, 2, 4, 8}-device meshes side by side."""
    ordered = {a: int(shape[a]) for a in MESH_AXES if a in shape}
    unknown = set(shape) - set(ordered)
    if unknown:
        raise ValueError(
            f"unknown mesh axes {sorted(unknown)}; valid axes: {MESH_AXES}")
    n = int(np.prod(list(ordered.values()))) if ordered else 0
    if n < 1:
        raise ValueError(f"empty mesh shape: {shape!r}")
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < n:
        raise ValueError(
            f"mesh shape {ordered} needs {n} devices, have {len(devs)}")
    from fedml_tpu.parallel.spmd import build_mesh

    return build_mesh(ordered, devices=devs[:n])


def _path_names(path) -> list:
    return [getattr(p, "key", getattr(p, "name", "")) for p in path]


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """The canonical per-parameter PartitionSpec layout for a named
    data x fsdp x tp mesh — ONE rule set unifying the ad-hoc pair that
    grew on disjoint meshes:

    - transformer Dense kernels get the Megatron split (column-parallel
      ``Dense_0``/``Dense_2`` + logit head on ``tp`` dim 1, row-parallel
      ``Dense_1``/``Dense_3`` on ``tp`` dim 0 — the tensor.py sets,
      imported), with the OTHER kernel dim ZeRO-sharded over ``fsdp``
      when divisible;
    - every other leaf (conv kernels, embeddings, heads of non-TP
      models) follows the ZeRO largest-divisible-axis rule
      (fsdp.leaf_fsdp_spec, imported) over ``fsdp``;
    - leaves smaller than ``min_size`` elements (LayerNorm/GroupNorm
      scales, biases) replicate — gathering them costs more than
      storing them;
    - a dim is sharded only when the axis size divides it (never
      oversharded past its size); anything unmatched falls back to the
      explicit replicated ``P()``.

    Axis sizes are read from the mesh, so the same layout object serves
    any mesh shape: absent/size-1 axes simply drop out of the specs
    (a ``{data: 1}`` mesh yields all-replicated params — the sim
    program).
    """

    data_axis: str = "data"
    fsdp_axis: str = "fsdp"
    tp_axis: str = "tp"
    min_size: int = 1024

    def axis_size(self, mesh: Mesh, axis: str) -> int:
        return int(dict(mesh.shape).get(axis, 1))

    def param_spec(self, path, leaf, mesh: Mesh) -> P:
        names = _path_names(path)
        shape = tuple(getattr(leaf, "shape", ()))
        tp_n = self.axis_size(mesh, self.tp_axis)
        fsdp_n = self.axis_size(mesh, self.fsdp_axis)
        in_block = any(n.startswith("TransformerBlock") for n in names)
        module = next((n for n in reversed(names)
                       if n.startswith(("Dense", "Embed", "LayerNorm",
                                        "pos_embed"))), "")
        leaf_name = names[-1] if names else ""
        # -- Megatron split for transformer Dense leaves (tensor.py rule)
        if tp_n > 1 and module.startswith("Dense") \
                and (in_block or module == "Dense_0"):
            column = (module in COLUMN_PARALLEL if in_block
                      else True)  # top-level Dense_0: logit head (vocab)
            row = in_block and module in ROW_PARALLEL
            if leaf_name == "kernel" and len(shape) == 2 \
                    and (column or row):
                tp_dim = 1 if column else 0
                dims: list = [None, None]
                if shape[tp_dim] % tp_n == 0:
                    dims[tp_dim] = self.tp_axis
                other = 1 - tp_dim
                if (fsdp_n > 1 and shape[other] % fsdp_n == 0
                        and int(np.prod(shape)) >= self.min_size):
                    dims[other] = self.fsdp_axis
                return P(*dims)
            if leaf_name == "bias":
                # column-parallel bias rides the split output features;
                # row-parallel bias applies after the psum -> replicated
                if column and shape and shape[0] % tp_n == 0:
                    return P(self.tp_axis)
                return P()
        # -- everything else: the ZeRO largest-divisible-axis rule
        if fsdp_n > 1:
            return leaf_fsdp_spec(leaf, fsdp_n, axis=self.fsdp_axis,
                                  min_size=self.min_size)
        return P()

    def param_specs(self, variables, mesh: Mesh):
        """PartitionSpec tree mirroring ``variables`` — every leaf gets
        a spec (the replicated fallback is explicit, never missing)."""
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: self.param_spec(path, leaf, mesh),
            variables)

    def specs_fn(self, mesh: Mesh):
        """The layout as a ``param_specs_fn`` for gspmd_round factories."""
        return lambda tree: self.param_specs(tree, mesh)

    def data_spec(self) -> P:
        """Client-major round inputs ``[P, ...]``: sharded over data."""
        return P(self.data_axis)

    def block_spec(self) -> P:
        """Fused-block inputs ``[R, P, ...]``: round dim replicated,
        client dim sharded over data."""
        return P(None, self.data_axis)


#: the one default layout every mesh driver shares
DEFAULT_LAYOUT = SpecLayout()


def make_mesh_federated_round(module, task: str, cfg: TrainConfig,
                              mesh: Mesh,
                              layout: SpecLayout = DEFAULT_LAYOUT,
                              donate: bool = False):
    """One FedAvg round on the named mesh: sampled clients data-parallel
    over ``data`` while every client's model carries the canonical
    fsdp/tp layout. Delegates to the shared gspmd_round factory — the
    same round body as every other FedAvg path. Returns
    ``(round_fn, shard_params)``."""
    from fedml_tpu.parallel.gspmd_round import make_sharded_federated_round

    return make_sharded_federated_round(
        module, task, cfg, mesh, layout.specs_fn(mesh),
        clients_axis=layout.data_axis, donate=donate)


def make_mesh_eval(module, task: str, mesh: Mesh,
                   layout: SpecLayout = DEFAULT_LAYOUT):
    """Sharded eval on the named mesh: the eval union rides ``data``,
    params keep their layout (gspmd_round.make_gspmd_eval)."""
    from fedml_tpu.parallel.gspmd_round import make_gspmd_eval

    return make_gspmd_eval(module, task, mesh, layout.specs_fn(mesh),
                           clients_axis=layout.data_axis)


def _data_only(mesh: Mesh, layout: SpecLayout) -> bool:
    """True when no model axis actually shards (every non-data axis is
    absent or size 1) — params are replicated, so the shard_map program
    (explicit psum aggregation) is expressible."""
    return all(int(size) <= 1 for name, size in dict(mesh.shape).items()
               if name != layout.data_axis)


def make_mesh_block_multiround(module, task: str, cfg: TrainConfig,
                               mesh: Mesh,
                               layout: SpecLayout = DEFAULT_LAYOUT,
                               donate: bool = True,
                               variant: Optional[str] = None):
    """R sampled-cohort FedAvg rounds as ONE jitted scan on the named
    mesh — the spmd.make_spmd_block_multiround program promoted to
    data x fsdp x tp. Two lowerings serve the one driver signature,
    picked by what the mesh can express (``variant`` None = auto):

    - ``"shard_map"`` — the explicit-psum fused scan
      (spmd.make_spmd_block_multiround) with its client axis renamed to
      ``data``. Fastest per-device program, but shard_map's
      replicated-params contract cannot express fsdp/tp-sharded
      parameters; auto-picked for multi-device data-ONLY meshes.
    - ``"gspmd"`` — a jit scan whose body is the SIM driver's round
      verbatim (make_vmapped_body + pt.tree_weighted_mean + the shared
      round_keys fold_in chain) with the layout's NamedShardings; XLA's
      partitioner inserts the collectives the layout implies. Auto-
      picked for sharded layouts, and for ``{data: 1}`` where the
      sim-identical jaxpr makes the trajectory BIT-exact vs
      FedAvgAPI/FusedRounds (the parity contract); wider meshes agree
      within reduction-reordering tolerance
      (tests/test_mesh_layout.py).

    Returns ``fn(variables, xs, ys, masks, idsR, weightsR, base_key,
    r0) -> (new_variables, stats[R])`` with block arrays
    ``[R, P, n_pad, ...]`` sharded ``P(None, 'data')``.
    """
    if variant is None:
        variant = ("shard_map"
                   if (_data_only(mesh, layout)
                       and int(dict(mesh.shape)[layout.data_axis]) > 1)
                   else "gspmd")
    if variant == "shard_map":
        if not _data_only(mesh, layout):
            raise ValueError(
                "shard_map block variant needs a data-only mesh "
                f"(replicated params); got {dict(mesh.shape)}")
        from fedml_tpu.parallel.spmd import make_spmd_block_multiround

        return make_spmd_block_multiround(
            module, task, cfg, mesh, axis=layout.data_axis, donate=donate,
            check_vma=not getattr(module, "flax_rnn_carry", False))
    if variant != "gspmd":
        raise ValueError(f"unknown block variant: {variant!r}")
    from fedml_tpu.algorithms.fedavg import make_vmapped_body
    from fedml_tpu.core import pytree as pt
    from fedml_tpu.parallel.gspmd_round import _avals_key, tree_shardings

    body_v = make_vmapped_body(make_local_train(module, task, cfg))

    def body(variables, xs, ys, masks, idsR, weightsR, base_key, r0):
        def one_round(vars_r, inp):
            r, x, y, mask, ids, weights = inp
            _, keys, _ = round_keys(base_key, r, ids)
            stacked, totals = body_v(vars_r, x, y, mask, keys,
                                     round_lr_scale(cfg, r))
            return pt.tree_weighted_mean(stacked, weights), totals

        rs = r0 + jnp.arange(xs.shape[0], dtype=jnp.uint32)
        return jax.lax.scan(one_round, variables,
                            (rs, xs, ys, masks, idsR, weightsR))

    _jit = {}  # one compile per variables structure (gspmd_round rule)

    def jitted(variables, xs, ys, masks, idsR, weightsR, base_key, r0):
        key = _avals_key(variables)
        if key not in _jit:
            params = tree_shardings(mesh,
                                    layout.param_specs(variables, mesh))
            block = NamedSharding(mesh, layout.block_spec())
            rep = NamedSharding(mesh, P())
            _jit[key] = jax.jit(
                body,
                in_shardings=(params, block, block, block, block, block,
                              rep, rep),
                out_shardings=(params, None),
                donate_argnums=(0,) if donate else ())
        return _jit[key](variables, xs, ys, masks, idsR, weightsR,
                         base_key, r0)

    return jitted


# -- measured collective accounting ------------------------------------------

#: HLO collective op mnemonics whose output bytes we account (the
#: GSPMD partitioner emits these; jaxpr-level psums don't exist on the
#: jit path, so the compiled module is the measurement surface)
_HLO_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                       "collective-permute", "all-to-all")

_HLO_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                    "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4,
                    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}

_HLO_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]*)\[([0-9,]*)\]")


def _hlo_shape_bytes(text: str) -> int:
    total = 0
    for dtype, dims in _HLO_SHAPE_RE.findall(text):
        size = _HLO_DTYPE_BYTES.get(dtype)
        if size is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * size
    return total


def collective_hlo_stats(compiled_text: str) -> Dict[str, Any]:
    """Measured collective footprint of ONE compiled (post-partitioner)
    HLO module: per-op instruction counts and output bytes. This is the
    program XLA actually runs — the honest wire figure for a GSPMD
    lowering, where no jaxpr-level collective exists to count."""
    ops: Dict[str, Dict[str, int]] = {}
    for line in compiled_text.splitlines():
        stripped = line.strip()
        if not stripped.startswith("%") and " = " not in stripped:
            continue
        for op in _HLO_COLLECTIVE_OPS:
            # match the instruction opcode, not fused-computation names
            if f" {op}(" not in stripped and f" {op}-start(" \
                    not in stripped:
                continue
            _, _, rhs = stripped.partition(" = ")
            out_part = rhs.split(f" {op}", 1)[0]
            entry = ops.setdefault(op, {"count": 0, "bytes": 0})
            entry["count"] += 1
            entry["bytes"] += _hlo_shape_bytes(out_part)
            break
    return {"ops": ops,
            "total_bytes": sum(e["bytes"] for e in ops.values()),
            "total_count": sum(e["count"] for e in ops.values())}


def program_collective_stats(fn, *args) -> Dict[str, Any]:
    """Lower + compile ``fn(*args)`` and account its collectives.
    ``fn`` may be a jitted callable or a plain function (wrapped)."""
    lower = getattr(fn, "lower", None)
    if lower is None:
        lower = jax.jit(fn).lower
    return collective_hlo_stats(lower(*args).compile().as_text())


# -- static-analysis hook (fedml_tpu.analysis layer 2) ----------------------
from fedml_tpu.analysis.registry import AuditSpec, hot_entry_point  # noqa: E402


def _audit_api(n_dev: int):
    from fedml_tpu.data.synthetic import make_blob_federated
    from fedml_tpu.models.lr import LogisticRegression
    from fedml_tpu.parallel.spmd import (DistributedFedAvgAPI,
                                         DistributedFedAvgConfig)

    ds = make_blob_federated(client_num=max(4, n_dev), n_samples=240,
                             seed=0)
    return DistributedFedAvgAPI(
        ds, LogisticRegression(num_classes=ds.class_num),
        config=DistributedFedAvgConfig(
            comm_round=4, client_num_per_round=max(2, n_dev),
            pack="global", prefetch_depth=0,
            mesh_shape={"data": n_dev},
            train=TrainConfig(epochs=1, batch_size=8)))


@hot_entry_point("mesh.block_multiround")
def _audit_mesh_block_multiround() -> AuditSpec:
    """The fused named-mesh block scan over two real windows built by
    the driver's own _pack_block — consecutive windows of one run must
    share one lowering. Pinned to the shard_map variant (the program
    multi-device data-only meshes run): its explicit psum set over
    'data' is the drift surface, and shard_map signatures are
    device-count-independent (spmd.block_multiround precedent). The
    gspmd variant's jaxpr-level signature is empty at every mesh size
    (partitioner-inserted collectives; fedavg.round_fn precedent) and
    is covered by mesh.federated_round."""
    api = _audit_api(len(jax.devices()))
    fn = make_mesh_block_multiround(api.module, api.task,
                                    api.config.train, api.mesh,
                                    api._layout, donate=False,
                                    variant="shard_map")

    def window(r0, rounds):
        _, args = api._pack_block((r0, rounds))
        return (api.variables, *args, api._base_key, jnp.uint32(r0))

    return AuditSpec(fn=fn, sweep=[window(0, 2), window(2, 2)],
                     max_lowerings=1, grad_path=True)


@hot_entry_point("mesh.federated_round")
def _audit_mesh_federated_round() -> AuditSpec:
    """The per-round named-mesh program (make_mesh_federated_round via
    the shared gspmd_round factory) over two rounds' real host inputs —
    every round of a run must hit the one compiled program."""
    api = _audit_api(len(jax.devices()))
    fn, _ = make_mesh_federated_round(api.module, api.task,
                                      api.config.train, api.mesh,
                                      api._layout, donate=False)

    def inputs(r):
        _, _, (xd, yd, maskd, keysd, wd) = api._pack_round(r)
        return (api.variables, xd, yd, maskd, keysd, wd)

    return AuditSpec(fn=fn, sweep=[inputs(0), inputs(1)],
                     max_lowerings=1, grad_path=True)


# -- CLI: ci smoke lane + bench scaling worker ------------------------------

def _measure_host_peak_flops(n: int = 768, iters: int = 4) -> float:
    """Measured f32 GEMM throughput of THIS host (whole host, not per
    virtual device — forced-host devices share the physical cores), as
    an honest denominator for CPU scaling rows where the documented
    per-chip peak table refuses to guess."""
    import time

    a = jnp.ones((n, n), jnp.float32)
    mm = jax.jit(lambda x: x @ x)
    jax.block_until_ready(mm(a))
    t0 = time.perf_counter()
    out = a
    for _ in range(iters):
        out = mm(out)
    jax.block_until_ready(out)
    return 2.0 * n ** 3 * iters / (time.perf_counter() - t0)


def _bench_workload(workload: str, mesh_shape: Dict[str, int],
                    rounds_per_dispatch: int, timed_dispatches: int
                    ) -> Dict[str, Any]:
    """Measure fused federated rounds/sec for one workload at one mesh
    shape — the mesh_scaling bench worker body. Times the fused block
    program itself (the block is packed once and re-dispatched; the
    driver pipelines host packing behind dispatch, so program
    throughput is the scaling observable)."""
    import time

    from fedml_tpu.parallel.spmd import (DistributedFedAvgAPI,
                                         DistributedFedAvgConfig)
    from fedml_tpu.utils.flops import analytic_flops

    n_dev = int(np.prod(list(mesh_shape.values())))
    tpu = jax.default_backend() == "tpu"
    if workload == "transformer_flash_s2048":
        from fedml_tpu.data.synthetic import make_token_federated
        from fedml_tpu.models.transformer import TransformerLM

        # CPU smoke shapes (same policy as bench_transformer_flash):
        # the real S=2048 stage shape only on a chip backend
        if tpu:
            vocab, width, depth, heads, S = 1024, 256, 4, 4, 2048
            n_pad, bsz, clients = 4, 4, 8
        else:
            vocab, width, depth, heads, S = 256, 64, 2, 2, 256
            n_pad, bsz, clients = 2, 2, 8
        ds = make_token_federated(client_num=clients, vocab_size=vocab,
                                  seq_len=S,
                                  sequences_per_client=n_pad * bsz,
                                  seed=0)
        module = TransformerLM(vocab_size=vocab, width=width, depth=depth,
                               num_heads=heads, max_len=S)
        task = "nwp"
        shape_note = {"seq_len": S, "width": width, "depth": depth,
                      "num_heads": heads, "cpu_smoke_shape": not tpu}
    elif workload == "resnet18_gn":
        from fedml_tpu.data.base import FederatedDataset
        from fedml_tpu.models import create_model

        hw, chans, classes, clients = (24, 3, 100, 8) if tpu \
            else (12, 3, 10, 8)
        samples, bsz = (20, 20) if tpu else (2, 2)
        rng = np.random.RandomState(0)
        train_local = {
            c: (rng.rand(samples, hw, hw, chans).astype(np.float32),
                rng.randint(0, classes, samples).astype(np.int32))
            for c in range(clients)}
        ds = FederatedDataset.from_client_arrays(
            train_local, {c: None for c in range(clients)}, classes)
        module = create_model("resnet18_gn", output_dim=classes)
        task = "classification"
        shape_note = {"hw": hw, "classes": classes,
                      "cpu_smoke_shape": not tpu}
    else:
        raise ValueError(f"unknown mesh_scaling workload: {workload!r}")

    R = rounds_per_dispatch
    api = DistributedFedAvgAPI(
        ds, module, task=task,
        config=DistributedFedAvgConfig(
            comm_round=R * (timed_dispatches + 1),
            client_num_per_round=clients, pack="global",
            prefetch_depth=0, mesh_shape=dict(mesh_shape),
            train=TrainConfig(epochs=1, batch_size=bsz, lr=0.1)))
    fn = make_mesh_block_multiround(api.module, api.task,
                                    api.config.train, api.mesh,
                                    api._layout, donate=False)
    # mirror the auto-variant rule so the row documents the program it
    # measured (and so analytic flops scale correctly below)
    variant = ("shard_map" if (_data_only(api.mesh, api._layout)
                               and n_dev > 1) else "gspmd")
    _, args = api._pack_block((0, R))
    run = lambda r0: fn(api.variables, *args, api._base_key,
                        jnp.uint32(r0))
    v, stats = run(0)  # compile + warmup
    jax.block_until_ready(v)
    assert np.isfinite(float(np.sum(np.asarray(stats["loss_sum"]))))
    t0 = time.perf_counter()
    for i in range(timed_dispatches):
        v, _ = run(i * R)
        jax.block_until_ready(v)
    dt = time.perf_counter() - t0
    rps = R * timed_dispatches / dt

    flops_block = None
    try:
        flops_block = float(analytic_flops(
            fn, api.variables, *args, api._base_key, jnp.uint32(0)))
    except Exception:  # ft: allow[FT005] analytic-flops cross-check column: a probe miss drops the column, never the bench row
        pass
    # shard_map jaxprs carry PER-DEVICE shapes (the data axis is already
    # split at trace time), so the global round count scales by the data
    # shard count; gspmd jaxprs trace at global shapes (x1)
    flops_scale = (int(dict(api.mesh.shape)[api._layout.data_axis])
                   if variant == "shard_map" else 1)
    round_flops = (flops_block * flops_scale / R if flops_block
                   else None)

    coll = program_collective_stats(
        fn, api.variables, *args, api._base_key, jnp.uint32(0))
    param_bytes = int(sum(
        np.prod(l.shape) * l.dtype.itemsize
        for l in jax.tree.leaves(api.variables)))

    from fedml_tpu.obs.perf import device_peak_flops
    per_dev_peak = device_peak_flops(api.mesh.devices.flat[0])
    achieved = rps * round_flops if round_flops else None
    row: Dict[str, Any] = {
        "workload": workload,
        "mesh": dict(mesh_shape),
        "mesh_devices": n_dev,
        "device_kind": str(api.mesh.devices.flat[0].device_kind),
        "program_variant": variant,
        "rounds_per_dispatch": R,
        "timed_rounds": R * timed_dispatches,
        "rounds_per_sec": round(rps, 3),
        "round_flops": round_flops,
        "achieved_flops_per_s": (round(achieved, 3) if achieved
                                 else None),
        "mfu": (float(f"{achieved / (per_dev_peak * n_dev):.6g}")
                if achieved and per_dev_peak else None),
        "param_bytes": param_bytes,
        "collective_bytes_per_round": coll["total_bytes"] // R,
        "collective_ops": coll["ops"],
        **shape_note,
    }
    if achieved and not per_dev_peak:
        # CPU host: the documented peak table never guesses, so measure
        # the host's own GEMM peak as a labeled denominator instead.
        # Whole-host figure — forced-host devices share the cores, so
        # the fleet peak does NOT scale with mesh size here.
        host_peak = _measure_host_peak_flops()
        row["measured_host_peak_flops"] = round(host_peak, 3)
        row["peak_source"] = "measured_host_gemm_f32"
        row["mfu_vs_measured_host_peak"] = float(
            f"{achieved / host_peak:.6g}")
    return row


def _run_smoke(out_dir: str) -> int:
    """ci/run_fast.sh mesh lane (<= 20 s on the CI host): a real
    2-device named-mesh federation with the flight recorder ON, the
    fused block program exercised, the mesh entry points' collective
    signatures checked against ci/collective_baseline.json, and the
    flight log rebuilt by ``obs merge --ledger`` at rc 0."""
    import json
    import os
    import shutil

    from fedml_tpu.data.synthetic import make_blob_federated
    from fedml_tpu.models.lr import LogisticRegression
    from fedml_tpu.parallel.spmd import (DistributedFedAvgAPI,
                                         DistributedFedAvgConfig)

    shutil.rmtree(out_dir, ignore_errors=True)
    os.makedirs(out_dir, exist_ok=True)
    flight_dir = os.path.join(out_dir, "flight")
    n_data = 2 if len(jax.devices()) >= 2 else 1
    ds = make_blob_federated(client_num=6, n_samples=240, seed=0)
    api = DistributedFedAvgAPI(
        ds, LogisticRegression(num_classes=ds.class_num),
        config=DistributedFedAvgConfig(
            comm_round=5, client_num_per_round=4, pack="global",
            prefetch_depth=0, mesh_shape={"data": n_data},
            obs_dir=flight_dir, job_id="mesh-smoke",
            train=TrainConfig(epochs=1, batch_size=8)))
    # per-round leg: flight records + the schedule-trace ledger the
    # merge cross-checks (cohorts recorded the moment they are drawn,
    # the single-process analogue of the cross-silo server's ledger)
    ledger_path = os.path.join(out_dir, "ledger.jsonl")
    with open(ledger_path, "w") as ledger:
        for r in range(3):
            idxs, stats = api.run_round(r)
            assert np.isfinite(float(stats["loss_sum"]))
            ledger.write(json.dumps(
                {"round": r, "cohort": [int(i) for i in idxs]}) + "\n")
    # fused leg: one 2-round block window through the named-mesh scan
    stats = api.run_rounds_fused(3, 2)
    jax.block_until_ready(api.variables)
    assert np.isfinite(float(np.asarray(stats["loss_sum"])[-1]))
    if api._obs is not None:
        api._obs.close()

    # collective audit over the mesh entry points vs the CI baseline
    from fedml_tpu.analysis.jaxpr_audit import (check_collective_baseline,
                                                run_audit)
    mesh_entries = ("mesh.block_multiround", "mesh.federated_round")
    findings, reports = run_audit(only=mesh_entries)
    baseline = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "ci",
        "collective_baseline.json")
    base_findings, _stale = check_collective_baseline(reports, baseline)
    findings += [f for f in base_findings if f.where in mesh_entries
                 or f.where == "<baseline>"]
    if findings:
        for f in findings:
            print(f"mesh smoke: {f.rule} {f.where}: {f.message}")
        return 1

    # flight log merged with the ledger — rc 0 is the lane's contract
    from fedml_tpu.obs.__main__ import main as obs_main
    rc = obs_main(["merge", flight_dir, "--ledger", ledger_path,
                   "--output", os.path.join(out_dir, "merged.json")])
    if rc != 0:
        print(f"mesh smoke: obs merge --ledger exited {rc}")
        return 1
    print(f"mesh smoke ok: {n_data}-device data mesh, 3 host rounds + "
          f"one fused 2-round block, collective audit green, "
          f"merge rc 0 ({out_dir})")
    return 0


def _cli(argv=None) -> int:
    import argparse
    import json
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m fedml_tpu.parallel.mesh",
        description="named-mesh federation: CI smoke + scaling worker")
    parser.add_argument("--smoke", action="store_true",
                        help="ci/run_fast.sh mesh lane")
    parser.add_argument("--out", default="runs/mesh_smoke",
                        help="smoke artifact directory")
    parser.add_argument("--bench-worker", action="store_true",
                        help="measure one (workload, mesh) point and "
                             "print a JSON row (bench.py mesh_scaling)")
    parser.add_argument("--workload", default="transformer_flash_s2048")
    parser.add_argument("--mesh", default="data=1",
                        help="mesh shape, e.g. data=8 or data=4,fsdp=2")
    parser.add_argument("--rounds", type=int, default=4,
                        help="fused rounds per dispatch")
    parser.add_argument("--dispatches", type=int, default=2,
                        help="timed dispatches (after one warmup)")
    parser.add_argument("--force-host", action="store_true",
                        help="pin the CPU platform (the caller sets "
                             "XLA_FLAGS for the virtual device count)")
    args = parser.parse_args(argv)
    if args.force_host:
        jax.config.update("jax_platforms", "cpu")
    if args.bench_worker:
        row = _bench_workload(args.workload, parse_mesh_shape(args.mesh),
                              args.rounds, args.dispatches)
        print(json.dumps(row), file=sys.stdout, flush=True)
        return 0
    if args.smoke:
        return _run_smoke(args.out)
    parser.error("pick one of --smoke / --bench-worker")
    return 2


if __name__ == "__main__":
    raise SystemExit(_cli())
