"""Pipeline parallelism: GPipe-style microbatched stages over a ``pp`` axis.

The reference's deepest model is ResNet-110 on one GPU; this gives the
framework a real depth axis: a stack of identical transformer blocks is
partitioned one-stage-per-device, activations flow stage-to-stage over ICI
with ``ppermute``, and microbatching keeps every stage busy outside the
fill/drain bubble (schedule length ``n_micro + n_stages − 1``).

TPU-first shape: the whole schedule is one ``lax.scan`` inside one
``shard_map`` program — no host round-trips between ticks; stage parameters
are a stacked pytree sharded ``P('pp')`` on the leading axis, so each
device holds exactly its stage's weights (parameter memory scales with the
mesh, the point of pipelining). Embedding/head stay outside the pipelined
region (replicated), as in practical GPipe deployments.

Composes with the other axes: ('pp', 'tp') nests Megatron sharding inside
each stage; ('clients', 'pp') pipelines each federated client's model.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.parallel.spmd import _pvary


def stack_stage_params(stage_params_list):
    """[per-stage param trees] -> one stacked tree with leading stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params_list)


def shard_stage_params(stacked, mesh: Mesh, axis: str = "pp"):
    """Place the stacked stage params: leading (stage) axis over ``pp``."""
    return jax.tree.map(
        lambda v: jax.device_put(v, NamedSharding(
            mesh, P(*((axis,) + (None,) * (v.ndim - 1))))), stacked)


def make_pipeline(block_module, mesh: Mesh, n_micro: int, axis: str = "pp"):
    """Public factory: returns (apply_fn, shard_fn)."""
    n_stages = mesh.shape[axis]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def local(stage_params, x):
        my_params = jax.tree.map(lambda v: v[0], stage_params)
        stage = jax.lax.axis_index(axis)
        mb = x.shape[0] // n_micro
        # input is replicated; mark it device-varying so the scan carry
        # (which becomes varying through ppermute) has a stable type
        micro = _pvary(x.reshape((n_micro, mb) + x.shape[1:]), (axis,))

        ticks = n_micro + n_stages - 1

        def tick(buf, t):
            idx = jnp.clip(t, 0, n_micro - 1)
            inp = jnp.where(stage == 0, micro[idx], buf)
            out = block_module.apply({"params": my_params}, inp)
            nxt = jax.lax.ppermute(out, axis, perm)
            return nxt, out

        zero = _pvary(jnp.zeros((mb,) + x.shape[1:], x.dtype), (axis,))
        _, outs = jax.lax.scan(tick, zero, jnp.arange(ticks))
        finished = jax.lax.dynamic_slice_in_dim(outs, n_stages - 1, n_micro,
                                                axis=0)
        is_last = (stage == n_stages - 1).astype(finished.dtype)
        full = jax.lax.psum(finished * is_last, axis)
        return full.reshape(x.shape)

    apply_fn = jax.jit(jax.shard_map(
        local, mesh=mesh, in_specs=(P(axis), P()), out_specs=P()))

    def shard_fn(stacked):
        return shard_stage_params(stacked, mesh, axis)

    return apply_fn, shard_fn
