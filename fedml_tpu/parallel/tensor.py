"""Tensor parallelism for the transformer LM — Megatron sharding via GSPMD.

The reference has no model-parallel machinery at all (its biggest model is
a DataParallel ResNet-56, GKTServerTrainer.py:27-29). This module gives the
framework's transformer a real ``tp`` axis the TPU-first way: instead of
hand-writing collectives, we annotate parameter shardings
(column-parallel up-projections, row-parallel down-projections) and let
XLA's SPMD partitioner insert the all-reduces over ICI — the
"pick a mesh, annotate shardings, let XLA insert collectives" recipe.

Per TransformerBlock (models/transformer.py:34-62, flax creation order):
- Dense_0  qkv    [w, 3w]  -> P(None, tp)   column parallel (heads split)
- Dense_1  out    [w, w]   -> P(tp, None)   row parallel (psum epilogue)
- Dense_2  mlp-up [w, 4w]  -> P(None, tp)   column parallel
- Dense_3  mlp-dn [4w, w]  -> P(tp, None)   row parallel
Top-level Dense_0 (logit head) is column parallel over the vocab;
embeddings and LayerNorms stay replicated. Activations flow sharded on the
hidden axis between the column/row pairs, so each layer needs exactly one
all-reduce in forward (and one in backward) — the Megatron-LM schedule.

Composes with the other axes: a ('clients', 'tp') mesh gives every
federated client its own tensor-parallel sub-mesh; ('tp', 'seq') combines
with sequence parallelism (parallel/sequence.py).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

COLUMN_PARALLEL = ("Dense_0", "Dense_2")  # qkv + mlp-up inside a block
ROW_PARALLEL = ("Dense_1", "Dense_3")     # attn-out + mlp-down


def transformer_tp_specs(variables: Dict[str, Any],
                         axis: str = "tp") -> Dict[str, Any]:
    """PartitionSpec tree for a TransformerLM variables dict."""

    def spec_for(path, leaf):
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        in_block = any(n.startswith("TransformerBlock") for n in names)
        module = next((n for n in reversed(names)
                       if n.startswith(("Dense", "Embed", "LayerNorm",
                                        "pos_embed"))), "")
        leaf_name = names[-1] if names else ""
        if module.startswith("Dense"):
            if in_block and module in COLUMN_PARALLEL:
                return P(None, axis) if leaf_name == "kernel" else P(axis)
            if in_block and module in ROW_PARALLEL:
                return P(axis, None) if leaf_name == "kernel" else P()
            if not in_block:  # logit head: column parallel over vocab
                return P(None, axis) if leaf_name == "kernel" else P(axis)
        return P()  # embeddings, layernorms, everything else: replicated

    return jax.tree_util.tree_map_with_path(spec_for, variables)


def shard_transformer_tp(variables, mesh: Mesh, axis: str = "tp"):
    """Place a TransformerLM variables tree with Megatron TP shardings."""
    from fedml_tpu.parallel.gspmd_round import place

    return place(variables, mesh, transformer_tp_specs(variables, axis))


def tp_param_specs(axis: str = "tp"):
    """The single copy of the Megatron spec rule as a specs_fn (consumed by
    make_tp_federated_round, the SPMD driver's --model_parallel tp path,
    and gspmd_round.make_gspmd_eval)."""
    return lambda tree: transformer_tp_specs(tree, axis)


def build_tp_mesh(n_devices: int, axis: str = "tp",
                  devices=None) -> Mesh:
    devs = (devices if devices is not None else jax.devices())[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def make_tp_federated_round(model, task: str, cfg, mesh: Mesh,
                            clients_axis: str = "clients",
                            tp_axis: str = "tp", donate: bool = False):
    """FedAvg round over a ('clients', 'tp') mesh: sampled clients are
    data-parallel on one axis while EVERY client's transformer is Megatron-
    sharded over the other — federated training of a model bigger than one
    chip. Pure GSPMD: the vmapped round program (the same body the
    single-axis path runs) is jitted with parameter shardings over ``tp``
    and client-batch shardings over ``clients``; XLA inserts the per-layer
    all-reduces inside each client's sub-mesh and the cross-client psum for
    the weighted aggregate.

    Returns (round_fn, shard_params): ``round_fn(variables, x, y, mask,
    keys, weights)`` with x [P, n_pad, S] int tokens.
    """
    from fedml_tpu.parallel.gspmd_round import make_sharded_federated_round

    return make_sharded_federated_round(
        model, task, cfg, mesh, tp_param_specs(tp_axis),
        clients_axis=clients_axis, donate=donate)


def make_tp_train_step(model, mesh: Mesh, lr: float = 1e-3,
                       axis: str = "tp"):
    """One SGD step on the TP-sharded LM. Inputs replicated, params stay in
    their Megatron layout (jit is given the output shardings so updated
    params land back where they live)."""
    import jax.numpy as jnp
    import optax

    def step(variables, tokens):
        def loss(params):
            logits = model.apply({"params": params}, tokens, train=False)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(
                    logits[:, :-1], tokens[:, 1:]))

        value, grads = jax.value_and_grad(loss)(variables["params"])
        new_params = jax.tree.map(lambda p, g: p - lr * g,
                                  variables["params"], grads)
        return {"params": new_params}, value

    return jax.jit(step)
