"""Fully-sharded data parallelism (ZeRO-3) — parameter sharding via GSPMD.

The reference's only answer to "model bigger than one device" is
``nn.DataParallel`` on the GKT server (GKTServerTrainer.py:27-29), which
*replicates* the model per GPU. This module is the TPU-native opposite:
every parameter (and its optimizer state) lives sharded across the
``fsdp`` mesh axis, and XLA's SPMD partitioner inserts the per-layer
all-gathers (params, forward+backward) and reduce-scatters (grads) over
ICI — the "pick a mesh, annotate shardings, let XLA insert collectives"
recipe, same as parallel/tensor.py.

Sharding rule (`fsdp_specs`): each leaf is sharded on its *largest* axis
divisible by the shard count; leaves smaller than ``min_size`` elements
(layernorm scales, biases) stay replicated — gathering them costs more
than storing them. Optimizer state follows the parameter sharding leaf
for leaf, so momentum/Adam moments are sharded too (ZeRO-1/2 come free).

Composes with the other axes: a ('clients', 'fsdp') mesh runs a federated
round where every sampled client trains the SAME fsdp-sharded model on its
own sub-mesh (`make_fsdp_federated_round`), mirroring
parallel/tensor.make_tp_federated_round.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def leaf_fsdp_spec(leaf, n_shard: int, axis: str = "fsdp",
                   min_size: int = 1024) -> P:
    """PartitionSpec for one array: shard the largest divisible axis."""
    shape = getattr(leaf, "shape", ())
    if not shape or int(np.prod(shape)) < min_size:
        return P()
    dims = sorted(range(len(shape)), key=lambda d: shape[d], reverse=True)
    for d in dims:
        if shape[d] % n_shard == 0:
            return P(*(axis if i == d else None for i in range(len(shape))))
    return P()


def fsdp_specs(tree: Dict[str, Any], n_shard: int, axis: str = "fsdp",
               min_size: int = 1024):
    """PartitionSpec tree mirroring ``tree`` (params or optimizer state)."""
    return jax.tree.map(
        lambda leaf: leaf_fsdp_spec(leaf, n_shard, axis, min_size), tree)


def fsdp_param_specs(n_shard: int, axis: str = "fsdp",
                     min_size: int = 1024):
    """The single copy of the ZeRO spec rule as a specs_fn (consumed by
    make_fsdp_federated_round, the SPMD driver's --model_parallel fsdp
    path, and gspmd_round.make_gspmd_eval)."""
    return lambda tree: fsdp_specs(tree, n_shard, axis, min_size)


def shard_params_fsdp(tree, mesh: Mesh, axis: str = "fsdp",
                      min_size: int = 1024):
    """Place a pytree with FSDP shardings over ``mesh``'s ``axis``."""
    from fedml_tpu.parallel.gspmd_round import place

    return place(tree, mesh, fsdp_specs(tree, mesh.shape[axis], axis,
                                        min_size))


def build_fsdp_mesh(n_devices: int, axis: str = "fsdp", devices=None) -> Mesh:
    devs = (devices if devices is not None else jax.devices())[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def make_fsdp_train_step(model, mesh: Mesh, lr: float = 1e-3,
                         momentum: float = 0.9, axis: str = "fsdp",
                         min_size: int = 1024, donate: bool = True):
    """One data-parallel SGD-momentum step on the fsdp-sharded LM.

    The batch is sharded over the same ``fsdp`` axis (FSDP *is* data
    parallelism with the replica memory deduplicated), params and momentum
    live sharded; jit's out_shardings pin the updated state back to the
    same layout so nothing silently gathers. Returns
    ``(init_state, step)`` factories: ``state = init_state(variables)``;
    ``state, loss = step(state, tokens)`` with tokens ``[B, S+1]`` int.
    """
    from fedml_tpu.parallel.gspmd_round import tree_shardings

    n_shard = mesh.shape[axis]
    tx = optax.sgd(lr, momentum=momentum)

    def to_sharding(tree):
        return tree_shardings(mesh, fsdp_specs(tree, n_shard, axis,
                                               min_size))

    def init_state(variables):
        params = shard_params_fsdp(variables["params"], mesh, axis, min_size)
        # momentum leaves inherit the param shardings via zeros_like
        return params, tx.init(params)

    def step(state, tokens):
        params, opt_state = state

        def loss_fn(p):
            logits = model.apply({"params": p}, tokens[:, :-1], train=False)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(
                    logits, tokens[:, 1:]))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, new_opt = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), new_opt), loss

    # cache keyed by the state's tree structure + leaf avals: a
    # differently-structured state (new model, new dtype) recompiles with
    # fresh shardings instead of silently reusing the first call's
    from fedml_tpu.parallel.gspmd_round import _avals_key
    _jit = {}

    def jitted_step(state, tokens):
        key = _avals_key(state)
        if key not in _jit:
            state_shardings = (to_sharding(state[0]), to_sharding(state[1]))
            _jit[key] = jax.jit(
                step,
                in_shardings=(state_shardings,
                              NamedSharding(mesh, P(axis))),
                out_shardings=(state_shardings, None),
                donate_argnums=(0,) if donate else ())
        return _jit[key](state, tokens)

    return init_state, jitted_step


def make_fsdp_federated_round(model, task: str, cfg, mesh: Mesh,
                              clients_axis: str = "clients",
                              fsdp_axis: str = "fsdp",
                              min_size: int = 1024,
                              donate: bool = False):
    """FedAvg round over a ('clients', 'fsdp') mesh: sampled clients are
    data-parallel on one axis while the global model's parameters are
    ZeRO-sharded over the other — so a federation can train a model whose
    full replica would not fit one chip. The vmapped round body (the same
    program every FedAvg path runs) is jitted with fsdp parameter
    shardings; XLA gathers each layer's shard just-in-time inside every
    client's sub-mesh and reduce-scatters the weighted aggregate back to
    the ZeRO layout.

    Returns (round_fn, shard_params): ``round_fn(variables, x, y, mask,
    keys, weights)``.
    """
    from fedml_tpu.parallel.gspmd_round import make_sharded_federated_round

    return make_sharded_federated_round(
        model, task, cfg, mesh,
        fsdp_param_specs(mesh.shape[fsdp_axis], fsdp_axis, min_size),
        clients_axis=clients_axis, donate=donate)
