"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference's longest sequences are LSTM char/word LMs (nlp/rnn.py:5,41);
it has no sequence-axis machinery (SURVEY §5.7). This module is the
framework's long-context subsystem so the mesh design carries a real
``seq`` axis rather than merely not precluding one: transformer-class
models (e.g. on-device LMs for federated next-word prediction at long
context) shard the sequence across devices and attend globally without any
device ever holding the full [S, S] score matrix or the full K/V.

Two standard schemes, both as ``shard_map``-ready collectives:

* :func:`ring_attention` — K/V blocks rotate around the ``seq`` axis ring
  via ``ppermute`` while each device keeps its Q shard; softmax is
  accumulated online (flash-attention style running max/denominator), so
  memory is O(S_local) and the N-1 rotations overlap compute with ICI
  transfer. Causality is enforced with global position ids, so the result
  is exactly ``softmax(QK^T/sqrt(d) + mask) V`` for the full sequence.

* :func:`ulysses_attention` — ``all_to_all`` re-shards [seq-shard, all
  heads] -> [full seq, head-shard], runs ordinary local attention per head
  group, and transposes back. One collective each way; preferable when
  heads >= devices and ICI all-to-all bandwidth is plentiful.

Both are pure functions of per-shard arrays and compose with the
``clients`` axis: :func:`make_seq_federated_round` runs the FULL FedAvg
round on a ('clients', 'seq') mesh — every federated client trains over
ring-attended long sequences on its own sub-mesh, with per-step gradient
sync over ``seq`` — and matches the single-device round exactly
(tests/test_seq_federated.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.utils.jax_compat import install_jax_compat

install_jax_compat()

_NEG_INF = -1e30  # finite: keeps fully-masked rows NaN-free in the online max


def _block_attend(q, k, v, qpos, kpos, m, denom, acc, causal: bool,
                  scale: float):
    """One online-softmax update with a visiting K/V block.

    q: [B, Sq, H, D]   k,v: [B, Sk, H, D]   qpos: [Sq]   kpos: [Sk]
    m, denom: [B, H, Sq]   acc: [B, Sq, H, D]
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = qpos[:, None] >= kpos[None, :]           # [Sq, Sk]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))              # [B, H, Sq]
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])                   # [B, H, Sq, Sk]
    denom_new = denom * corr + p.sum(axis=-1)
    acc_new = (acc * corr.transpose(0, 2, 1)[..., None]
               + jnp.einsum("bhqk,bkhd->bqhd", p, v))
    return m_new, denom_new, acc_new


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "seq", causal: bool = False) -> jax.Array:
    """Exact global attention over a sequence sharded on ``axis_name``.

    Call inside ``shard_map`` (or ``pmap``) with q/k/v = this device's
    sequence shard, laid out [batch, seq_local, heads, head_dim]. Returns
    the attention output for the local Q shard. K/V travel the ring once
    (N-1 ``ppermute`` hops); each hop's matmul overlaps the next transfer.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype)).astype(jnp.float32)

    qpos = idx * s_local + jnp.arange(s_local)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # accumulators in f32 regardless of input dtype (bf16-safe softmax),
    # derived from q so they inherit its full device-varying set (seq axis
    # plus any outer axes like 'clients') — otherwise the fori_loop carry
    # type changes after the first iteration and tracing fails
    qf = q.astype(jnp.float32)
    zeros_bhs = qf[..., 0].transpose(0, 2, 1) * 0.0     # [B, H, Sq]
    m = zeros_bhs + _NEG_INF
    denom = zeros_bhs
    acc = qf * 0.0

    def body(t, carry):
        k_blk, v_blk, m, denom, acc = carry
        src = (idx - t) % n                 # whose K/V we hold at step t
        kpos = src * s_local + jnp.arange(s_local)

        def attend(ops):
            m, denom, acc = ops
            return _block_attend(qf, k_blk.astype(jnp.float32),
                                 v_blk.astype(jnp.float32),
                                 qpos, kpos, m, denom, acc, causal, scale)

        if causal:
            # skip blocks entirely in this Q shard's future — at N devices
            # that is ~half the ring's attention FLOPs
            visible = kpos[0] <= qpos[-1]
            m, denom, acc = jax.lax.cond(visible, attend,
                                         lambda ops: ops, (m, denom, acc))
        else:
            m, denom, acc = attend((m, denom, acc))
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, denom, acc

    k_blk, v_blk, m, denom, acc = jax.lax.fori_loop(
        0, n, body, (k, v, m, denom, acc))
    out = acc / jnp.maximum(denom, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = "seq",
                      causal: bool = False, local_attn=None) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses scheme).

    Input shards are [B, S_local, H, D] with H divisible by the axis size.
    ``all_to_all`` turns them into [B, S_full, H/N, D] (full sequence, a
    slice of heads), local attention runs exactly, and the inverse
    all-to-all restores the sequence sharding.

    ``local_attn``: the per-head-group attention over the re-sharded
    [B, S_full, H/N, D] arrays — any (q, k, v, causal=...) callable.
    None = the plain XLA oracle; pass the :mod:`fedml_tpu.ops.autotune`
    selection (see :func:`make_sequence_parallel_attention`) so the local
    step runs whichever of Pallas/XLA actually wins at this shape.
    """
    n = jax.lax.psum(1, axis_name)  # static under shard_map
    if q.shape[2] % n:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by the "
            f"{axis_name!r} axis size ({n}); use ring_attention otherwise")

    def seq2head(x):  # [B, S_loc, H, D] -> [B, S_full, H/N, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def head2seq(x):  # inverse
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    out = (local_attn or reference_attention)(
        seq2head(q), seq2head(k), seq2head(v), causal=causal)
    return head2seq(out)


def reference_attention(q, k, v, causal: bool = False) -> jax.Array:
    """Unsharded oracle: plain softmax attention, [B, S, H, D]."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / jnp.sqrt(jnp.asarray(d,
                                                                 jnp.float32))
    if causal:
        pos = jnp.arange(q.shape[1])
        s = jnp.where((pos[:, None] >= pos[None, :])[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def make_sequence_parallel_attention(
        mesh: Mesh, scheme: str = "ring", causal: bool = False,
        axis_name: str = "seq", local_attn="auto"):
    """Wrap the chosen scheme in shard_map over ``mesh``'s seq axis.

    Returns ``fn(q, k, v) -> out`` taking GLOBAL [B, S, H, D] arrays;
    sharding to [B, S/N, H, D] shards and back is handled by shard_map.

    ``local_attn`` is the attention that runs where the scheme attends
    locally: ulysses' per-head-group step, and the whole computation when
    the ``seq`` axis has size 1 (a degenerate ring is pure
    ppermute/fori_loop overhead around plain attention — the single-chip
    bench case — so it is short-circuited to the local attention).
    ``"auto"`` = the :mod:`fedml_tpu.ops.autotune` per-shape winner
    (tuned Pallas blocks vs XLA reference, decision cached on disk);
    None = the plain XLA oracle; or any (q, k, v, causal=...) callable.
    """
    if scheme not in ("ring", "ulysses"):
        raise ValueError(f"scheme must be ring|ulysses, got {scheme!r}")
    if local_attn == "auto":
        from fedml_tpu.ops.autotune import make_autotuned_attention
        local_attn = make_autotuned_attention()
    if int(mesh.shape[axis_name]) == 1:
        fn = functools.partial(local_attn or reference_attention,
                               causal=causal)
    elif scheme == "ring":
        fn = functools.partial(ring_attention, axis_name=axis_name,
                               causal=causal)
    else:
        fn = functools.partial(ulysses_attention, axis_name=axis_name,
                               causal=causal, local_attn=local_attn)
    spec = P(None, axis_name, None, None)

    def sharded(q, k, v):
        return fn(q, k, v)

    return jax.jit(jax.shard_map(sharded, mesh=mesh,
                                 in_specs=(spec, spec, spec),
                                 out_specs=spec))


class _SeqShardedLM:
    """Adapter giving the trainer's ``module.apply(variables, x, train=...)``
    contract for a TransformerLM whose sequence axis is sharded: positions
    are offset by this shard's location on the ``seq`` axis."""

    def __init__(self, lm, seq_axis: str = "seq"):
        self._lm = lm
        self._seq_axis = seq_axis

    def apply(self, variables, x, train: bool = False, **kw):
        offset = jax.lax.axis_index(self._seq_axis) * x.shape[-1]
        return self._lm.apply(variables, x, train=train, pos_offset=offset,
                              **kw)

    def init(self, *a, **kw):
        return self._lm.init(*a, **kw)


def make_seq_federated_round(lm, cfg, mesh: Mesh,
                             clients_axis: str = "clients",
                             seq_axis: str = "seq", task: str = "nwp"):
    """FedAvg round over a ('clients', 'seq') mesh: sampled clients are
    data-parallel on one axis while every client's long sequences are
    sharded over the other — federated long-context training. The LM must
    take an ``attn_fn`` spanning the seq axis (ring/ulysses above); the
    local trainer syncs loss terms and gradients over ``seq`` each step
    (trainer.functional.make_local_train ``grad_sync_axes``), so all of a
    client's shards take the identical optimizer step and the round equals
    its single-device counterpart exactly.

    Inputs: x, y [P, n_pad, S] (token ids, S = GLOBAL length), mask
    [P, n_pad], keys [P], weights [P]. Returns (replicated new variables,
    psum'd stats).

    Warm-up note (the r5 bench's 577.8 tokens/s "pathology", VERDICT #5):
    the returned jit caches on input *sharding*. A first call made with
    the raw ``lm.init`` variables (uncommitted) compiles one program; its
    output comes back mesh-committed (out_specs P()), so the next call is
    a cache MISS and recompiles — ~seconds on CPU, tens of seconds through
    a chip tunnel. That second compile was inside the bench's timed
    region (its TP twin pre-places params via ``shard_params``, so only
    this round hit it), mis-measuring the round by orders of magnitude.
    Warm BOTH signatures before timing: ``v, _ = fn(variables, *args);
    v, _ = fn(v, *args)`` — steady state is the second signature.
    """
    from fedml_tpu.parallel.spmd import (_pvary, _weighted_psum_mean)
    from fedml_tpu.trainer.functional import make_local_train

    if getattr(cfg, "lr_decay_round", 1.0) != 1.0:
        raise NotImplementedError(
            "lr_decay_round is not threaded through the sequence-parallel "
            "round; use the flat clients-axis drivers for the schedule")
    module = _SeqShardedLM(lm, seq_axis)
    local_train = make_local_train(module, task, cfg,
                                   grad_sync_axes=(seq_axis,))

    def body(variables, x, y, mask, keys, weights):
        variables = _pvary(variables, (clients_axis, seq_axis))
        weights = _pvary(weights, (seq_axis,))  # psum'able over both axes
        stacked, stats = jax.vmap(
            local_train, in_axes=(None, 0, 0, 0, 0))(variables, x, y, mask,
                                                     keys)
        # every seq shard holds the identical client model (grads psum'd per
        # step), so the weighted mean over BOTH axes equals the mean over
        # clients — and clears the device-varying type for the replicated
        # output (psum over seq divides out: n_seq cancels top and bottom)
        new_vars = _weighted_psum_mean(stacked, weights,
                                       (clients_axis, seq_axis))
        # stats were already psum'd over seq inside the loss; only the
        # client axis remains
        totals = jax.tree.map(
            lambda s: jax.lax.psum(jnp.sum(s, axis=0), clients_axis), stats)
        return new_vars, totals

    seq_data = P(clients_axis, None, seq_axis)
    flat = P(clients_axis)
    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), seq_data, seq_data, flat, flat, flat),
        out_specs=(P(), P()),
    ))
