"""Async round pipeline: overlap host cohort packing + H2D upload with
device compute.

Every federated round used to be a strictly serial host→device chain:
sample the cohort, pack it on host, ``device_put`` it, and only then
dispatch — pack and upload paid their full latency on the critical path
every round (BENCH_r05 ``fedavg_powerlaw_1000``: ``pack: 30.2ms`` of a
~413ms round). But ``sample_clients(round_idx, ...)`` is a deterministic
function of the round index, so round r+1's cohort is fully known while
round r is still executing on device, and JAX's async dispatch makes the
overlap free to exploit. This is flax's ``prefetch_to_device``
double-buffering pattern applied to federated cohorts instead of batches.

(This lives next to ``pipeline.py`` — GPipe *model* pipelining over a
``pp`` mesh axis; this module pipelines the *input side* of the round.)

:class:`RoundPrefetcher` runs a caller-supplied ``produce(key)`` (host
pack + sharded upload) on ONE background thread, keeping up to ``depth``
produced slots in flight — depth 2 is classic double buffering, and the
bound is what caps HBM growth. Correctness contract:

- **bit-identical trajectories**: the prefetcher never computes anything
  itself; it runs the exact serial-path ``produce`` for the exact key, so
  the arrays a round consumes are the arrays the serial path would build.
- **donation-safe**: payloads are data arrays only (the round programs
  donate the model buffer, never the data operands); a slot is popped at
  ``get`` and dropped by the caller after its round's dispatch holds it.
- **graceful degradation**: ``depth<=0`` (or ``FEDML_TPU_PREFETCH=0``)
  means the serial path runs; a worker-thread exception is re-raised on
  the caller at ``get``; :meth:`invalidate` discards every in-flight slot
  (the mid-run dataset-swap contract, mirroring the drivers'
  ``_pack_cache``).
- **speculation misses are safe**: an out-of-sequence ``get`` (resume at
  an arbitrary round, an async server re-sampling) simply produces
  inline and re-aims the speculation stream at the new key's successors.
"""

from __future__ import annotations

import os
import queue
import threading
import time
import weakref
from typing import Any, Callable, Dict, Optional, Tuple

#: env kill switch / override: ``FEDML_TPU_PREFETCH=0`` forces the serial
#: path everywhere regardless of config (the escape hatch if a remote-PJRT
#: tunnel mishandles concurrent host threads); any other integer overrides
#: the configured depth.
PREFETCH_ENV = "FEDML_TPU_PREFETCH"

_SHUTDOWN = object()


def resolve_prefetch_depth(requested: int) -> int:
    """The effective prefetch depth: ``$FEDML_TPU_PREFETCH`` wins over the
    configured value when set (so a bad tunnel can be worked around
    without touching configs); negative values clamp to 0 (serial)."""
    env = os.environ.get(PREFETCH_ENV)
    if env is not None and env.strip() != "":
        try:
            return max(0, int(env))
        except ValueError as exc:
            raise ValueError(
                f"${PREFETCH_ENV}={env!r} is not an integer") from exc
    return max(0, int(requested))


def _worker(ref: "weakref.ref", requests: "queue.SimpleQueue") -> None:
    """Worker loop. Holds NO strong reference to the prefetcher between
    requests (only the weakref + queue), so dropping the prefetcher lets
    its ``weakref.finalize`` enqueue the shutdown sentinel and the thread
    exits instead of leaking."""
    while True:
        item = requests.get()
        if item is _SHUTDOWN:
            return
        key, gen, produce = item
        t0 = time.perf_counter()
        try:
            payload, exc = produce(key), None
        except BaseException as e:  # noqa: BLE001 — re-raised at get()
            payload, exc = None, e
        dt = time.perf_counter() - t0
        pf = ref()
        if pf is None:
            return
        with pf._cond:
            if pf._inflight.get(key) == gen:
                del pf._inflight[key]
            if gen == pf._gen and key in pf._window:
                pf._ready[key] = (payload, exc, dt)
            else:  # invalidated or mispredicted past: drop the stale slot
                pf._stats["invalidated"] += 1
            pf._cond.notify_all()
        del pf, payload, exc, item  # hold nothing while idle


class RoundPrefetcher:
    """Speculative producer of per-round host payloads.

    ``produce(key) -> payload`` is the serial path's host work for one
    round (pack + upload), called either on the worker thread (hit) or
    inline on the caller (miss). ``next_key`` predicts the key sequence
    (default ``key + 1`` for plain round indices; fused block windows use
    ``(r0, R) -> (r0 + R, R)``). After every :meth:`get` the next
    ``depth`` keys are scheduled, so steady state keeps ``depth`` slots
    in flight/ready — the HBM bound.
    """

    def __init__(self, produce: Callable[[Any], Any], depth: int,
                 next_key: Optional[Callable[[Any], Any]] = None,
                 name: str = "round-prefetch"):
        self.produce = produce
        self.depth = max(0, int(depth))
        self.next_key = next_key or (lambda k: k + 1)
        self.name = name
        self._cond = threading.Condition()
        self._ready: Dict[Any, Tuple[Any, Optional[BaseException],
                                     float]] = {}
        self._inflight: Dict[Any, int] = {}  # key -> generation
        self._window: set = set()  # keys speculation currently expects
        self._gen = 0
        self._requests: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._stats = {"hits": 0, "misses": 0, "invalidated": 0,
                       "wait_s": 0.0, "hidden_s": 0.0}
        # GC of the prefetcher (or interpreter exit) stops the worker
        self._finalizer = weakref.finalize(self, self._requests.put,
                                           _SHUTDOWN)

    # -- caller side -------------------------------------------------------
    def get(self, key, upcoming=None) -> Tuple[Any, float, bool]:
        """Payload for ``key``: ``(payload, waited_s, hit)``.

        Hit = the slot was produced (or is being produced) by the worker;
        ``waited_s`` is the time this call blocked on an in-flight slot
        (``prefetch_wait``). Miss = produced inline on this thread (the
        serial path, charged to the producer's own timer phases). Either
        way the speculation stream is re-aimed before any inline work, so
        the worker packs ahead while a miss packs here.

        ``upcoming`` — when the caller KNOWS its future key sequence
        (a driver's chunked schedule, a round loop that ends at
        ``comm_round``), pass it and exactly those keys are speculated:
        an empty list means "nothing follows; speculate nothing" (the
        end-of-run case — without it the worker would pack slots nothing
        ever consumes and they would pin HBM for the API's lifetime).
        ``None`` falls back to ``next_key`` prediction."""
        if self.depth <= 0 or self._closed:
            self._stats["misses"] += 1
            return self.produce(key), 0.0, False
        waited = 0.0
        with self._cond:
            gen = self._gen
            if key not in self._ready and self._inflight.get(key) == gen:
                t0 = time.perf_counter()
                while (self._gen == gen and key not in self._ready
                       and key in self._inflight):
                    self._cond.wait()
                waited = time.perf_counter() - t0
                self._stats["wait_s"] += waited
            slot = self._ready.pop(key, None)
            self._schedule_locked(key, upcoming)
        if slot is not None:
            payload, exc, dt = slot
            if exc is not None:
                raise exc
            self._stats["hits"] += 1
            self._stats["hidden_s"] += max(0.0, dt - waited)
            return payload, waited, True
        self._stats["misses"] += 1
        return self.produce(key), waited, False

    def _schedule_locked(self, key, upcoming=None) -> None:
        """Queue the next speculation window — ``upcoming[:depth]`` when
        the caller supplied its real schedule, else ``depth`` successors
        of ``key`` via ``next_key`` — and evict ready slots outside that
        window (caller holds the lock). The eviction is what bounds
        resident slots to ``depth`` even under persistent mispredictions —
        orphaned speculative payloads must not pin HBM."""
        gen = self._gen
        if upcoming is None:
            upcoming, k = [], key
            for _ in range(self.depth):
                k = self.next_key(k)
                upcoming.append(k)
        upcoming = list(upcoming)[:self.depth]
        window = set(upcoming)
        for k in upcoming:
            if k in self._ready or k in self._inflight:
                continue
            self._inflight[k] = gen
            self._requests.put((k, gen, self.produce))
        self._window = window  # the worker drops deliveries outside it
        for stale in [r for r in self._ready if r not in window]:
            del self._ready[stale]
            self._stats["invalidated"] += 1
        if window and (self._thread is None
                       or not self._thread.is_alive()):
            self._thread = threading.Thread(
                target=_worker, args=(weakref.ref(self), self._requests),
                name=self.name, daemon=True)
            self._thread.start()

    def invalidate(self) -> None:
        """Discard every ready and in-flight slot (mid-run dataset swap:
        the exact contract of the drivers' ``_pack_cache``). Slots already
        being produced are dropped on arrival via the generation check."""
        with self._cond:
            self._gen += 1
            self._stats["invalidated"] += len(self._ready)
            self._stats["invalidated"] += len(self._inflight)
            self._ready.clear()
            self._inflight.clear()
            self._cond.notify_all()

    def close(self) -> None:
        """Stop the worker and drop all slots; subsequent ``get`` calls
        produce inline (serial path)."""
        with self._cond:
            self._closed = True
            self._gen += 1
            self._ready.clear()
            self._inflight.clear()
            self._cond.notify_all()
        if self._finalizer.detach() is not None:
            self._requests.put(_SHUTDOWN)
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def stats(self) -> Dict[str, float]:
        """Counters for evidence rows: ``hits``/``misses``/``invalidated``
        plus ``wait_s`` (caller time blocked on in-flight slots) and
        ``hidden_s`` (worker produce time that overlapped device compute —
        the pack+upload latency removed from the critical path)."""
        with self._cond:
            return dict(self._stats)


def bind_prefetcher(slot, dataset, build):
    """Driver-side slot management, ONE definition for every consumer:
    ``slot`` is ``(RoundPrefetcher, dataset-at-bind) | None``. Builds the
    prefetcher lazily on first use; a dataset swap invalidates every
    in-flight slot (the drivers' ``_pack_cache`` contract). Returns the
    updated slot tuple."""
    if slot is None:
        return (build(), dataset)
    if slot[1] is not dataset:
        slot[0].invalidate()
        return (slot[0], dataset)
    return slot


def consume(pf: RoundPrefetcher, key, timer, dataset, repack,
            upcoming=None, round_bound=None):
    """Driver-side consume protocol, ONE definition so the sim, mesh, and
    fused-block paths cannot drift: ``get`` the slot, verify its payload
    was packed against the CURRENT dataset (``repack(key)`` serially and
    drop everything speculative if a produce raced a swap), and charge
    ``prefetch_wait`` + hit/miss counters to the round timer. The payload
    contract is ``(dataset, ...)`` — produce snapshots the dataset it
    packed from as element 0.

    With a store-backed virtual population (fedml_tpu/state/), the
    ``produce`` running on the worker IS the streaming cohort
    materialization: shard fetch (LRU/disk/generate) + pack + upload for
    round r+1 overlaps round r's device compute, and the store's cache —
    not a resident ``_pack_cache`` — is what absorbs repeat-sampled
    clients. Every consume also samples peak host RSS into the timer's
    ``host_rss_peak_mb`` gauge: the round loop's choke point is where
    the O(cohort + cache) memory claim gets measured, round by round.

    ``round_bound`` (integer keys only): speculate successor rounds
    strictly below it — the round-loop clamp that keeps the last rounds
    from packing slots nothing will consume."""
    if round_bound is not None:
        upcoming = [r for r in range(key + 1, key + 1 + pf.depth)
                    if r < round_bound]
    payload, waited, hit = pf.get(key, upcoming=upcoming)
    if payload[0] is not dataset:
        pf.invalidate()
        hit = False
        payload = repack(key)
    timer.add("prefetch_wait", waited)
    timer.count("prefetch_hit" if hit else "prefetch_miss")
    timer.update_rss()
    return payload
