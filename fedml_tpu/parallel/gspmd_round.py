"""Shared factory for federated rounds over a ('clients', <model>) mesh.

Both Megatron-TP (parallel/tensor.py) and ZeRO-FSDP (parallel/fsdp.py)
federated rounds are the same program — the vmapped FedAvg body (local SGD
scan + weighted aggregation, the semantics of the reference's
FedAVGAggregator.py:58-87 round) jitted with *parameter* shardings over the
model axis and *client-batch* shardings over the clients axis; only the
parameter-spec rule differs. This factory holds the single copy of the
round body, the sharding wiring, and the one-compile jit cache.
"""

from __future__ import annotations

from typing import Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P



def _avals_key(tree):
    """Jit-cache key: tree structure + leaf shapes/dtypes, so a
    differently-structured variables tree recompiles with fresh shardings
    instead of reusing the first call's (stale) ones. Shared with
    parallel/fsdp.py — keep the rule in one place."""
    return (jax.tree.structure(tree),
            tuple((l.shape, str(l.dtype)) for l in jax.tree.leaves(tree)))

def tree_shardings(mesh: Mesh, spec_tree):
    """PartitionSpec tree -> NamedSharding tree over ``mesh``."""
    return jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                        is_leaf=lambda s: isinstance(s, P))


def place(tree, mesh: Mesh, spec_tree):
    """device_put every leaf of ``tree`` per the matching PartitionSpec."""
    return jax.tree.map(
        lambda v, s: jax.device_put(v, NamedSharding(mesh, s)),
        tree, spec_tree, is_leaf=lambda s: isinstance(s, P))


def make_sharded_federated_round(model, task: str, cfg, mesh: Mesh,
                                 param_specs_fn: Callable,
                                 clients_axis: str = "clients",
                                 donate: bool = False):
    """FedAvg round with model params sharded per ``param_specs_fn``.

    ``param_specs_fn(variables) -> PartitionSpec tree`` decides the model
    layout (Megatron column/row, ZeRO largest-axis, ...). Client batches
    shard over ``clients_axis``; XLA inserts the intra-client collectives
    the param layout implies plus the cross-client weighted-mean reduce.

    Returns ``(round_fn, shard_params)``: ``round_fn(variables, x, y,
    mask, keys, weights)``; ``shard_params`` places a replicated variables
    tree into the model layout.
    """
    from fedml_tpu.algorithms.fedavg import make_vmapped_body
    from fedml_tpu.core import pytree as pt
    from fedml_tpu.trainer.functional import make_local_train

    if getattr(cfg, "lr_decay_round", 1.0) != 1.0:
        raise NotImplementedError(
            "lr_decay_round is not threaded through the model-parallel "
            "(gspmd) round — run the schedule on the sim/spmd drivers")
    body = make_vmapped_body(make_local_train(model, task, cfg))

    def round_fn(variables, x, y, mask, keys, weights):
        stacked, totals = body(variables, x, y, mask, keys)
        return pt.tree_weighted_mean(stacked, weights), totals

    def to_sharding(tree):
        return tree_shardings(mesh, param_specs_fn(tree))

    def shard_params(variables):
        return place(variables, mesh, param_specs_fn(variables))

    _jit = {}  # one compile per variables structure (see _avals_key)

    def jitted(variables, x, y, mask, keys, weights):
        key = _avals_key(variables)
        if key not in _jit:
            data = NamedSharding(mesh, P(clients_axis))
            _jit[key] = jax.jit(
                round_fn,
                in_shardings=(to_sharding(variables), data, data, data,
                              data, data),
                out_shardings=(to_sharding(variables), None),
                donate_argnums=(0,) if donate else ())
        return _jit[key](variables, x, y, mask, keys, weights)

    return jitted, shard_params


def make_gspmd_eval(module, task: str, mesh: Mesh,
                    param_specs_fn: Callable,
                    clients_axis: str = "clients"):
    """Sharded evaluation with model-parallel params: the eval union rides
    the ``clients`` axis, the params keep their TP/FSDP layout, and XLA
    partitions the stat-sum program (no explicit psum — the replicated
    output forces the reduce). Counterpart of spmd.make_sharded_eval for
    2-D ('clients', <model>) meshes, where shard_map's replicated-params
    contract doesn't hold."""
    from fedml_tpu.trainer.functional import make_eval

    ev = make_eval(module, task)
    _jit = {}

    def jitted(variables, x, y, mask):
        key = _avals_key(variables)
        if key not in _jit:
            data = NamedSharding(mesh, P(clients_axis))
            _jit[key] = jax.jit(
                ev,
                in_shardings=(tree_shardings(mesh,
                                             param_specs_fn(variables)),
                              data, data, data),
                out_shardings=None)
        return _jit[key](variables, x, y, mask)

    return jitted
