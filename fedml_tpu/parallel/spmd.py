"""SPMD federated rounds over a device mesh — the distributed backend.

This file is the TPU-native answer to the reference's entire distributed
stack: the MPI rank dispatch (FedAvgAPI.py:20-67), the Server/Client manager
message loops (FedAvgServerManager.py:43-93, FedAvgClientManager.py), and the
all-received barrier (FedAVGAggregator.py:50-56). On a mesh there are no
messages and no barrier code: each device trains its shard of the sampled
clients, "send model to server" is a weighted ``psum`` over the ``clients``
ICI axis, and "sync model to client" is the replication of the psum result.
One jitted program per round; the barrier is implicit in SPMD.

Scaling model (how this maps to hardware):
- clients axis -> all chips of a slice (ICI). client_num_per_round is padded
  to a multiple of the mesh size with zero-weight slots.
- hierarchical FL -> 2-D mesh ('group', 'clients'): psum over 'clients' is
  the edge aggregation, psum over 'group' the cloud aggregation
  (reference hierarchical_fl/trainer.py re-expressed as two collectives).
- multi-host: the same program under ``jax.distributed.initialize`` — XLA
  routes the psum over ICI within a slice and DCN across slices; nothing in
  this file changes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.core.sampling import (eval_subsample, round_keys,
                                     sample_clients)
from fedml_tpu.data.base import FederatedDataset
from fedml_tpu.trainer.functional import (TrainConfig, make_eval,
                                          make_local_train, round_lr_scale)
from fedml_tpu.utils.jax_compat import install_jax_compat

install_jax_compat()


def build_mesh(axis_sizes: Dict[str, int],
               devices: Optional[list] = None) -> Mesh:
    """Build a named mesh, e.g. {'clients': 8} or {'group': 2, 'clients': 4}."""
    shape = tuple(axis_sizes.values())
    names = tuple(axis_sizes.keys())
    # Auto axis types where the API has them: arrays don't get
    # mesh-committed shardings-in-types (Explicit mode pins inputs to one
    # mesh and breaks multi-mesh programs). Pre-AxisType jax is all-Auto
    # already, so omitting the kwarg is the same semantics.
    if hasattr(jax.sharding, "AxisType"):
        types = tuple(jax.sharding.AxisType.Auto for _ in names)
        if devices is None:
            return jax.make_mesh(shape, names, axis_types=types)
        return Mesh(np.asarray(devices).reshape(shape), names,
                    axis_types=types)
    if devices is None:
        return jax.make_mesh(shape, names)
    return Mesh(np.asarray(devices).reshape(shape), names)


def _pvary(tree, axes: Tuple[str, ...]):
    """Mark a replicated pytree as device-varying inside shard_map.

    Without this, ``jax.grad`` w.r.t. the replicated global params inside the
    shard_map body transposes the broadcast into an implicit ``psum`` — every
    client would receive the SUM of all clients' gradients instead of its own
    (caught by the sim==distributed parity test)."""
    if hasattr(jax.lax, "pcast"):
        return jax.tree.map(lambda v: jax.lax.pcast(v, axes, to="varying"), tree)
    return jax.tree.map(lambda v: jax.lax.pvary(v, axes), tree)


def _weighted_psum_mean(stacked, weights, axes: Tuple[str, ...]):
    """sum_i w_i * leaf_i over the local client axis, psum over mesh axes,
    divide by the global weight total — the FedAvg aggregation rule
    (FedAVGAggregator.py:58-87) as two collectives."""
    wsum = jax.tree.map(
        lambda s: jnp.tensordot(weights.astype(s.dtype), s, axes=1), stacked)
    wsum = jax.lax.psum(wsum, axes)
    wtot = jax.lax.psum(jnp.sum(weights), axes)
    return jax.tree.map(lambda s: s / wtot.astype(s.dtype), wsum)


def make_spmd_round(module, task: str, cfg: TrainConfig, mesh: Mesh,
                    axis: str = "clients", donate: bool = False,
                    check_vma: bool = True):
    """Compile one FedAvg round over ``mesh[axis]``.

    Inputs are client-major: x [P, n_pad, ...], y, mask, keys, weights with
    P = clients_per_round (a multiple of the axis size; each device trains
    P/axis_size clients via vmap). Returns (replicated new variables,
    psum-reduced train stats).

    ``donate=True`` lets XLA reuse the incoming variables' HBM for the new
    model (the driver overwrites its reference each round); leave False when
    the caller reuses the same variables across calls (parity tests).
    """
    local_train = make_local_train(module, task, cfg)
    decayed = cfg.lr_decay_round != 1.0

    def body(variables, x, y, mask, keys, weights, *maybe_r):
        variables = _pvary(variables, (axis,))
        # replicated round index -> decay**r scale, broadcast to the
        # vmapped clients (same f32 power as the sim driver's round_fn,
        # so sim==mesh parity holds under the schedule too); None traces
        # the identical constant-LR program
        scale = round_lr_scale(cfg, maybe_r[0]) if decayed else None
        stacked, stats = jax.vmap(
            lambda v, xc, yc, mc, kc: local_train(
                v, xc, yc, mc, kc, lr_scale=scale),
            in_axes=(None, 0, 0, 0, 0))(variables, x, y, mask, keys)
        new_vars = _weighted_psum_mean(stacked, weights, (axis,))
        totals = jax.tree.map(
            lambda s: jax.lax.psum(jnp.sum(s, axis=0), axis), stats)
        return new_vars, totals

    sharded = P(axis)
    in_specs = (P(), sharded, sharded, sharded, sharded, sharded)
    if decayed:  # extra replicated round-index operand
        in_specs = in_specs + (P(),)
    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(), P()),
        check_vma=check_vma,
    ), donate_argnums=(0,) if donate else ())


def make_spmd_multiround(module, task: str, cfg: TrainConfig, mesh: Mesh,
                         rounds: int, axis: str = "clients",
                         donate: bool = True, check_vma: bool = True):
    """R full-participation FedAvg rounds as ONE jitted shard_map program:
    ``lax.scan`` over round indices with the weighted ``psum`` aggregation
    inside the scan body — on a slice the host is touched once per R
    rounds instead of once per round (the mesh analogue of
    algorithms.fedavg.FusedRounds; SURVEY §7 "keep the entire round
    on-device"). Per-round/per-client keys are derived in-scan by the same
    fold_in chain the host loop uses, so the trajectory equals R calls of
    ``make_spmd_round`` with FedAvgAPI-style keys.

    Returns ``fn(variables, x, y, mask, client_ids, weights, base_key,
    r0) -> (new_variables, stats[R])`` with x/y/mask/weights client-major
    as in make_spmd_round and ``client_ids`` the uint32 global client ids
    of the local slots (used only for key derivation).
    """
    local_train = make_local_train(module, task, cfg)

    def body(variables, x, y, mask, client_ids, weights, base_key, r0):
        # client_ids/x/y/mask/weights are sharded inputs — already
        # device-varying; only the replicated variables need the pcast
        variables = _pvary(variables, (axis,))

        def one_round(vars_r, r):
            _, keys, _ = round_keys(base_key, r, client_ids)
            scale = round_lr_scale(cfg, r)
            stacked, stats = jax.vmap(
                lambda v, xc, yc, mc, kc: local_train(
                    v, xc, yc, mc, kc, lr_scale=scale),
                in_axes=(None, 0, 0, 0, 0))(vars_r, x, y,
                                            mask, keys)
            new_vars = _weighted_psum_mean(stacked, weights, (axis,))
            totals = jax.tree.map(
                lambda s: jax.lax.psum(jnp.sum(s, axis=0), axis), stats)
            # re-vary: the psum result is replicated-typed, the next scan
            # step consumes it as the (device-varying) client input again
            return _pvary(new_vars, (axis,)), totals

        new_vars, stats = jax.lax.scan(
            one_round, variables,
            r0 + jnp.arange(rounds, dtype=jnp.uint32))
        # the carry is device-varying-typed but value-identical on every
        # device (each step ends in the same psum); one pmean clears the
        # type for the replicated output at zero numeric cost
        new_vars = jax.tree.map(lambda v: jax.lax.pmean(v, axis), new_vars)
        return new_vars, stats

    sharded = P(axis)
    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), sharded, sharded, sharded, sharded, sharded, P(),
                  P()),
        out_specs=(P(), P()),
        check_vma=check_vma,
    ), donate_argnums=(0,) if donate else ())


def make_spmd_block_multiround(module, task: str, cfg: TrainConfig,
                               mesh: Mesh, axis: str = "clients",
                               donate: bool = True,
                               check_vma: bool = True):
    """R SAMPLED-cohort FedAvg rounds as ONE jitted shard_map program.

    The mesh analogue of ``algorithms.fedavg.FusedRounds`` block mode: the
    host draws the R cohorts up front with the reference sampling stream
    (FedAVGAggregator.py:89-97 np.random contract), packs them as one
    ``[R, P, n_pad, ...]`` block (P = cohort size padded to a mesh
    multiple), and this program scans the R rounds with the weighted
    ``psum`` aggregation inside the scan body — composing cohort-bucket
    packing with multi-round fusion on the slice, which
    ``make_spmd_multiround`` (full participation, federation-resident)
    cannot do for sampled regimes.

    Returns ``fn(variables, xs, ys, masks, idsR, weightsR, base_key, r0)
    -> (new_variables, stats[R])`` with the block arrays ``[R, P, ...]``
    sharded over ``axis`` on dim 1 and ``idsR`` the uint32 global client
    ids per round (key derivation via the shared fold_in chain,
    core/sampling.round_keys — trajectory parity with R ``run_round``
    calls is exact).
    """
    local_train = make_local_train(module, task, cfg)

    def body(variables, xs, ys, masks, idsR, weightsR, base_key, r0):
        variables = _pvary(variables, (axis,))

        def one_round(vars_r, inp):
            r, x, y, mask, ids, weights = inp
            _, keys, _ = round_keys(base_key, r, ids)
            scale = round_lr_scale(cfg, r)
            stacked, stats = jax.vmap(
                lambda v, xc, yc, mc, kc: local_train(
                    v, xc, yc, mc, kc, lr_scale=scale),
                in_axes=(None, 0, 0, 0, 0))(vars_r, x, y,
                                            mask, keys)
            new_vars = _weighted_psum_mean(stacked, weights, (axis,))
            totals = jax.tree.map(
                lambda s: jax.lax.psum(jnp.sum(s, axis=0), axis), stats)
            return _pvary(new_vars, (axis,)), totals

        rs = r0 + jnp.arange(xs.shape[0], dtype=jnp.uint32)
        new_vars, stats = jax.lax.scan(one_round, variables,
                                       (rs, xs, ys, masks, idsR, weightsR))
        new_vars = jax.tree.map(lambda v: jax.lax.pmean(v, axis), new_vars)
        return new_vars, stats

    blocked = P(None, axis)
    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), blocked, blocked, blocked, blocked, blocked, P(),
                  P()),
        out_specs=(P(), P()),
        check_vma=check_vma,
    ), donate_argnums=(0,) if donate else ())


def make_sharded_eval(module, task: str, mesh: Mesh, axis="clients",
                      check_vma: bool = True):
    """Evaluation sharded over the mesh: each device scores its slice of
    the eval union, stat sums meet in one psum. The multi-chip analogue of
    the reference's rank-0 test_on_server_for_all_clients
    (FedAVGAggregator.py:109) — no device ever holds the whole eval set."""
    ev = make_eval(module, task)
    axes = (axis,) if isinstance(axis, str) else tuple(axis)

    def body(variables, x, y, mask):
        stats = ev(variables, x, y, mask)  # this shard's sums
        return jax.tree.map(lambda s: jax.lax.psum(s, axes), stats)

    sharded = P(axes)
    return jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P(), sharded, sharded, sharded),
        out_specs=P(), check_vma=check_vma))


def make_hierarchical_spmd_round(module, task: str, cfg: TrainConfig,
                                 mesh: Mesh, group_comm_round: int = 1,
                                 donate: bool = False,
                                 check_vma: bool = True):
    """Two-tier FedAvg round on a ('group', 'clients') mesh: run
    ``group_comm_round`` edge rounds (train + psum over 'clients' within each
    group), then one cloud aggregation (psum over 'group') — the reference's
    hierarchical_fl group/global loop (hierarchical_fl/{trainer,group}.py) as
    nested collectives."""
    if cfg.lr_decay_round != 1.0:
        raise NotImplementedError(
            "lr_decay_round is not defined for the 2-tier round (ambiguous "
            "round index); use the flat FedAvg drivers for the schedule")
    local_train = make_local_train(module, task, cfg)

    def body(variables, x, y, mask, keys, weights):
        # carry type: group-varying; per-client variation is introduced at the
        # consumption point each edge round so the carry type stays stable
        variables = _pvary(variables, ("group",))

        def scan_body(vars_g, rkeys):
            local_vars = _pvary(vars_g, ("clients",))
            stacked, stats = jax.vmap(
                local_train, in_axes=(None, 0, 0, 0, 0))(local_vars, x, y,
                                                         mask, rkeys)
            agg = _weighted_psum_mean(stacked, weights, ("clients",))
            return agg, stats

        # fresh per-client keys per edge round
        all_keys = jax.vmap(
            lambda r: jax.vmap(
                lambda k: jax.random.fold_in(k, r))(keys))(
                    jnp.arange(group_comm_round, dtype=jnp.uint32))
        vars_g, stats_per_round = jax.lax.scan(scan_body, variables, all_keys)
        stats = jax.tree.map(lambda s: s[-1], stats_per_round)
        # cloud tier: weight each group model by its group sample count
        gw = jax.lax.psum(jnp.sum(weights), "clients")
        gsum = jax.tree.map(lambda s: s * gw.astype(s.dtype), vars_g)
        gsum = jax.lax.psum(gsum, "group")
        gtot = jax.lax.psum(gw, "group")
        new_vars = jax.tree.map(lambda s: s / gtot.astype(s.dtype), gsum)
        totals = jax.tree.map(
            lambda s: jax.lax.psum(jnp.sum(s, axis=0), ("group", "clients")),
            stats)
        return new_vars, totals

    sharded = P(("group", "clients"))
    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), sharded, sharded, sharded, sharded, sharded),
        out_specs=(P(), P()),
        check_vma=check_vma,
    ), donate_argnums=(0,) if donate else ())


@dataclasses.dataclass(frozen=True)
class DistributedFedAvgConfig:
    comm_round: int = 10
    client_num_per_round: int = 8
    frequency_of_the_test: int = 5
    seed: int = 0
    # padding policy, mirroring FedAvgConfig.pack: "cohort" (pow-2 bucket of
    # the sampled cohort's max — mesh-padded duplicate slots never raise the
    # max) or "global" (dataset-wide static shape)
    pack: str = "cohort"
    # seeded test-union eval subsample, same stream as
    # FedAvgConfig.eval_test_subsample so histories stay comparable
    eval_test_subsample: Optional[int] = None
    # async round pipeline (parallel/prefetch.py): host pack + sharded
    # device_put of round r+1 (or the next fused block window) runs on a
    # background thread while round r's dispatch executes; at most this
    # many cohorts stay in flight (2 = double buffering, 0 = serial;
    # $FEDML_TPU_PREFETCH overrides). Trajectories are bit-identical to
    # the serial path — the prefetcher runs the exact same pack for the
    # exact round index. Engages only for partial participation (full
    # participation keeps the resident _pack_cache cohort).
    prefetch_depth: int = 2
    # federation flight recorder (fedml_tpu/obs) — mirrors
    # FedAvgConfig.obs_dir/job_id; None = off, pure observer when on
    obs_dir: Optional[str] = None
    job_id: Optional[str] = None
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    # model parallelism INSIDE each client slot: shard the model over a
    # second mesh axis — "tp" (Megatron, transformer models) or "fsdp"
    # (ZeRO-3, any model) with mp_size devices per client
    model_parallel: Optional[str] = None
    mp_size: int = 1
    # named data x fsdp x tp mesh (parallel/mesh.py): e.g.
    # {"data": 4, "fsdp": 2}. Supersedes model_parallel/mp_size — ONE
    # mesh carries the federation axis AND the canonical SpecLayout
    # parameter layout, so fused block scans and fsdp/tp rounds compose
    # instead of living on disjoint 1-D meshes. Mutually exclusive with
    # model_parallel.
    mesh_shape: Optional[Dict[str, int]] = None


class DistributedFedAvgAPI:
    """Distributed FedAvg driver (parity: FedML_FedAvg_distributed,
    FedAvgAPI.py:20) — outer loop on the host, round on the mesh.

    Sampled-client shards are placed with
    ``NamedSharding(mesh, P('clients'))`` so each device receives only its
    clients' data (the client-virtualization gather, FedAVGTrainer.py:25-30).
    """

    def __init__(self, dataset: FederatedDataset, module,
                 task: str = "classification", mesh: Optional[Mesh] = None,
                 config: Optional[DistributedFedAvgConfig] = None):
        self.dataset = dataset
        self.module = module
        self.task = task
        self.config = config or DistributedFedAvgConfig()
        mp = self.config.model_parallel
        mesh_shape = getattr(self.config, "mesh_shape", None)
        if mp and mp not in ("tp", "fsdp"):
            raise ValueError(f"unknown model_parallel: {mp!r}")
        if mp and mesh_shape:
            raise ValueError(
                "mesh_shape supersedes model_parallel — declare the mp "
                "axis on the named mesh instead, e.g. "
                "mesh_shape={'data': n, 'tp': k}")
        if (mp or mesh_shape) and self.config.train.lr_decay_round != 1.0:
            raise NotImplementedError(
                "lr_decay_round is not threaded through the model-parallel "
                "(gspmd) round; use the flat clients-axis mesh")
        if self.config.pack not in ("cohort", "global"):
            raise ValueError(f"unknown pack policy: {self.config.pack!r}")
        from fedml_tpu.trainer.functional import validate_accum_steps
        validate_accum_steps(self.config.train,
                             dataset.train_data_local_num_dict)
        if mesh is None and mp:
            devs = jax.devices()
            k = self.config.mp_size
            if len(devs) % k != 0:
                raise ValueError(
                    f"mp_size {k} must divide device count {len(devs)}")
            mesh = Mesh(np.asarray(devs).reshape(len(devs) // k, k),
                        ("clients", mp))
        # named data x fsdp x tp mesh (parallel/mesh.py): the canonical
        # SpecLayout drives both the round programs and parameter
        # placement; the federation axis is 'data' instead of 'clients'
        self._layout = None
        self._data_axis = "clients"
        if mesh_shape:
            from fedml_tpu.parallel.mesh import (DEFAULT_LAYOUT,
                                                 build_named_mesh)
            if mesh is None:
                mesh = build_named_mesh(dict(mesh_shape))
            self._layout = DEFAULT_LAYOUT
            self._data_axis = DEFAULT_LAYOUT.data_axis
            if self._data_axis not in mesh.axis_names:
                raise ValueError(
                    f"named federation mesh needs a {self._data_axis!r} "
                    f"axis; got axes {mesh.axis_names}")
        self.mesh = mesh or build_mesh({"clients": len(jax.devices())})
        if mp and mp not in self.mesh.axis_names:
            raise ValueError(
                f"model_parallel={mp!r} needs a mesh axis named {mp!r}; "
                f"got axes {self.mesh.axis_names}")
        # round/eval slots pad to the FEDERATION axis ('clients', or
        # 'data' on the named mesh — == all devices when 1-D)
        self.n_dev = int(self.mesh.shape[self._data_axis])
        if self._layout is not None:
            from fedml_tpu.parallel.mesh import (make_mesh_eval,
                                                 make_mesh_federated_round)
            self._round_fn, self._shard_params = make_mesh_federated_round(
                module, task, self.config.train, self.mesh, self._layout,
                donate=True)
            self._eval_fn = make_mesh_eval(module, task, self.mesh,
                                           self._layout)
        elif mp:
            from fedml_tpu.parallel.gspmd_round import (
                make_gspmd_eval, make_sharded_federated_round)
            if mp == "tp":
                from fedml_tpu.parallel.tensor import tp_param_specs
                specs_fn = tp_param_specs()
            else:
                from fedml_tpu.parallel.fsdp import fsdp_param_specs
                specs_fn = fsdp_param_specs(int(self.mesh.shape["fsdp"]))
            self._round_fn, self._shard_params = \
                make_sharded_federated_round(module, task, self.config.train,
                                             self.mesh, specs_fn,
                                             donate=True)
            self._eval_fn = make_gspmd_eval(module, task, self.mesh,
                                            specs_fn)
        else:
            self._shard_params = None
            # flax nn.RNN creates its scan carry (zeros) inside the body,
            # which the varying-manual-axes checker rejects under
            # shard_map; recurrent models declare `flax_rnn_carry = True`
            # and run with the check off (correctness held by the
            # sim==mesh parity tests) — every other model keeps the guard
            self._check_vma = not getattr(module, "flax_rnn_carry", False)
            self._round_fn = make_spmd_round(module, task, self.config.train,
                                             self.mesh, donate=True,
                                             check_vma=self._check_vma)
            self._eval_fn = make_sharded_eval(module, task, self.mesh,
                                              check_vma=self._check_vma)
        self._n_pad = dataset.padded_len(self.config.train.batch_size)
        self._base_key = jax.random.key(self.config.seed)
        self._data_sharding = NamedSharding(self.mesh, P(self._data_axis))
        sample_x = dataset.train_data_global[0][:1]
        self.variables = module.init(jax.random.key(self.config.seed),
                                     jnp.asarray(sample_x), train=False)
        if self._shard_params is not None:  # place into the TP/FSDP layout
            self.variables = self._shard_params(self.variables)
        self.history: List[Dict] = []
        from fedml_tpu.utils.tracing import RoundTimer
        self.timer = RoundTimer()  # pack/dispatch means, as FedAvgAPI
        # observability (fedml_tpu/obs): per-round flight timeline +
        # slow-round anomaly profiling; config.obs_dir None = off
        from fedml_tpu.obs import build_observability, default_job_id
        self._obs = build_observability(
            getattr(self.config, "obs_dir", None),
            # collision-safe default (see obs.default_job_id): unset
            # job ids must not collide in a shared obs dir
            job_id=(getattr(self.config, "job_id", None)
                    or default_job_id("spmd")),
            rank=0, role="server",
            # fleet MFU denominator: the WHOLE mesh (data x fsdp x tp),
            # not just the federation axis — an fsdp/tp round must never
            # report single-chip MFU. Kind read from a mesh device so a
            # mixed host (CPU coordinator + TPU mesh) rates the mesh.
            perf_device_count=int(self.mesh.size),
            perf_device=self.mesh.devices.flat[0])
        if self._obs is not None:
            self._obs.bind_timer(self.timer)
        # same-cohort device cache as FedAvgAPI._pack_cache: full
        # participation re-samples the identical set each round, so the
        # sharded x/y/mask/weights can stay resident across rounds
        self._pack_cache = None
        # eval union: padded to a mesh multiple, sharded, device-resident
        self._eval_cache = None
        # cohort / fused-block prefetchers (parallel/prefetch.py), built
        # lazily; each is (prefetcher, dataset-at-build) so a mid-run
        # dataset swap invalidates in-flight slots like _pack_cache
        self._prefetch = None
        self._block_prefetch = None

    def _eval_global(self):
        xt, yt = self.dataset.test_data_global
        if not len(xt):
            return None
        if (self._eval_cache is None
                or self._eval_cache[0] is not self.dataset):
            xt, yt = eval_subsample(xt, yt,
                                    self.config.eval_test_subsample,
                                    self.config.seed)
            n = len(xt)
            n_pad = ((n + self.n_dev - 1) // self.n_dev) * self.n_dev
            pad = n_pad - n
            x = np.pad(np.asarray(xt), [(0, pad)] + [(0, 0)] * (xt.ndim - 1))
            y = np.pad(np.asarray(yt), [(0, pad)] + [(0, 0)] * (yt.ndim - 1))
            m = np.concatenate([np.ones(n, np.float32),
                                np.zeros(pad, np.float32)])
            put = lambda a: jax.device_put(jnp.asarray(a),
                                           self._data_sharding)
            self._eval_cache = (self.dataset, (put(x), put(y), put(m)))
        x, y, m = self._eval_cache[1]
        return self._eval_fn(self.variables, x, y, m)

    def _pad_round(self, idxs: np.ndarray):
        """Pad the sampled-client list to a mesh-size multiple with
        zero-weight duplicate slots (masked out of the aggregation)."""
        P_round = len(idxs)
        rem = (-P_round) % self.n_dev
        if rem == 0:
            return idxs, np.ones(P_round, np.float32)
        padded = np.concatenate([idxs, np.repeat(idxs[-1:], rem)])
        alive = np.concatenate([np.ones(P_round), np.zeros(rem)])
        return padded, alive.astype(np.float32)

    def _pack_cohort(self, idxs, dataset=None):
        """Cache-free pad + pack + sharded upload of one sampled cohort
        (thread-safe: no shared mutable state — the prefetcher worker runs
        this concurrently with the main thread's dispatch)."""
        cfg = self.config
        ds = dataset if dataset is not None else self.dataset
        with self.timer.phase("pack"):
            padded, alive = self._pad_round(np.asarray(idxs))
            n_pad = (ds.cohort_padded_len(padded, cfg.train.batch_size)
                     if cfg.pack == "cohort" else self._n_pad)
            x, y, mask = ds.pack_clients(padded, cfg.train.batch_size,
                                         n_pad=n_pad)
            mask = mask * alive[:, None]
            weights = ds.client_weights(padded) * alive
        with self.timer.phase("upload"):
            put = lambda a: jax.device_put(jnp.asarray(a),
                                           self._data_sharding)
            return padded, (put(x), put(y), put(mask), put(weights))

    def _pack_round(self, round_idx: int):
        """Full host side of one round (sampling, pack, upload, sharded
        per-client keys) as a function of the round index — the
        prefetcher's ``produce``. The dataset reference is snapshot once
        so a concurrent swap can't mix arrays; the payload carries it for
        the caller's identity check."""
        ds = self.dataset
        idxs = sample_clients(round_idx, ds.client_num,
                              self.config.client_num_per_round)
        padded, (xd, yd, maskd, wd) = self._pack_cohort(idxs, dataset=ds)
        _, keys, _ = round_keys(
            self._base_key, round_idx,
            jnp.asarray(np.asarray(padded), dtype=jnp.uint32))
        keysd = jax.device_put(keys, self._data_sharding)
        return ds, idxs, (xd, yd, maskd, keysd, wd)

    def _round_prefetcher(self):
        """Cohort prefetcher, or None for the serial path (depth 0 via
        config or the $FEDML_TPU_PREFETCH kill switch, or full
        participation where _pack_cache already keeps the cohort
        resident)."""
        from fedml_tpu.parallel.prefetch import (RoundPrefetcher,
                                                 bind_prefetcher,
                                                 resolve_prefetch_depth)
        depth = resolve_prefetch_depth(
            getattr(self.config, "prefetch_depth", 0))
        if (depth <= 0 or self.config.client_num_per_round
                >= self.dataset.client_num):
            if self._prefetch is not None:
                # kill switch flipped mid-run: free the resident slots
                self._prefetch[0].invalidate()
            return None
        self._prefetch = bind_prefetcher(
            self._prefetch, self.dataset,
            lambda: RoundPrefetcher(self._pack_round, depth,
                                    name="mesh-cohort-prefetch"))
        return self._prefetch[0]

    def prefetch_stats(self):
        """Merged cohort + block prefetcher counters, or None when every
        round ran the serial path — evidence hook for bench/tests."""
        out = None
        for pf in (self._prefetch, self._block_prefetch):
            if pf is None:
                continue
            stats = pf[0].stats()
            if out is None:
                out = stats
            else:
                for k, v in stats.items():
                    out[k] = out[k] + v
        return out

    def release_prefetch(self):
        """Drop every speculative slot (their device buffers — a block
        slot is a whole ``[R, P, n_pad, ...]`` sharded window) without
        stopping the workers. ``train``/``train_fused`` end clean on
        their own (the speculation clamp / final ``()`` window), but a
        DIRECT ``run_rounds_fused`` loop leaves its last speculative
        window resident — call this when it finishes if you need the HBM
        back before the API dies."""
        for pf in (self._prefetch, self._block_prefetch):
            if pf is not None:
                pf[0].invalidate()

    def run_round(self, round_idx: int):
        # flight-recorder round boundary (fedml_tpu/obs) — same pure-
        # observer wiring as FedAvgAPI.run_round
        self.timer.begin_round(round_idx)
        if self._obs is not None:
            self._obs.round_begin(round_idx)
        pf = self._round_prefetcher()
        if pf is not None:
            from fedml_tpu.parallel.prefetch import consume
            _, idxs, args = consume(pf, round_idx, self.timer,
                                    self.dataset, self._pack_round,
                                    round_bound=self.config.comm_round)
            xd, yd, maskd, keysd, wd = args
        else:
            cfg = self.config
            idxs = sample_clients(round_idx, self.dataset.client_num,
                                  cfg.client_num_per_round)
            cohort = tuple(int(i) for i in idxs)
            if (self._pack_cache is not None
                    and self._pack_cache[0] is self.dataset
                    and self._pack_cache[1] == cohort):
                padded, xd, yd, maskd, wd = self._pack_cache[2]
            else:
                self._pack_cache = None
                padded, (xd, yd, maskd, wd) = self._pack_cohort(idxs)
                if len(idxs) == self.dataset.client_num:
                    self._pack_cache = (self.dataset, cohort,
                                        (padded, xd, yd, maskd, wd))
            _, keys, _ = round_keys(
                self._base_key, round_idx,
                jnp.asarray(np.asarray(padded), dtype=jnp.uint32))
            keysd = jax.device_put(keys, self._data_sharding)
        decayed = self.config.train.lr_decay_round != 1.0
        if self._obs is not None:
            # one-shot roofline probe (obs/perf.py): trace the sharded
            # round program at GLOBAL shapes — analytic_flops then counts
            # the whole-mesh FLOPs, matching the fleet peak the perf
            # accountant was built with (perf_device_count=mesh.size).
            # Traced before dispatch so donation can't invalidate inputs.
            from fedml_tpu.utils.flops import analytic_flops
            args = ((self.variables, xd, yd, maskd, keysd, wd,
                     jnp.uint32(round_idx)) if decayed
                    else (self.variables, xd, yd, maskd, keysd, wd))
            self._obs.probe_round_flops(
                lambda: analytic_flops(self._round_fn, *args),
                source="analytic_conv_gn_jaxpr")
        with self.timer.phase("dispatch"):
            if decayed:
                # decayed builder takes the replicated round index as its
                # final operand (make_spmd_round's conditional spec)
                self.variables, stats = self._round_fn(
                    self.variables, xd, yd, maskd, keysd, wd,
                    jnp.uint32(round_idx))
            else:
                self.variables, stats = self._round_fn(
                    self.variables, xd, yd, maskd, keysd, wd)
        rec = self.timer.end_round(
            round_idx, extra={"cohort": [int(i) for i in idxs]})
        if self._obs is not None:
            self._obs.round_end(round_idx,
                                rec["duration_s"] if rec else None,
                                record=rec)
        return idxs, stats

    def run_rounds_fused(self, r0: int, rounds: int, next_window=None):
        """Advance the model by ``rounds`` rounds in ONE device dispatch.

        Full participation (``client_num_per_round == client_num``): the
        federation is packed and uploaded once, resident across calls, and
        per-round keys derive in-scan (make_spmd_multiround). Sampled
        cohorts: the R cohorts are drawn host-side with the host loop's
        exact sampling stream, packed as one ``[R, P, n_pad, ...]`` block
        at the block's cohort bucket, and scanned in one dispatch
        (make_spmd_block_multiround) — both throughput levers at once,
        trajectory-identical to R ``run_round`` calls. Returns stacked
        per-round stats.

        ``next_window``: the caller's ACTUAL next ``(r0, rounds)`` window
        (``train_fused`` knows its whole chunk schedule up front), so the
        block prefetcher packs exactly that window behind this dispatch;
        the bare ``(r0 + rounds, rounds)`` guess would miss at every
        eval-boundary chunk-size change and waste whole-window speculative
        uploads. ``()`` means "nothing follows" (last window: speculate
        nothing); None keeps the uniform-window guess for direct callers."""
        cfg = self.config
        N = self.dataset.client_num
        if cfg.model_parallel:
            raise ValueError(
                "fused mesh rounds support the flat 'clients' mesh or a "
                "named mesh_shape mesh; legacy model_parallel does not "
                "compose with the fused scan")
        if self._layout is not None or cfg.client_num_per_round != N:
            # named mesh: the GSPMD block scan serves full AND sampled
            # participation (the resident full-federation fast path below
            # is a shard_map program on the 'clients' axis only)
            return self._run_block_fused(r0, rounds,
                                         next_window=next_window)
        if (getattr(self, "_fused_data", None) is None
                or self._fused_data[0] is not self.dataset):
            padded, alive = self._pad_round(np.arange(N))
            x, y, mask = self.dataset.pack_clients(
                padded, cfg.train.batch_size, n_pad=self._n_pad)
            mask = mask * alive[:, None]
            weights = self.dataset.client_weights(padded) * alive
            put = lambda a: jax.device_put(jnp.asarray(a),
                                           self._data_sharding)
            # keyed by dataset identity like _pack_cache/_eval_cache: a
            # mid-run dataset swap must invalidate the resident arrays
            self._fused_data = (self.dataset,
                                (put(x), put(y), put(mask),
                                 put(jnp.asarray(np.asarray(padded),
                                                 dtype=jnp.uint32)),
                                 put(weights)))
            self._fused_fns = {}
        if rounds not in self._fused_fns:
            self._fused_fns[rounds] = make_spmd_multiround(
                self.module, self.task, cfg.train, self.mesh, rounds,
                check_vma=getattr(self, "_check_vma", True))
        self.variables, stats = self._fused_fns[rounds](
            self.variables, *self._fused_data[1], self._base_key,
            jnp.uint32(r0))
        return stats

    def _pack_block(self, key):
        """Host side of one fused block window ``key = (r0, rounds)``:
        draw the R cohorts with the host sampling stream, pack them as one
        ``[R, P, n_pad, ...]`` batch, shard-upload. Thread-safe (the block
        prefetcher's ``produce``); the payload carries the dataset for the
        caller's identity check."""
        r0, rounds = key
        cfg = self.config
        bsz = cfg.train.batch_size
        ds = self.dataset
        with self.timer.phase("pack"):
            cohorts = [sample_clients(r, ds.client_num,
                                      cfg.client_num_per_round)
                       for r in range(r0, r0 + rounds)]
            padded_alive = [self._pad_round(np.asarray(c)) for c in cohorts]
            flat = np.concatenate([p for p, _ in padded_alive])
            alive = np.concatenate([a for _, a in padded_alive])
            n_pad = (max(ds.cohort_padded_len(c, bsz) for c in cohorts)
                     if cfg.pack == "cohort" else self._n_pad)
            x, y, mask = ds.pack_clients(flat, bsz, n_pad=n_pad)
            mask = mask * alive[:, None]
            weights = ds.client_weights(flat) * alive
            P_pad = len(padded_alive[0][0])  # cohort padded to the mesh
            lead = (rounds, P_pad)
        with self.timer.phase("upload"):
            put = lambda a: jax.device_put(
                jnp.asarray(a), NamedSharding(self.mesh,
                                              P(None, self._data_axis)))
            args = (put(x.reshape(lead + x.shape[1:])),
                    put(y.reshape(lead + y.shape[1:])),
                    put(mask.reshape(lead + mask.shape[1:])),
                    put(flat.astype(np.uint32).reshape(lead)),
                    put(weights.reshape(lead)))
        return ds, args

    def _block_prefetcher(self):
        """Fused-block-window prefetcher. Clamped to ONE window ahead
        regardless of prefetch_depth: each slot holds a whole R-round
        block, so depth 1 is already double buffering and deeper
        speculation would multiply HBM by block size."""
        from fedml_tpu.parallel.prefetch import (RoundPrefetcher,
                                                 bind_prefetcher,
                                                 resolve_prefetch_depth)
        depth = resolve_prefetch_depth(
            getattr(self.config, "prefetch_depth", 0))
        if depth <= 0:
            if self._block_prefetch is not None:
                # kill switch flipped mid-run: a block slot is a whole
                # [R, P, n_pad, ...] sharded window — free it
                self._block_prefetch[0].invalidate()
            return None
        self._block_prefetch = bind_prefetcher(
            self._block_prefetch, self.dataset,
            lambda: RoundPrefetcher(self._pack_block, depth=1,
                                    next_key=lambda k: (k[0] + k[1], k[1]),
                                    name="mesh-block-prefetch"))
        return self._block_prefetch[0]

    def _run_block_fused(self, r0: int, rounds: int, next_window=None):
        """Sampled-cohort fused block on the mesh: host-drawn cohorts,
        one [R, P, n_pad, ...] sharded upload, one scan dispatch. With
        prefetching on, the NEXT window's pack + upload runs behind this
        window's scan (the caller's real schedule when supplied, see
        run_rounds_fused)."""
        pf = self._block_prefetcher()
        if pf is not None:
            from fedml_tpu.parallel.prefetch import consume
            upcoming = (None if next_window is None
                        else ([tuple(next_window)] if next_window else []))
            _, args = consume(pf, (r0, rounds), self.timer,
                              self.dataset, self._pack_block,
                              upcoming=upcoming)
        else:
            _, args = self._pack_block((r0, rounds))
        if getattr(self, "_block_fn", None) is None:
            # one jitted program; jit's own shape-keyed trace cache
            # specializes per (R, P_pad, n_pad) block shape
            if self._layout is not None:
                from fedml_tpu.parallel.mesh import make_mesh_block_multiround
                self._block_fn = make_mesh_block_multiround(
                    self.module, self.task, self.config.train, self.mesh,
                    self._layout, donate=True)
            else:
                self._block_fn = make_spmd_block_multiround(
                    self.module, self.task, self.config.train, self.mesh,
                    check_vma=getattr(self, "_check_vma", True))
        with self.timer.phase("dispatch"):
            self.variables, stats = self._block_fn(
                self.variables, *args, self._base_key, jnp.uint32(r0))
        return stats

    def train_fused(self, max_rounds_per_dispatch: Optional[int] = None
                    ) -> Dict:
        """The round loop with fused dispatches: one device call per eval
        interval (capped at ``max_rounds_per_dispatch``), eval after rounds
        0, freq, 2*freq, ..., and the last round — the same cadence as
        ``train()``, so fused and host histories line up (the mesh analogue
        of FusedRounds.train)."""
        from fedml_tpu.algorithms.fedavg import _normalized
        cfg = self.config
        if self._obs is not None:
            import logging
            # same caveat as FedAvgAPI.fused_rounds: fused scans have no
            # per-round host boundary to record
            logging.warning(
                "observability is on but train_fused dispatches whole "
                "round blocks — no per-round flight records for fused "
                "spans; use train() for per-round timelines")
        if cfg.comm_round <= 0:
            return self.history[-1] if self.history else {}
        freq = cfg.frequency_of_the_test
        evals = sorted({r for r in range(0, cfg.comm_round, freq)}
                       | {cfg.comm_round - 1})
        # the whole chunk schedule is known up front — computed here so
        # each dispatch can hand the block prefetcher its REAL successor
        # window (chunk sizes change at eval boundaries, which a uniform
        # stride guess would miss every time)
        windows, r = [], 0
        for e in evals:
            while r <= e:
                chunk = e + 1 - r
                if max_rounds_per_dispatch:
                    chunk = min(chunk, max_rounds_per_dispatch)
                windows.append((r, chunk, e))
                r += chunk
        wi = 0
        for e in evals:
            stats = None
            while wi < len(windows) and windows[wi][2] == e:
                w0, chunk, _ = windows[wi]
                nxt = (windows[wi + 1][:2] if wi + 1 < len(windows)
                       else ())
                stats = self.run_rounds_fused(w0, chunk, next_window=nxt)
                wi += 1
            rec = {"round": e,
                   "train_loss_local": (
                       float(stats["loss_sum"][-1])
                       / max(1.0, float(stats["count"][-1])))}
            with self.timer.phase("device_wait"):
                # ft: allow[FT003] eval-boundary sync, by design
                jax.block_until_ready(self.variables)
            with self.timer.phase("eval"):
                test_stats = self._eval_global()
            if test_stats is not None:
                rec.update(_normalized(test_stats, "test"))
            self.history.append(rec)
        return self.history[-1] if self.history else {}

    def train(self, checkpoint_mgr=None, resume: bool = False) -> Dict:
        """Round loop with optional round-level checkpoint/resume: client
        sampling and per-client RNG are (seed, round)-derived, so restarting
        from ``(round_idx, variables)`` is bit-identical to never stopping
        (utils/checkpoint.py)."""
        import time

        from fedml_tpu.algorithms.fedavg import _normalized, _progress_log
        cfg = self.config
        if (checkpoint_mgr is not None and self._obs is not None
                and getattr(cfg, "job_id", None) is None):
            # re-key the derived default id onto the run's durable
            # namespace BEFORE any record lands: a crash-resumed leg must
            # rejoin its own flight timeline, not fork a phantom second
            # job under a fresh nonce (obs.default_job_id stable_key)
            from fedml_tpu.obs import default_job_id
            self._obs.recorder.job_id = default_job_id(
                "spmd", stable_key=checkpoint_mgr.directory)
        t0 = time.time()
        start = 0
        if checkpoint_mgr is not None and resume:
            restored = checkpoint_mgr.restore_latest(
                {"variables": self.variables})
            if restored:
                state, meta = restored
                self.variables = state["variables"]
                start = meta["round_idx"]
        for round_idx in range(start, cfg.comm_round):
            _, stats = self.run_round(round_idx)
            _progress_log.info("round %d/%d dispatched (wall %.1fs)",
                               round_idx + 1, cfg.comm_round,
                               time.time() - t0)
            last = round_idx == cfg.comm_round - 1
            if round_idx % cfg.frequency_of_the_test == 0 or last:
                rec = {"round": round_idx,
                       "train_loss_local": float(stats["loss_sum"]) / max(
                           1.0, float(stats["count"]))}
                with self.timer.phase("device_wait"):
                    # ft: allow[FT003] eval-boundary sync, by design
                    jax.block_until_ready(self.variables)
                with self.timer.phase("eval"):
                    test_stats = self._eval_global()
                if test_stats is not None:
                    rec.update(_normalized(test_stats, "test"))
                self.history.append(rec)
            if checkpoint_mgr is not None:
                checkpoint_mgr.save(round_idx + 1,
                                    {"variables": self.variables})
        return self.history[-1] if self.history else {}


# -- static-analysis hook (fedml_tpu.analysis layer 2) ----------------------
from fedml_tpu.analysis.registry import AuditSpec, hot_entry_point  # noqa: E402


@hot_entry_point("spmd.block_multiround")
def _audit_block_multiround() -> AuditSpec:
    """The fused mesh block (make_spmd_block_multiround) over two real
    [R, P, n_pad, ...] windows built by the driver's own _pack_block:
    consecutive windows of one run must share one lowering (pack="global"
    pins n_pad; P is the cohort padded to the mesh). Mesh size adapts to
    the backend (8 virtual CPU devices under CI, 1 on a bare host) —
    the audit checks the program, not the device count."""
    from fedml_tpu.data.synthetic import make_blob_federated
    from fedml_tpu.models.lr import LogisticRegression

    n_dev = len(jax.devices())
    ds = make_blob_federated(client_num=max(4, n_dev), n_samples=240, seed=0)
    api = DistributedFedAvgAPI(
        ds, LogisticRegression(num_classes=ds.class_num),
        mesh=build_mesh({"clients": n_dev}),
        config=DistributedFedAvgConfig(
            comm_round=4, client_num_per_round=max(2, n_dev), pack="global",
            prefetch_depth=0,
            train=TrainConfig(epochs=1, batch_size=8)))
    fn = make_spmd_block_multiround(api.module, api.task, api.config.train,
                                    api.mesh,
                                    check_vma=getattr(api, "_check_vma",
                                                      True))

    def window(r0, rounds):
        _, args = api._pack_block((r0, rounds))
        return (api.variables, *args, api._base_key, jnp.uint32(r0))

    return AuditSpec(fn=fn, sweep=[window(0, 2), window(2, 2)],
                     max_lowerings=1, grad_path=True)


@hot_entry_point("spmd.sharded_eval")
def _audit_sharded_eval() -> AuditSpec:
    """The shard_map'd eval path (make_sharded_eval): per-device stat
    sums meeting in one psum over 'clients'. Registered so the
    collective-signature audit (FT105/FT106) pins the psum set of the
    sharded eval lowering — the mesh work inherits drift detection on
    its simplest collective program. The eval batch (24) divides every
    CI device count (1 and 8), so one lowering serves both."""
    from fedml_tpu.data.synthetic import make_blob_federated
    from fedml_tpu.models.lr import LogisticRegression

    n_dev = len(jax.devices())
    mesh = build_mesh({"clients": n_dev})
    ds = make_blob_federated(client_num=4, n_samples=240, seed=0)
    module = LogisticRegression(num_classes=ds.class_num)
    xt, yt = ds.test_data_global
    n = (24 // n_dev) * n_dev or n_dev  # largest multiple of n_dev <= 24
    xt, yt = jnp.asarray(xt[:n]), jnp.asarray(yt[:n])
    mask = jnp.ones(len(xt), jnp.float32)
    variables = module.init(jax.random.key(0), xt[:1], train=False)
    fn = make_sharded_eval(module, "classification", mesh)
    # sweep point 2 mirrors the actor path: wire-decoded NUMPY arrays
    # (uncommitted) — a different caller that must share the jnp-typed
    # point's lowering key, like the cross-silo warmup contract
    np_args = (variables, np.asarray(xt), np.asarray(yt),
               np.ones(len(xt), np.float32))
    return AuditSpec(fn=fn,
                     sweep=[(variables, xt, yt, mask), np_args],
                     max_lowerings=1, grad_path=False)
