"""Pure, jittable local-training and evaluation programs.

This is the TPU-native replacement for the reference's hot loop
(fedml_api/distributed/fedavg/MyModelTrainer.py:19-49: python epochs × torch
DataLoader batches). Here one client's whole local-training pass —
``epochs × batches`` of forward/CE/backward/SGD — is a single ``lax.scan``
over a precomputed (epoch-shuffled) index array of padded batches, so XLA
compiles it into one fused device program. Under ``jax.vmap`` it trains every
sampled client simultaneously (standalone simulation); under ``shard_map`` it
becomes the per-shard body of the distributed SPMD round.

Data layout per client: flat padded arrays ``x: [n_pad, ...]``, ``y``,
``mask: [n_pad]`` with ``n_pad`` a multiple of the batch size; the mask
weights the loss so padding rows contribute zero gradient and the per-batch
loss equals torch's mean over the real examples.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.trainer.tasks import TASK_HEADS, TaskHead


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Local-training hyperparameters (reference argparse flags:
    --epochs --batch_size --client_optimizer --lr --wd)."""

    epochs: int = 1
    batch_size: Optional[int] = None  # None = full batch (one step per epoch)
    lr: float = 0.03
    client_optimizer: str = "sgd"  # "sgd" | "adam"
    wd: float = 0.0
    momentum: float = 0.0
    shuffle: bool = True
    # mixed precision: run forward/backward in this dtype (e.g. "bfloat16"
    # — the MXU's native input type) while master params, optimizer state,
    # loss, and metrics stay float32. None = pure f32 (parity tests).
    compute_dtype: Optional[str] = None
    # gradient accumulation: average grads over k consecutive micro-batches
    # before each optimizer step (effective batch = k * batch_size at the
    # HBM footprint of one micro-batch)
    accum_steps: int = 1
    # per-ROUND exponential client-LR decay: effective lr at round r is
    # ``lr * lr_decay_round ** r``. 1.0 = constant lr (the reference's only
    # mode — its argparse has no schedule; FedAvg-paper-style decay is the
    # standard fix for the constant-LR late-round overfit tail seen on the
    # fed_cifar100 anchor). Exact, not approximate: the client optimizer is
    # reconstructed fresh each round (reference MyModelTrainer.py:26-31
    # semantics) and lr enters optax's sgd/adam updates as a final
    # multiplicative scale, so scaling the round's updates by decay**r IS
    # running the round at lr*decay**r.
    lr_decay_round: float = 1.0


def validate_accum_steps(cfg: TrainConfig, client_sizes) -> None:
    """Host-side accum_steps guard: MultiSteps emits an optimizer update
    only on every k-th REAL micro-batch (padding-only batches are gated
    no-ops), so a client whose ``epochs * ceil(n_i / bsz)`` is not a
    multiple of ``accum_steps`` silently drops its trailing micro-batches
    (worst case: zero optimizer steps). The real batch count is per-client
    data the traced trainer cannot see — drivers that know the federation's
    sizes call this at construction."""
    if cfg.accum_steps <= 1:
        return
    bad = {}
    for c, n in dict(client_sizes).items():
        bsz = cfg.batch_size or n
        # an empty client has zero real batches -> zero optimizer steps,
        # which accum_steps>1 cannot fix; flag it rather than divide by 0
        real_steps = cfg.epochs * -(-n // bsz) if bsz else 0
        if real_steps % cfg.accum_steps != 0:
            bad[c] = real_steps
    if bad:
        some = dict(list(bad.items())[:5])
        raise ValueError(
            f"accum_steps={cfg.accum_steps} must divide every client's "
            f"epochs*ceil(n_i/batch_size); offending clients (first 5 of "
            f"{len(bad)}): {some} — trailing real micro-batches would be "
            "silently dropped")


def round_lr_scale(cfg: TrainConfig, round_idx):
    """In-graph per-round client-LR scale ``lr_decay_round ** round_idx``,
    or None when the schedule is off (so constant-LR programs are traced
    without the extra multiply). ``round_idx`` may be a host int or a traced
    scalar (fused drivers derive it inside the round scan); the f32 power is
    computed the same way on every path so host-loop and fused trajectories
    stay bit-identical."""
    if cfg.lr_decay_round == 1.0:
        return None
    return jnp.power(jnp.float32(cfg.lr_decay_round),
                     jnp.asarray(round_idx).astype(jnp.float32))


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    """Client optimizer factory, matching the reference's two choices
    (MyModelTrainer.py:26-31): plain SGD, or Adam(amsgrad) with L2-style
    weight decay folded into the gradient like torch's ``weight_decay``."""
    def wrap(tx: optax.GradientTransformation):
        if cfg.accum_steps > 1:
            return optax.MultiSteps(tx, every_k_schedule=cfg.accum_steps)
        return tx

    if cfg.client_optimizer == "sgd":
        if cfg.momentum:
            return wrap(optax.sgd(cfg.lr, momentum=cfg.momentum))
        return wrap(optax.sgd(cfg.lr))
    if cfg.client_optimizer == "adam":
        steps = []
        if cfg.wd:
            steps.append(optax.add_decayed_weights(cfg.wd))
        steps.append(optax.amsgrad(cfg.lr))
        return wrap(optax.chain(*steps))
    raise ValueError(f"unknown client_optimizer: {cfg.client_optimizer!r}")


def make_forward(module) -> Callable:
    """Uniform apply over a variables dict {'params', [other collections]}.

    Returns ``(outputs, updated_collections)``; in train mode non-param
    collections (e.g. flax ``batch_stats``) are mutable, mirroring how the
    reference ships the *full* state_dict (weights + BN running stats) through
    aggregation (FedAVGAggregator.py:58-87 averages every key).
    """

    def forward(variables, x, train: bool, rng=None):
        rngs = {"dropout": rng} if (train and rng is not None) else None
        mutable = [k for k in variables if k != "params"]
        if train:
            out, updates = module.apply(variables, x, train=True, rngs=rngs,
                                        mutable=mutable)
            return out, {**variables, **updates}
        out = module.apply(variables, x, train=False)
        return out, variables

    return forward


def make_batch_schedule(n_pad: int, epochs: int, bsz: int, shuffle: bool,
                        rng, mask=None):
    """Shared epochs×batches schedule: per-epoch permutations reshaped to
    [epochs*nb, bsz] index batches plus one dropout key per step. Used by the
    FedAvg local trainer and custom local trainers (FedNova) so shuffle
    semantics cannot diverge.

    The schedule is PADDING-INVARIANT: row ``i``'s sort key is derived from
    ``fold_in(epoch_key, i)`` alone, and padding rows (``mask == 0``) sort
    last, so the order restricted to real rows — and therefore the whole
    trajectory — is identical for every ``n_pad`` the caller packs to. This
    is what lets cohort-bucket packing, global packing, and fused R-round
    blocks (one static shape for R cohorts) share one trajectory. It is
    also the reference's DataLoader semantics: full real batches, then one
    partial boundary batch, then pure-padding batches that the trainers
    gate into no-ops (local_train's ``has_real``); the reference shuffles
    only real samples (torch DataLoader(shuffle=True),
    MyModelTrainer.py:19-49)."""
    assert n_pad % bsz == 0, "data must be padded to a batch multiple"
    nb = n_pad // bsz
    perm_key, step_key = jax.random.split(rng)
    epoch_keys = jax.random.split(perm_key, epochs)
    rows = jnp.arange(n_pad)
    if shuffle:
        def epoch_perm(k):
            vals = jax.vmap(
                lambda i: jax.random.bits(jax.random.fold_in(k, i)))(rows)
            if mask is not None:
                # padding last; ties resolve by row index (stable argsort),
                # and real rows always have lower indices than padding
                vals = jnp.where(mask > 0, vals, jnp.uint32(0xFFFFFFFF))
            return jnp.argsort(vals)
        perms = jax.vmap(epoch_perm)(epoch_keys)
    else:
        # pack_clients lays real rows first, so the identity order already
        # has padding last
        perms = jnp.tile(rows, (epochs, 1))
    batch_idx = perms.reshape(epochs * nb, bsz)
    # step (dropout) keys are per (epoch, batch-position): batch b of epoch
    # e gets the same key at every n_pad, keeping stochastic layers on the
    # padding-invariant trajectory too
    step_keys = jax.vmap(
        lambda ek: jax.vmap(lambda b: jax.random.fold_in(ek, b))(
            jnp.arange(nb)))(jax.random.split(step_key, epochs))
    return batch_idx, step_keys.reshape(epochs * nb)


def make_local_train(module, task: str, cfg: TrainConfig,
                     grad_sync_axes: tuple = ()):
    """Build ``local_train(variables, x, y, mask, rng) -> (variables, stats)``.

    One call = the reference's ``ModelTrainer.train`` for one client: fresh
    optimizer (the reference constructs a new torch optimizer every call, so
    client momentum never crosses rounds), ``cfg.epochs`` passes with per-epoch
    reshuffling, mask-weighted per-batch mean loss.

    ``grad_sync_axes``: mesh axis names this client's model is itself
    sharded over inside a ``shard_map`` (e.g. ('seq',) for sequence-parallel
    clients): per-step loss terms and gradients are psum'd over them so
    every shard takes the identical optimizer step.
    """
    head: TaskHead = TASK_HEADS[task]
    forward = make_forward(module)
    tx = make_optimizer(cfg)
    cdtype = jnp.dtype(cfg.compute_dtype) if cfg.compute_dtype else None

    def _to_compute(tree):
        return jax.tree.map(
            lambda a: a.astype(cdtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)

    def _to_f32(tree):
        return jax.tree.map(
            lambda a: a.astype(jnp.float32)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)

    def local_train(variables, x, y, mask, rng, lr_scale=None):
        n_pad = x.shape[0]
        bsz = cfg.batch_size or n_pad
        # accum_steps divisibility cannot be checked here: only REAL
        # batches advance MultiSteps (padding-only batches are has_real
        # no-ops), and the real count is per-client data, not the static
        # n_pad. Drivers that know client sizes call
        # validate_accum_steps() host-side instead.
        batch_idx, step_keys = make_batch_schedule(n_pad, cfg.epochs, bsz,
                                                   cfg.shuffle, rng,
                                                   mask=mask)
        params = variables["params"]
        opt_state = tx.init(params)
        init = (params, {k: v for k, v in variables.items() if k != "params"},
                opt_state)

        def step(carry, inp):
            params, colls, opt_state = carry
            idx, key = inp
            xb = jnp.take(x, idx, axis=0)
            yb = jnp.take(y, idx, axis=0)
            mb = jnp.take(mask, idx, axis=0)

            def loss_fn(p):
                if cdtype is not None:
                    # bf16 forward/backward off f32 masters: the cast is on
                    # the autodiff path, so grads come back f32; updated
                    # collections (BN stats) are restored to f32 to keep the
                    # scan carry type stable
                    out, new_vars = forward(
                        {"params": _to_compute(p), **_to_compute(colls)},
                        _to_compute(xb), True, key)
                    out = out.astype(jnp.float32)
                    new_vars = _to_f32(new_vars)
                else:
                    out, new_vars = forward({"params": p, **colls}, xb,
                                            True, key)
                stats = head(out, yb, mb)
                if grad_sync_axes:
                    # differentiate the UNNORMALIZED local loss sum and
                    # keep every psum outside the grad: the client's loss
                    # is psum(loss_sum)/psum(count), whose gradient is
                    # psum(d loss_sum/dθ)/psum(count) because count does
                    # not depend on θ — so syncing and normalizing after
                    # jax.grad is exact, and it sidesteps the psum
                    # transpose entirely (pre-VMA jax transposes psum to
                    # psum, which would scale in-grad-synced gradients by
                    # the axis size)
                    loss = stats["loss_sum"]
                else:
                    loss = stats["loss_sum"] / jnp.maximum(stats["count"],
                                                           1.0)
                return loss, (new_vars, stats)

            grads, (new_vars, stats) = jax.grad(loss_fn, has_aux=True)(params)
            if grad_sync_axes:
                # each shard's backward holds only its tokens' terms of
                # d[loss_sum]/dθ; the psum + global-count normalization
                # completes the exact full-sequence gradient on every shard
                stats = jax.tree.map(
                    lambda s: jax.lax.psum(s, grad_sync_axes), stats)
                denom = jnp.maximum(stats["count"], 1.0)
                grads = jax.tree.map(
                    lambda g: g / denom,
                    jax.lax.psum(grads, grad_sync_axes))
            updates, new_opt_state = tx.update(grads, opt_state, params)
            if lr_scale is not None:
                # round-level lr schedule (TrainConfig.lr_decay_round):
                # exact because the optimizer is fresh per call and lr is a
                # final multiplicative scale in sgd/adam updates
                updates = jax.tree.map(lambda u: u * lr_scale, updates)
            new_params = optax.apply_updates(params, updates)
            # padding-only batches (small client, dataset-wide n_pad) must be
            # true no-ops: zero grads still move stateful optimizers
            # (weight decay, momentum, adam count), so gate the whole update
            has_real = stats["count"] > 0

            def sel(new, old):
                return jax.tree.map(lambda a, b: jnp.where(has_real, a, b),
                                    new, old)

            params = sel(new_params, params)
            opt_state = sel(new_opt_state, opt_state)
            colls = sel({k: v for k, v in new_vars.items() if k != "params"},
                        colls)
            return (params, colls, opt_state), stats

        (params, colls, _), stats = jax.lax.scan(
            step, init, (batch_idx, step_keys))
        total = jax.tree.map(lambda s: jnp.sum(s, axis=0), stats)
        return {"params": params, **colls}, total

    return local_train


def make_eval(module, task: str, eval_batch_size: int = 512):
    """Build ``evaluate(variables, x, y, mask) -> stat sums`` that scans fixed
    eval batches (deterministic mode, no dropout), the jittable analogue of
    the reference's ``ModelTrainer.test`` loop (MyModelTrainer.py:51-96)."""
    head: TaskHead = TASK_HEADS[task]
    forward = make_forward(module)

    def evaluate(variables, x, y, mask):
        n = x.shape[0]
        if n == 0:
            # empty eval set: run the head once on a zero dummy batch with a
            # zero mask so the stat keys exist and all sums are 0
            dummy_x = jnp.zeros((1,) + x.shape[1:], x.dtype)
            dummy_y = jnp.zeros((1,) + y.shape[1:], y.dtype)
            out, _ = forward(variables, dummy_x, False)
            return head(out, dummy_y, jnp.zeros((1,), jnp.float32))
        bsz = min(eval_batch_size, n)
        n_pad = ((n + bsz - 1) // bsz) * bsz
        pad = n_pad - n
        if pad:
            x_p = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
            y_p = jnp.pad(y, [(0, pad)] + [(0, 0)] * (y.ndim - 1))
            m_p = jnp.pad(mask, (0, pad))
        else:
            x_p, y_p, m_p = x, y, mask
        nb = n_pad // bsz
        xb = x_p.reshape((nb, bsz) + x.shape[1:])
        yb = y_p.reshape((nb, bsz) + y.shape[1:])
        mb = m_p.reshape(nb, bsz)

        def step(carry, batch):
            bx, by, bm = batch
            out, _ = forward(variables, bx, False)
            stats = head(out, by, bm)
            return carry, stats

        _, stats = jax.lax.scan(step, 0, (xb, yb, mb))
        return jax.tree.map(lambda s: jnp.sum(s, axis=0), stats)

    return evaluate
