"""The framework-agnostic trainer protocol.

Parity target: ``fedml_core/trainer/model_trainer.py:4-41`` — the reference's
ABC is explicitly the seam where non-torch trainers plug in ("PyTorch,
TensorFlow, Keras, MXNET"; model_trainer.py:5-10). Our Flax/Optax
implementation lives in :mod:`fedml_tpu.trainer.flax_trainer`; algorithms are
written against this protocol so alternative backends (e.g. a pure-numpy
oracle trainer used in tests) slot in.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Optional


class ModelTrainer(abc.ABC):
    """Abstract operator for local training/eval on one client.

    Unlike the reference (whose trainer mutates an internal torch module),
    implementations here are *functional*: model state travels as an explicit
    pytree through ``get/set_model_params`` and the train/test methods, so the
    same trainer object can be vmapped across thousands of simulated clients.
    """

    def __init__(self, model: Any, args: Optional[Any] = None):
        self.model = model
        self.id = 0
        self.args = args

    def set_id(self, trainer_id: int):
        self.id = trainer_id

    @abc.abstractmethod
    def get_model_params(self):
        """Return the current model state pytree (reference: cpu state_dict)."""

    @abc.abstractmethod
    def set_model_params(self, model_parameters):
        """Install a model state pytree."""

    @abc.abstractmethod
    def train(self, train_data, device=None, args=None):
        """Run local training on this client's data."""

    @abc.abstractmethod
    def test(self, test_data, device=None, args=None) -> Dict[str, float]:
        """Evaluate; returns the reference metrics dict
        (test_correct/test_loss/test_total[/test_precision/test_recall])."""

    def test_on_the_server(self, train_data_local_dict, test_data_local_dict,
                           device=None, args=None) -> bool:
        """Optional whole-federation eval hook; False = not implemented
        (reference model_trainer.py:36-41)."""
        return False
