from fedml_tpu.trainer.model_trainer import ModelTrainer
from fedml_tpu.trainer.tasks import TASK_HEADS, classification_head
from fedml_tpu.trainer.functional import (
    TrainConfig,
    make_optimizer,
    make_forward,
    make_local_train,
    make_eval,
)
from fedml_tpu.trainer.flax_trainer import FlaxModelTrainer
