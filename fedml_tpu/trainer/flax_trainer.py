"""Flax/Optax implementation of the ModelTrainer protocol.

The host-facing glue object: algorithms that want the reference's
object-oriented seam (get/set params, train, test — reference
fedml_core/trainer/model_trainer.py) use this class; the compiled inner
programs come from :mod:`fedml_tpu.trainer.functional` and are shared with
the vmapped/SPMD round programs, so the class and the pure paths cannot
drift apart.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.trainer.functional import (TrainConfig, make_eval,
                                          make_local_train)
from fedml_tpu.trainer.model_trainer import ModelTrainer
from fedml_tpu.trainer.tasks import stats_to_metrics

Arrays = Tuple[np.ndarray, np.ndarray]  # (x, y)


class FlaxModelTrainer(ModelTrainer):
    def __init__(self, module, task: str = "classification",
                 cfg: Optional[TrainConfig] = None, seed: int = 0):
        super().__init__(module, cfg)
        self.module = module
        self.task = task
        self.cfg = cfg or TrainConfig()
        if self.cfg.lr_decay_round != 1.0:
            raise NotImplementedError(
                "lr_decay_round is a ROUND-level schedule; the ModelTrainer "
                "operator has no round index — drivers apply it")
        self._rng = jax.random.key(seed)
        self._variables = None
        self._train_fn = jax.jit(make_local_train(module, task, self.cfg))
        self._eval_fn = jax.jit(make_eval(module, task))

    # -- state ------------------------------------------------------------
    def init(self, sample_x: np.ndarray, seed: int = 0):
        init_rng = jax.random.key(seed)
        self._variables = self.module.init(init_rng, jnp.asarray(sample_x),
                                           train=False)
        return self._variables

    def get_model_params(self):
        return self._variables

    def set_model_params(self, model_parameters):
        self._variables = model_parameters

    # -- compute ----------------------------------------------------------
    def train(self, train_data, device=None, args=None):
        """train_data: (x, y) arrays or (x, y, mask); trains in place on the
        currently installed params and returns summed train stats."""
        x, y, mask = _with_mask(train_data)
        bsz = self.cfg.batch_size or x.shape[0]
        if self.cfg.accum_steps > 1:
            # per-call guard: this trainer sees one client's real length
            # only here (validate_accum_steps semantics, one client)
            from fedml_tpu.trainer.functional import validate_accum_steps
            validate_accum_steps(self.cfg, {0: len(x)})
        x, y, mask = _pad_to_multiple(x, y, mask, bsz)
        self._rng, sub = jax.random.split(self._rng)
        self._variables, stats = self._train_fn(
            self._variables, jnp.asarray(x), jnp.asarray(y),
            jnp.asarray(mask), sub)
        return jax.tree.map(float, stats)

    def test(self, test_data, device=None, args=None) -> Dict[str, float]:
        x, y, mask = _with_mask(test_data)
        stats = self._eval_fn(self._variables, jnp.asarray(x), jnp.asarray(y),
                              jnp.asarray(mask))
        return stats_to_metrics(stats)


def _with_mask(data):
    if len(data) == 3:
        return data
    x, y = data
    return x, y, np.ones(len(x), dtype=np.float32)


def _pad_to_multiple(x, y, mask, bsz: int):
    n = len(x)
    n_pad = ((n + bsz - 1) // bsz) * bsz
    pad = n_pad - n
    if pad == 0:
        return x, y, mask
    x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
    y = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)])
    mask = np.concatenate([mask, np.zeros(pad, mask.dtype)])
    return x, y, mask
