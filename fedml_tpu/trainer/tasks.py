"""Task heads: loss + metric kernels for the three federated task families.

The reference hardwires these into per-task trainer subclasses
(fedml_api/standalone/fedavg/my_model_trainer_{classification,nwp,
tag_prediction}.py and the stackoverflow_lr branch in
fedml_api/distributed/fedavg/MyModelTrainer.py:72-83). Here each head is a
pure function ``head(logits, targets, mask) -> stat sums`` so it can run
inside jit/vmap/shard_map; all stats are *sums* (not means) so they aggregate
correctly across batches, clients and mesh shards by plain addition / psum.

Masking convention: every example row carries a 0/1 ``mask`` weight (padding
rows are 0). Sequence heads additionally mask padding tokens inside each
example. The per-batch training loss is ``loss_sum / count`` — identical to
torch's reduction='mean' over the real examples in the batch.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import optax

Stats = Dict[str, jnp.ndarray]
TaskHead = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], Stats]

PAD_TOKEN = 0  # sequence pad id (LEAF/TFF convention: 0-padded batches)


def classification_head(logits: jnp.ndarray, targets: jnp.ndarray,
                        mask: jnp.ndarray) -> Stats:
    """Softmax CE + top-1 accuracy. logits [B, C], integer targets [B]."""
    per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    correct = (jnp.argmax(logits, -1) == targets).astype(jnp.float32)
    return {
        "loss_sum": jnp.sum(per_ex * mask),
        "count": jnp.sum(mask),
        "correct_sum": jnp.sum(correct * mask),
    }


def nwp_head(logits: jnp.ndarray, targets: jnp.ndarray,
             mask: jnp.ndarray) -> Stats:
    """Next-word/char prediction: per-token CE over [B, T, V] logits.

    The accounting unit is the *token* (reference my_model_trainer_nwp
    counts correct tokens and divides by token totals); pad tokens
    (``PAD_TOKEN``) and padded example rows are excluded.
    """
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    tok_mask = (targets != PAD_TOKEN).astype(jnp.float32) * mask[:, None]
    correct = (jnp.argmax(logits, -1) == targets).astype(jnp.float32)
    return {
        "loss_sum": jnp.sum(per_tok * tok_mask),
        "count": jnp.sum(tok_mask),
        "correct_sum": jnp.sum(correct * tok_mask),
    }


def tag_prediction_head(logits: jnp.ndarray, targets: jnp.ndarray,
                        mask: jnp.ndarray) -> Stats:
    """Multi-label tag prediction (stackoverflow_lr): sigmoid BCE.

    Metrics mirror MyModelTrainer.py:72-83: an example is "correct" only when
    every label matches at threshold 0.5; precision/recall are per-example
    ratios summed over examples (averaged by the caller via ``count``).
    """
    per_label = optax.sigmoid_binary_cross_entropy(logits, targets)
    per_ex = jnp.mean(per_label, axis=-1)
    pred = (jax.nn.sigmoid(logits) > 0.5).astype(jnp.float32)
    exact = jnp.all(pred == targets, axis=-1).astype(jnp.float32)
    tp = jnp.sum(pred * targets, axis=-1)
    precision = tp / (jnp.sum(pred, axis=-1) + 1e-13)
    recall = tp / (jnp.sum(targets, axis=-1) + 1e-13)
    return {
        "loss_sum": jnp.sum(per_ex * mask),
        "count": jnp.sum(mask),
        "correct_sum": jnp.sum(exact * mask),
        "precision_sum": jnp.sum(precision * mask),
        "recall_sum": jnp.sum(recall * mask),
    }


# -- segmentation heads (reference fedseg SegmentationLosses, utils.py:71) --

IGNORE_INDEX = 255  # Pascal-VOC convention: pixels excluded from loss/metrics


def _pixel_mask(targets: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Valid-pixel weights: example mask x (target != ignore_index)."""
    valid = (targets != IGNORE_INDEX).astype(jnp.float32)
    return valid * mask.reshape(mask.shape + (1,) * (targets.ndim - 1))


def segmentation_head(logits, targets, mask) -> Stats:
    """Mean per-valid-pixel CE (SegmentationLosses.CrossEntropyLoss)."""
    safe_targets = jnp.where(targets == IGNORE_INDEX, 0, targets)
    per_px = optax.softmax_cross_entropy_with_integer_labels(logits,
                                                             safe_targets)
    pm = _pixel_mask(targets, mask)
    correct = (jnp.argmax(logits, -1) == targets).astype(jnp.float32)
    return {"loss_sum": jnp.sum(per_px * pm), "count": jnp.sum(pm),
            "correct_sum": jnp.sum(correct * pm)}


def segmentation_focal_head(logits, targets, mask, gamma: float = 2.0,
                            alpha: float = 0.5) -> Stats:
    """Focal loss: -alpha * (1-pt)^gamma * log pt per valid pixel
    (SegmentationLosses.FocalLoss, utils.py:95-109)."""
    safe_targets = jnp.where(targets == IGNORE_INDEX, 0, targets)
    logpt = -optax.softmax_cross_entropy_with_integer_labels(logits,
                                                             safe_targets)
    pt = jnp.exp(logpt)
    per_px = -((1.0 - pt) ** gamma) * alpha * logpt
    pm = _pixel_mask(targets, mask)
    correct = (jnp.argmax(logits, -1) == targets).astype(jnp.float32)
    return {"loss_sum": jnp.sum(per_px * pm), "count": jnp.sum(pm),
            "correct_sum": jnp.sum(correct * pm)}


TASK_HEADS: Dict[str, TaskHead] = {
    "classification": classification_head,
    "nwp": nwp_head,
    "tag_prediction": tag_prediction_head,
    "segmentation": segmentation_head,
    "segmentation_focal": segmentation_focal_head,
}


def stats_to_metrics(stats: Stats, prefix: str = "test") -> Dict[str, float]:
    """Convert device stat sums to the reference metrics dict shape
    (MyModelTrainer.test: test_correct/test_loss/test_total...)."""
    out = {
        f"{prefix}_correct": float(stats["correct_sum"]),
        f"{prefix}_loss": float(stats["loss_sum"]),
        f"{prefix}_total": float(stats["count"]),
    }
    if "precision_sum" in stats:
        out[f"{prefix}_precision"] = float(stats["precision_sum"])
        out[f"{prefix}_recall"] = float(stats["recall_sum"])
    return out
