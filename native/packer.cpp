// Parallel host-side cohort packer for fedml_tpu.
//
// Role: the per-round host hot path — gathering P sampled clients' ragged
// sample arrays into the dense, device-ready [P, n_pad, ...] round input
// (fedml_tpu/data/base.py pack_clients). The reference pays this cost as
// torch DataLoader iteration + pickle per message
// (fedml_api/distributed/fedavg/MyModelTrainer.py batch loop); here it is
// one memcpy/memset pass per client, spread across host cores (a thread
// pool over clients). On a single-core host this degenerates to exactly
// the numpy loop's cost; multi-channel hosts get parallel bandwidth.
//
// Layout contract (enforced by the Python wrapper): every client i owns a
// C-contiguous [counts[i], row_bytes] buffer; dst is C-contiguous
// [P, n_pad, row_bytes]; mask (optional) is [P, n_pad] float32.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Returns 0 on success, -1 if any counts[i] > n_pad (nothing written).
int fedml_pack_clients(const uint8_t* const* src_ptrs,
                       const int64_t* counts, int64_t P, int64_t n_pad,
                       int64_t row_bytes, uint8_t* dst, float* mask,
                       int n_threads) {
  for (int64_t i = 0; i < P; ++i) {
    if (counts[i] > n_pad || counts[i] < 0) return -1;
  }
  auto work = [&](int64_t i) {
    const int64_t n = counts[i];
    uint8_t* out = dst + i * n_pad * row_bytes;
    if (n > 0) std::memcpy(out, src_ptrs[i], n * row_bytes);
    std::memset(out + n * row_bytes, 0, (n_pad - n) * row_bytes);
    if (mask != nullptr) {
      float* m = mask + i * n_pad;
      std::fill(m, m + n, 1.0f);
      std::fill(m + n, m + n_pad, 0.0f);
    }
  };
  const int k = static_cast<int>(
      std::max<int64_t>(1, std::min<int64_t>(n_threads, P)));
  if (k == 1) {
    for (int64_t i = 0; i < P; ++i) work(i);
    return 0;
  }
  std::atomic<int64_t> next{0};
  std::vector<std::thread> threads;
  threads.reserve(k);
  for (int t = 0; t < k; ++t) {
    threads.emplace_back([&] {
      for (int64_t i; (i = next.fetch_add(1)) < P;) work(i);
    });
  }
  for (auto& t : threads) t.join();
  return 0;
}

}  // extern "C"
